"""Preemptible background train/eval scheduler — the Arbiter analog
(ISSUE 19 tentpole; ROADMAP items 4 and 5).

Even a well-tuned serving fleet leaves most device time idle —
``bench.py`` measured a 0.57 ``device_idle_fraction`` after PR 18. The
reference stack answered this workload class with **Arbiter**
(random/grid hyperparameter search over builder configs) and ran heavy
training off the serving path via ``SharedTrainingMaster``. This module
is the unified version: background jobs run ON the serving workers, in
the gaps traffic leaves, and yield the moment traffic returns.

Design invariants:

- **Admission is signal-gated.** A job only starts (or keeps running)
  while the live capacity/SLO signals the autoscaler already consumes
  say the worker has slack: per-model busy fractions under
  ``max_busy_fraction``, queue depth zero / headroom above
  ``min_queue_headroom``, fast-window SLO burn under ``max_fast_burn``.
  The same predicate that refuses admission triggers preemption — there
  is exactly one definition of "traffic needs the devices".
- **Preemption is free.** Job runners do bounded work per
  :meth:`JobRun.step` and checkpoint through the same atomics training
  uses (``atomic_save_model`` + the :class:`DistributedTrainer`
  residual/archive checkpoint). Resume is EXACT batch-skip: the batch
  schedule is a pure function of (seed, step index), and the restored
  archive carries updater state, RNG stream position and iteration
  counters — a preempted-then-resumed fine-tune's trajectory bit-matches
  an uninterrupted run (tested).
- **Exactly-once claims.** Job state lives in the shared
  :class:`~deeplearning4j_tpu.serving.control_plane.FleetConfig`; a
  scheduler may only run a job after winning
  ``try_claim("scheduler.job:<id>")`` on the PR 12 applied-actions
  ledger, so two schedulers racing the same job can never double-run it.
  The claim attempt is a chaos point (``serving.scheduler.claim``).
- **Every transition is a journal event.** submitted / claimed /
  started / preempted / resumed / completed / failed / cancelled each
  emit a typed ``runtime/journal.py`` event, so one ``/v1/debug/bundle``
  pull reconstructs a job's whole life with gapless seqs.
- **Harvest is measured, not assumed.** The scheduler accumulates the
  wall seconds its job steps actually ran (``harvested_busy_s``) and
  registers itself with :mod:`serving.capacity`, which folds the number
  into the ``device_idle_fraction`` headline — ``bench.py --scheduler``
  asserts the headline drop is real and that serving stayed bit-exact.

Job types: ``finetune`` (:class:`DistributedTrainer` steps over a fixed
npz dataset), ``eval`` (golden-set accuracy through the REAL registry
batcher path), ``score`` (offline batch scoring to an npz), ``sweep``
(Arbiter-style random/grid search over builder-config space, trial
granular preemption), and ``flywheel`` (ROADMAP item 5's learning half:
``DL4J_TPU_FEEDBACK_FILE`` labeled examples through a
:class:`DevicePrefetcher` feed into a transfer-learning +
early-stopping fine-tune whose candidate archive re-enters
``rolling_deploy(strategy="gated")`` via the injected ``deploy_fn``).
"""

from __future__ import annotations

import itertools
import logging
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.runtime import chaos, journal

__all__ = ["JobStore", "Scheduler", "SchedulerConfig", "JobRun",
           "JOB_RUNNERS", "JOB_STATES", "CLAIM_POINT",
           "capacity_signals", "render_prometheus", "build_net_from_spec"]

logger = logging.getLogger(__name__)

#: the exactly-once claim's chaos point: fired before every ledger
#: claim attempt, so a drill can kill/hang/fail a scheduler mid-claim
#: and assert the job still runs at most once
CLAIM_POINT = "serving.scheduler.claim"

#: every lifecycle state a job record can hold (journal event
#: ``scheduler.<verb>`` mirrors each transition)
JOB_STATES = ("submitted", "claimed", "started", "preempted", "resumed",
              "completed", "failed", "cancelled")

# ============================================================ job store
class JobStore:
    """Job records in the shared :class:`FleetConfig` (``cfg["jobs"]``),
    with exactly-once run rights through the applied-actions ledger.

    The store is a thin veneer: every mutation goes through
    ``FleetConfig.mutate`` (cross-process flock + atomic replace), every
    read through ``snapshot()``, and the claim is ``try_claim`` on the
    same ledger rolling deploys use — no state of its own, so N
    schedulers and M submitters can share one store safely."""

    def __init__(self, config):
        self.config = config

    # ---- submit / read -------------------------------------------------
    def submit(self, jtype: str, payload: Dict[str, Any],
               job_id: Optional[str] = None, priority: int = 0) -> str:
        """Register a job (state ``submitted``); returns its id."""
        if job_id is None:
            job_id = f"{jtype}-{random.getrandbits(48):012x}"
        rec = {"id": job_id, "type": str(jtype), "payload": payload,
               "priority": int(priority), "state": "submitted",
               "owner": None, "submitted_at": time.time(),
               "progress": {}, "result": None, "error": None}
        def fn(cfg):
            cfg.setdefault("jobs", {})[job_id] = rec
        self.config.mutate(fn)
        journal.emit("scheduler.submit", job=job_id, type=jtype,
                     priority=int(priority))
        return job_id

    def jobs(self) -> Dict[str, Dict[str, Any]]:
        return dict((self.config.snapshot() or {}).get("jobs", {}))

    def get(self, job_id: str) -> Optional[Dict[str, Any]]:
        return self.jobs().get(job_id)

    # ---- claim / transitions -------------------------------------------
    def claim(self, job_id: str, owner: str) -> bool:
        """Try to win the job's exactly-once run right. Exactly one
        caller across every process sharing this config can ever win;
        the loser's attempt is still a journal event (``won=False``) so
        a claim race is visible in the bundle."""
        chaos.inject(CLAIM_POINT)
        won = self.config.try_claim(f"scheduler.job:{job_id}",
                                    {"owner": owner})
        journal.emit("scheduler.claim", job=job_id, owner=owner, won=won)
        if won:
            self.update(job_id, state="claimed", owner=owner)
        return won

    def update(self, job_id: str, **fields) -> Optional[Dict[str, Any]]:
        """Merge ``fields`` into the job record; a ``state`` change
        emits its journal event. Returns the updated record (or ``None``
        for an unknown id — an updater must tolerate a cancel race)."""
        out: Dict[str, Any] = {}
        def fn(cfg):
            rec = cfg.get("jobs", {}).get(job_id)
            if rec is None:
                return
            rec.update(fields)
            out.update(rec)
        self.config.mutate(fn)
        if not out:
            return None
        state = fields.get("state")
        if state and state != "claimed":  # claim emits its own event
            # one literal emit per transition (the journal linter's
            # emit-site <-> registry parity needs the spelling visible)
            attrs = dict(job=job_id, state=state,
                         owner=out.get("owner"), type=out.get("type"))
            if state == "started":
                journal.emit("scheduler.start", **attrs)
            elif state == "preempted":
                journal.emit("scheduler.preempt", **attrs)
            elif state == "resumed":
                journal.emit("scheduler.resume", **attrs)
            elif state == "completed":
                journal.emit("scheduler.complete", **attrs)
            elif state == "cancelled":
                journal.emit("scheduler.cancel", **attrs)
            else:
                journal.emit("scheduler.fail", **attrs)
        return out

    def cancel(self, job_id: str) -> bool:
        """Cancel a job that is not yet terminal. A RUNNING job is
        cancelled cooperatively by its scheduler at the next step
        boundary (the record flips first; the runner observes it)."""
        rec = self.get(job_id)
        if rec is None or rec["state"] in ("completed", "failed",
                                           "cancelled"):
            return False
        return self.update(job_id, state="cancelled") is not None


# ==================================================== admission signals
def capacity_signals(registry, slo=None) -> Callable[[], Dict[str, Any]]:
    """Build the scheduler's admission-signal callable from the live
    serving objects — the SAME numbers the autoscaler consumes: per-model
    busy fractions and queue depth/headroom from the capacity ledger,
    fast-window SLO burn from the monitor. Returns worst-case (max busy,
    max burn, min headroom) so one hot model blocks harvest."""
    def signals() -> Dict[str, Any]:
        from deeplearning4j_tpu.serving import capacity as cap
        busy = 0.0
        depth = 0
        headroom: Optional[int] = None
        for name in registry.names():
            try:
                c = cap.model_capacity(registry.get(name))
            except Exception:
                continue  # cold or mid-swap: not a traffic signal
            busy = max(busy, c["utilization"]["busy_fraction"])
            depth += c["queue"]["depth"]
            h = c["queue"]["headroom_requests"]
            headroom = h if headroom is None else min(headroom, h)
        burn = 0.0
        if slo is not None:
            try:
                rep = slo.report()
                for m in rep.values():
                    windows = (m or {}).get("windows") or {}
                    if not windows:
                        continue
                    fast = windows[min(windows,
                                       key=lambda w: float(w))]
                    burn = max(burn, float(
                        fast.get("availability_burn_rate", 0.0)), float(
                        fast.get("latency_burn_rate", 0.0)))
            except Exception:
                pass  # a broken monitor must not wedge admission
        return {"busy_fraction": round(busy, 6), "queue_depth": depth,
                "queue_headroom": headroom, "fast_burn": round(burn, 6)}
    return signals


class SchedulerConfig:
    """Admission/preemption knobs (one predicate serves both)."""

    def __init__(self, tick_s: float = 0.05,
                 max_busy_fraction: float = 0.5,
                 max_queue_depth: int = 0,
                 min_queue_headroom: int = 1,
                 max_fast_burn: float = 1.0,
                 preempt_join_s: float = 30.0,
                 duty_fraction: float = 1.0,
                 job_nice: Optional[int] = None):
        self.tick_s = float(tick_s)
        self.max_busy_fraction = float(max_busy_fraction)
        self.max_queue_depth = int(max_queue_depth)
        self.min_queue_headroom = int(min_queue_headroom)
        self.max_fast_burn = float(max_fast_burn)
        self.preempt_join_s = float(preempt_join_s)
        # interference controls for core-sharing hosts: pace the job
        # thread to at most `duty_fraction` of wall time (an admission
        # tick is too coarse to protect millisecond tails; the pause
        # between steps is what keeps foreground p99 flat), and renice
        # it (Linux best-effort) so the kernel deschedules harvest the
        # moment a request thread becomes runnable
        self.duty_fraction = min(1.0, max(0.01, float(duty_fraction)))
        self.job_nice = None if job_nice is None else int(job_nice)

    def to_dict(self) -> Dict[str, Any]:
        return {"tick_s": self.tick_s,
                "max_busy_fraction": self.max_busy_fraction,
                "max_queue_depth": self.max_queue_depth,
                "min_queue_headroom": self.min_queue_headroom,
                "max_fast_burn": self.max_fast_burn,
                "duty_fraction": self.duty_fraction,
                "job_nice": self.job_nice}


# ============================================================== runners
class JobRun:
    """One job's in-memory execution. The contract that makes preemption
    instant and resume exact:

    - :meth:`step` does one BOUNDED unit (one global batch, one sweep
      trial, one eval chunk, one epoch) and returns True when done;
    - :meth:`checkpoint` persists everything a bit-exact continuation
      needs through atomic writes, returning the JSON progress dict the
      job record carries;
    - construction with a non-empty ``progress`` RESUMES: restore from
      the checkpoint, then skip exactly the completed units — never
      replay one.
    """

    def __init__(self, job: Dict[str, Any], ctx: "JobContext"):
        self.job = job
        self.payload = dict(job.get("payload") or {})
        self.progress = dict(job.get("progress") or {})
        self.ctx = ctx

    def step(self) -> bool:
        raise NotImplementedError

    def checkpoint(self) -> Dict[str, Any]:
        return dict(self.progress)

    def result(self) -> Dict[str, Any]:
        return {}


class JobContext:
    """What a scheduler hands its runners: the live registry (eval jobs
    go through the REAL batcher path), the injected gated-deploy hook,
    and the owning scheduler (cancel checks)."""

    def __init__(self, registry=None, deploy_fn=None, scheduler=None):
        self.registry = registry
        self.deploy_fn = deploy_fn
        self.scheduler = scheduler


def _one_hot(labels, n_out: int) -> np.ndarray:
    y = np.zeros((len(labels), n_out), np.float32)
    y[np.arange(len(labels)), np.asarray(labels, np.int64)] = 1.0
    return y


def _atomic_savez(path: str, **arrays) -> None:
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)


def build_net_from_spec(spec: Dict[str, Any]):
    """A builder config from a JSON spec — the sweep's search space is
    over THESE knobs (the Arbiter analog: hyperparameters as data, so a
    trial's config travels through the job store). Keys: ``nin``,
    ``nout`` (required), ``hidden`` (list of widths), ``activation``,
    ``seed``, ``lr`` + ``updater`` ("sgd"/"adam"/None)."""
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train import Adam, Sgd
    updater = None
    name = spec.get("updater")
    lr = float(spec.get("lr", 0.1))
    if name == "adam":
        updater = Adam(lr)
    elif name == "sgd":
        updater = Sgd(lr)
    b = (NeuralNetConfiguration.builder()
         .seed(int(spec.get("seed", 7))).updater(updater).list())
    for width in (spec.get("hidden") or [16]):
        b = b.layer(DenseLayer(n_out=int(width),
                               activation=spec.get("activation", "tanh")))
    b = b.layer(OutputLayer(n_out=int(spec["nout"]),
                            activation="softmax"))
    conf = b.set_input_type(
        InputType.feed_forward(int(spec["nin"]))).build()
    return MultiLayerNetwork(conf).init()


class FineTuneRun(JobRun):
    """``finetune``: :class:`DistributedTrainer` steps over a fixed npz
    dataset with a deterministic (seed, step)->batch schedule. The
    checkpoint is the trainer's own group-consistent one (residuals
    first, then the atomic model archive), so resume restores updater
    state, codec residuals, RNG position and iteration counter — the
    continuation bit-matches the uninterrupted trajectory."""

    def __init__(self, job, ctx):
        super().__init__(job, ctx)
        from deeplearning4j_tpu.models import MultiLayerNetwork
        from deeplearning4j_tpu.train.distributed import (
            DistributedConfig, DistributedTrainer)
        p = self.payload
        data = np.load(p["data"])
        self.x = np.asarray(data["x"], np.float32)
        self.y = np.asarray(data["y"], np.float32)
        self.batch_size = int(p.get("batch_size", 8))
        self.total_steps = int(p.get("steps", 10))
        seed = int(p.get("seed", 0))
        self._perm = np.random.default_rng(seed).permutation(len(self.x))
        ckpt_dir = p.get("checkpoint_dir") or (
            f"{p['archive']}.job-{job['id']}.ckpt")
        os.makedirs(ckpt_dir, exist_ok=True)
        net = MultiLayerNetwork.load(p["archive"], load_updater=True)
        self.trainer = DistributedTrainer(
            net, DistributedConfig(threshold=float(p.get("threshold", 0.0)),
                                   checkpoint_dir=ckpt_dir),
            world=int(p.get("world", 1)), rank=None)
        self.steps_done = int(self.progress.get("steps_done", 0))
        self.losses: List[float] = list(self.progress.get("losses", []))
        if self.steps_done:
            if not self.trainer.restore():
                raise RuntimeError(
                    f"job {job['id']}: {self.steps_done} steps recorded "
                    f"but no checkpoint in {ckpt_dir} — cannot resume")

    def _batch(self, i: int):
        n = len(self.x)
        idx = [self._perm[(i * self.batch_size + j) % n]
               for j in range(self.batch_size)]
        return self.x[idx], self.y[idx]

    def step(self) -> bool:
        x, y = self._batch(self.steps_done)
        self.losses.append(float(self.trainer.step(x, y)))
        self.steps_done += 1
        return self.steps_done >= self.total_steps

    def checkpoint(self) -> Dict[str, Any]:
        self.trainer._checkpoint(int(self.trainer.net._iteration))
        self.progress = {"steps_done": self.steps_done,
                         "losses": self.losses}
        return dict(self.progress)

    def result(self) -> Dict[str, Any]:
        out = self.payload.get("out")
        if out:
            from deeplearning4j_tpu.train.checkpoint import atomic_save_model
            atomic_save_model(self.trainer.net, out)
        return {"steps": self.steps_done, "losses": self.losses,
                "final_loss": self.losses[-1] if self.losses else None,
                "out": out}


class EvalRun(JobRun):
    """``eval``: a golden set (or npz dataset) through the registry's
    REAL batcher path — the accuracy serving would deliver, not a
    flattering direct ``net.output``. One chunk per step."""

    def __init__(self, job, ctx):
        super().__init__(job, ctx)
        p = self.payload
        if ctx.registry is None:
            raise RuntimeError("eval job needs a live registry")
        self.model = p["model"]
        if p.get("golden"):
            from deeplearning4j_tpu.serving.delivery import GoldenSet
            gs = GoldenSet.load(p["golden"])
            self.x, self.labels = gs.inputs, gs.labels
        else:
            data = np.load(p["data"])
            self.x = np.asarray(data["x"], np.float32)
            self.labels = (np.asarray(data["labels"])
                           if "labels" in data else None)
        self.chunk = int(p.get("batch_size", 16))
        self.done_rows = int(self.progress.get("done_rows", 0))
        self.correct = int(self.progress.get("correct", 0))

    def step(self) -> bool:
        lo = self.done_rows
        hi = min(lo + self.chunk, len(self.x))
        probs = np.asarray(self.ctx.registry.predict(
            self.model, self.x[lo:hi]))
        if self.labels is not None:
            self.correct += int(
                (probs.argmax(-1) == np.asarray(
                    self.labels[lo:hi])).sum())
        self.done_rows = hi
        return self.done_rows >= len(self.x)

    def checkpoint(self) -> Dict[str, Any]:
        self.progress = {"done_rows": self.done_rows,
                         "correct": self.correct}
        return dict(self.progress)

    def result(self) -> Dict[str, Any]:
        out = {"model": self.model, "examples": self.done_rows}
        if self.labels is not None and self.done_rows:
            out["accuracy"] = round(self.correct / self.done_rows, 6)
        return out


class ScoreRun(JobRun):
    """``score``: offline batch scoring — an archive's outputs over an
    npz dataset, written (atomically) to an output npz."""

    def __init__(self, job, ctx):
        super().__init__(job, ctx)
        from deeplearning4j_tpu.models import MultiLayerNetwork
        p = self.payload
        data = np.load(p["data"])
        self.x = np.asarray(data["x"], np.float32)
        self.chunk = int(p.get("batch_size", 16))
        self.net = MultiLayerNetwork.load(p["archive"])
        self.done_rows = int(self.progress.get("done_rows", 0))
        self.outputs: List[np.ndarray] = []
        if self.done_rows:
            # deterministic recompute of the finished prefix: outputs are
            # pure functions of (frozen archive, rows), so a resume can
            # rebuild them instead of spilling partial results
            for lo in range(0, self.done_rows, self.chunk):
                hi = min(lo + self.chunk, self.done_rows)
                self.outputs.append(np.asarray(self.net.output(
                    self.x[lo:hi])))

    def step(self) -> bool:
        lo = self.done_rows
        hi = min(lo + self.chunk, len(self.x))
        self.outputs.append(np.asarray(self.net.output(self.x[lo:hi])))
        self.done_rows = hi
        return self.done_rows >= len(self.x)

    def checkpoint(self) -> Dict[str, Any]:
        self.progress = {"done_rows": self.done_rows}
        return dict(self.progress)

    def result(self) -> Dict[str, Any]:
        outputs = (np.concatenate(self.outputs, axis=0)
                   if self.outputs else np.zeros((0,), np.float32))
        out = self.payload.get("out")
        if out:
            _atomic_savez(out, outputs=outputs)
        return {"examples": self.done_rows, "out": out}


class SweepRun(JobRun):
    """``sweep``: the Arbiter analog — random or grid search over
    builder-config space (:func:`build_net_from_spec` knobs). One TRIAL
    per step, so preemption lands on trial boundaries and resume re-runs
    nothing: the trial sequence is a pure function of (space, mode,
    seed), and each trial's own training is seeded by its spec."""

    def __init__(self, job, ctx):
        super().__init__(job, ctx)
        p = self.payload
        data = np.load(p["data"])
        self.x = np.asarray(data["x"], np.float32)
        self.y = np.asarray(data["y"], np.float32)
        self.base = dict(p.get("base") or {})
        self.base.setdefault("nin", self.x.shape[-1])
        self.base.setdefault("nout", self.y.shape[-1])
        self.steps = int(p.get("steps", 10))
        self.batch_size = int(p.get("batch_size", min(8, len(self.x))))
        self.trial_params = self._trial_sequence(
            dict(p.get("space") or {}), p.get("mode", "grid"),
            int(p.get("trials", 8)), int(p.get("seed", 0)))
        self.trials_done = int(self.progress.get("trials_done", 0))
        self.results: List[Dict[str, Any]] = list(
            self.progress.get("results", []))

    @staticmethod
    def _trial_sequence(space: Dict[str, List[Any]], mode: str,
                        trials: int, seed: int) -> List[Dict[str, Any]]:
        keys = sorted(space)
        if mode == "grid":
            return [dict(zip(keys, combo)) for combo in
                    itertools.product(*(space[k] for k in keys))]
        rng = random.Random(seed)
        return [{k: rng.choice(space[k]) for k in keys}
                for _ in range(trials)]

    def step(self) -> bool:
        spec = {**self.base, **self.trial_params[self.trials_done]}
        net = build_net_from_spec(spec)
        n = len(self.x)
        for i in range(self.steps):
            lo = (i * self.batch_size) % n
            idx = [(lo + j) % n for j in range(self.batch_size)]
            net.fit(self.x[idx], self.y[idx])
        from deeplearning4j_tpu.data.dataset import DataSet
        score = float(net.score(DataSet(self.x, self.y)))
        self.results.append({"params": spec, "score": round(score, 9)})
        self.trials_done += 1
        return self.trials_done >= len(self.trial_params)

    def checkpoint(self) -> Dict[str, Any]:
        self.progress = {"trials_done": self.trials_done,
                         "results": self.results}
        return dict(self.progress)

    def result(self) -> Dict[str, Any]:
        best = (min(self.results, key=lambda r: r["score"])
                if self.results else None)
        return {"trials": self.trials_done, "results": self.results,
                "best": best}


class FlywheelRun(JobRun):
    """``flywheel`` (ROADMAP item 5's learning half): labeled examples
    from the feedback file (live + keep-1 rollover) become a
    transfer-learning fine-tune — base archive grafted through
    ``TransferLearning``, fed through the :class:`DevicePrefetcher`
    training feed (``prefetch_buffer``), early-stopped on held-in loss —
    and the candidate archive (golden sidecar carried over) re-enters
    gated delivery through the injected ``deploy_fn``. One EPOCH per
    step; preemption checkpoints the net archive atomically."""

    def __init__(self, job, ctx):
        super().__init__(job, ctx)
        from deeplearning4j_tpu.models import (FineTuneConfiguration,
                                               MultiLayerNetwork,
                                               TransferLearning)
        from deeplearning4j_tpu.serving.delivery import (
            iter_feedback_examples)
        from deeplearning4j_tpu.train import Sgd
        p = self.payload
        path = p.get("feedback_file") or os.environ.get(
            "DL4J_TPU_FEEDBACK_FILE")
        if not path:
            raise RuntimeError("flywheel job needs a feedback file "
                               "(payload or DL4J_TPU_FEEDBACK_FILE)")
        model_filter = p.get("model")
        rows = [r for r in iter_feedback_examples(path)
                if r.get("inputs") is not None
                and r.get("label") is not None
                and (model_filter is None
                     or r.get("model") == model_filter)]
        self.n_examples = len(rows)
        self.min_examples = int(p.get("min_examples", 4))
        self.base_archive = p["base_archive"]
        self.out_archive = p.get("out_archive",
                                 f"{self.base_archive}.flywheel.zip")
        self.ckpt = f"{self.out_archive}.job-{job['id']}.ckpt.zip"
        self.max_epochs = int(p.get("max_epochs", 20))
        self.patience = int(p.get("patience", 3))
        self.prefetch_buffer = int(p.get("prefetch_buffer", 2))
        self.batch_size = int(p.get("batch_size", 8))
        self.epochs_done = int(self.progress.get("epochs_done", 0))
        self.best_score = self.progress.get("best_score")
        self.bad_epochs = int(self.progress.get("bad_epochs", 0))
        self._stopped = bool(self.progress.get("stopped", False))
        if self.n_examples < self.min_examples:
            self.net = None
            return
        if self.epochs_done and os.path.exists(self.ckpt):
            self.net = MultiLayerNetwork.load(self.ckpt,
                                              load_updater=True)
        else:
            base = MultiLayerNetwork.load(self.base_archive)
            b = TransferLearning.builder(base).fine_tune_configuration(
                FineTuneConfiguration(updater=Sgd(float(p.get("lr", 0.05)))))
            if p.get("freeze_up_to") is not None:
                b = b.set_feature_extractor(int(p["freeze_up_to"]))
            self.net = b.build()
        nout = int(self.net.conf.layers[-1].n_out)
        self.x = np.asarray([r["inputs"] for r in rows], np.float32)
        self.y = _one_hot([int(r["label"]) for r in rows], nout)

    def _iterator(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator
        sets = [DataSet(self.x[lo:lo + self.batch_size],
                        self.y[lo:lo + self.batch_size])
                for lo in range(0, len(self.x), self.batch_size)]
        return ListDataSetIterator(sets, batch_size=self.batch_size)

    def step(self) -> bool:
        from deeplearning4j_tpu.data.dataset import DataSet
        if self.net is None or self._stopped:
            return True
        # the flywheel's feed goes through the DevicePrefetcher path —
        # same staged-on-device pipeline full training uses
        self.net.fit(self._iterator(), epochs=1,
                     prefetch_buffer=self.prefetch_buffer)
        score = float(self.net.score(DataSet(self.x, self.y)))
        self.epochs_done += 1
        if self.best_score is None or score < self.best_score - 1e-12:
            self.best_score = score
            self.bad_epochs = 0
        else:
            self.bad_epochs += 1
        if (self.epochs_done >= self.max_epochs
                or self.bad_epochs >= self.patience):
            self._stopped = True
        return self._stopped

    def checkpoint(self) -> Dict[str, Any]:
        if self.net is not None:
            from deeplearning4j_tpu.train.checkpoint import atomic_save_model
            atomic_save_model(self.net, self.ckpt)
        self.progress = {"epochs_done": self.epochs_done,
                         "best_score": self.best_score,
                         "bad_epochs": self.bad_epochs,
                         "stopped": self._stopped}
        return dict(self.progress)

    def result(self) -> Dict[str, Any]:
        if self.net is None:
            return {"status": "insufficient_data",
                    "examples": self.n_examples,
                    "min_examples": self.min_examples}
        from deeplearning4j_tpu.serving.delivery import GoldenSet
        from deeplearning4j_tpu.train.checkpoint import atomic_save_model
        atomic_save_model(self.net, self.out_archive)
        # the candidate inherits its deploy bar: the base archive's
        # golden sidecar rides along so the gated pipeline can gate it
        golden = GoldenSet.for_archive(self.base_archive)
        if golden is not None:
            golden.save(GoldenSet.sidecar(self.out_archive))
        out = {"status": "trained", "examples": self.n_examples,
               "epochs": self.epochs_done,
               "best_score": self.best_score,
               "archive": self.out_archive, "deployed": False}
        if self.ctx.deploy_fn is not None:
            report = self.ctx.deploy_fn(self.out_archive, self.payload)
            out["deployed"] = True
            out["deploy"] = report
        return out


#: runner registry (type -> JobRun subclass); extendable per Scheduler
JOB_RUNNERS: Dict[str, type] = {
    "finetune": FineTuneRun,
    "eval": EvalRun,
    "score": ScoreRun,
    "sweep": SweepRun,
    "flywheel": FlywheelRun,
}


# ============================================================ scheduler
class Scheduler:
    """One worker's harvest loop: a ``fleet-scheduler`` control thread
    ticking every ``tick_s``, admitting at most one background job when
    the signals show slack and preempting it within one tick when they
    stop. Callable tick-by-tick without the thread (tests drive
    :meth:`tick` directly under a fake signal)."""

    def __init__(self, store: JobStore, signals=None,
                 worker_id: str = "worker", registry=None,
                 config: Optional[SchedulerConfig] = None,
                 deploy_fn=None, runners: Optional[Dict[str, type]] = None):
        self.store = store
        self.worker_id = worker_id
        self.config = config or SchedulerConfig()
        if signals is None and registry is not None:
            signals = capacity_signals(registry)
        self._signals = signals or (lambda: {})
        self._runners = dict(JOB_RUNNERS)
        if runners:
            self._runners.update(runners)
        self.ctx = JobContext(registry=registry, deploy_fn=deploy_fn,
                              scheduler=self)
        self._lock = threading.Lock()  # guards: (_active, _job_thread,
        #   _harvested_busy_s, counters) against tick/job/scrape threads
        self._stop = threading.Event()
        self._preempt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._job_thread: Optional[threading.Thread] = None
        self._active: Optional[Dict[str, Any]] = None
        self._harvested_busy_s = 0.0
        self._counters = {"completed_total": 0, "failed_total": 0,
                          "preemptions_total": 0, "resumes_total": 0,
                          "claims_won_total": 0, "claims_lost_total": 0,
                          "admission_blocked_total": 0,
                          "cancelled_total": 0}
        self._last_preempt: Optional[Dict[str, float]] = None

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> "Scheduler":
        from deeplearning4j_tpu.serving import capacity
        capacity.attach_harvest(self.harvest_snapshot)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"fleet-scheduler-{self.worker_id}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the control loop; a running job is preempted (and
        checkpointed) first, so nothing is lost and a later scheduler
        resumes it exactly."""
        from deeplearning4j_tpu.serving import capacity
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.config.preempt_join_s + 5.0)
            self._thread = None
        self._preempt_active("shutdown")
        capacity.detach_harvest()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                logger.exception("scheduler tick failed")
            self._stop.wait(self.config.tick_s)

    # ---- admission / preemption ---------------------------------------
    def _has_slack(self, sig: Dict[str, Any]) -> bool:
        cfg = self.config
        if float(sig.get("busy_fraction", 0.0)) > cfg.max_busy_fraction:
            return False
        if int(sig.get("queue_depth", 0)) > cfg.max_queue_depth:
            return False
        headroom = sig.get("queue_headroom")
        if headroom is not None and int(headroom) < cfg.min_queue_headroom:
            return False
        if float(sig.get("fast_burn", 0.0)) > cfg.max_fast_burn:
            return False
        return True

    def tick(self) -> Optional[str]:
        """One control decision. Returns what it did (for tests):
        ``"preempted"``, ``"started"``, ``"resumed"``, ``"blocked"``,
        ``"running"`` or ``None`` (idle, nothing to do)."""
        try:
            sig = self._signals() or {}
        except Exception:
            sig = {}  # a broken signal source reads as "no slack info"
        slack = self._has_slack(sig)
        with self._lock:
            active = self._active
            job_thread = self._job_thread
        if active is not None:
            if job_thread is not None and not job_thread.is_alive():
                with self._lock:  # job finished on its own
                    self._job_thread = None
                    self._active = None
                return None
            if not slack:
                t0 = time.monotonic()
                self._preempt_active("traffic")
                self._last_preempt = {
                    "signals": sig,
                    "join_s": round(time.monotonic() - t0, 6)}
                return "preempted"
            return "running"
        if not slack:
            with self._lock:
                self._counters["admission_blocked_total"] += 1
            return "blocked"
        return self._admit()

    def _admit(self) -> Optional[str]:
        jobs = self.store.jobs()
        # own preempted work resumes before new work starts: finishing
        # a half-done fine-tune beats fanning out
        mine = sorted((j for j in jobs.values()
                       if j["state"] == "preempted"
                       and j.get("owner") == self.worker_id),
                      key=lambda j: (-j["priority"], j["id"]))
        for job in mine:
            rec = self.store.update(job["id"], state="resumed")
            if rec is not None:
                with self._lock:
                    self._counters["resumes_total"] += 1
                self._launch(rec)
                return "resumed"
        pending = sorted((j for j in jobs.values()
                          if j["state"] == "submitted"),
                         key=lambda j: (-j["priority"], j["id"]))
        for job in pending:
            won = self.store.claim(job["id"], self.worker_id)
            with self._lock:
                self._counters["claims_won_total" if won
                               else "claims_lost_total"] += 1
            if won:
                rec = self.store.update(job["id"], state="started")
                if rec is None:
                    continue  # cancelled between claim and start
                self._launch(rec)
                return "started"
        return None

    def _launch(self, job: Dict[str, Any]) -> None:
        self._preempt.clear()
        t = threading.Thread(
            target=self._run_job, args=(job,),
            name=f"fleet-scheduler-job-{job['id']}", daemon=True)
        with self._lock:
            self._active = job
            self._job_thread = t
        t.start()

    def _preempt_active(self, cause: str) -> None:
        with self._lock:
            t = self._job_thread
            active = self._active
        if t is None or active is None:
            return
        self._preempt.set()
        t.join(timeout=self.config.preempt_join_s)
        with self._lock:
            self._job_thread = None
            self._active = None
            self._counters["preemptions_total"] += 1

    # ---- the job thread ------------------------------------------------
    def _run_job(self, job: Dict[str, Any]) -> None:
        job_id = job["id"]
        if self.config.job_nice is not None:
            try:
                os.setpriority(os.PRIO_PROCESS,
                               threading.get_native_id(),
                               self.config.job_nice)
            except (AttributeError, OSError):
                pass  # not Linux / not permitted: pacing still applies
        try:
            runner = self._runners[job["type"]](job, self.ctx)
        except Exception as e:
            logger.exception("job %s failed to build", job_id)
            self.store.update(job_id, state="failed", error=str(e))
            with self._lock:
                self._counters["failed_total"] += 1
            return
        while True:
            if self._preempt.is_set():
                try:
                    progress = runner.checkpoint()
                except Exception as e:
                    self.store.update(job_id, state="failed",
                                      error=f"checkpoint failed: {e}")
                    with self._lock:
                        self._counters["failed_total"] += 1
                    return
                self.store.update(job_id, state="preempted",
                                  progress=progress)
                return
            rec = self.store.get(job_id)
            if rec is not None and rec["state"] == "cancelled":
                with self._lock:
                    self._counters["cancelled_total"] += 1
                return  # cancel already journaled by the store
            t0 = time.perf_counter()
            try:
                done = runner.step()
            except Exception as e:
                logger.exception("job %s step failed", job_id)
                self.store.update(job_id, state="failed", error=str(e))
                with self._lock:
                    self._counters["failed_total"] += 1
                return
            finally:
                dt = time.perf_counter() - t0
                with self._lock:
                    self._harvested_busy_s += dt
            if done:
                try:
                    result = runner.result()
                except Exception as e:
                    logger.exception("job %s finalize failed", job_id)
                    self.store.update(job_id, state="failed",
                                      error=str(e))
                    with self._lock:
                        self._counters["failed_total"] += 1
                    return
                self.store.update(job_id, state="completed",
                                  progress=runner.progress,
                                  result=result)
                with self._lock:
                    self._counters["completed_total"] += 1
                return
            duty = self.config.duty_fraction
            if duty < 1.0:
                # hold the measured duty cycle: a step that took dt is
                # followed by dt*(1-d)/d of yield, so harvest never
                # claims more than `duty` of wall time from the cores
                # serving shares. Waiting on the preempt flag keeps
                # preemption within one control tick even mid-pause.
                self._preempt.wait(min(1.0, dt * (1.0 - duty) / duty))

    # ---- observability -------------------------------------------------
    def harvest_snapshot(self) -> Dict[str, Any]:
        """What :mod:`serving.capacity` folds into ``/v1/capacity``: the
        measured harvested busy seconds plus the job/claim counters and
        the active job (one glance says what the idle time bought)."""
        with self._lock:
            running = (self._job_thread is not None
                       and self._job_thread.is_alive())
            snap: Dict[str, Any] = {
                "worker": self.worker_id,
                "harvested_busy_s": round(self._harvested_busy_s, 6),
                "active_job": (self._active or {}).get("id")
                if running else None,
                **dict(self._counters),
            }
        if self._last_preempt is not None:
            snap["last_preempt_join_s"] = self._last_preempt["join_s"]
        snap["config"] = self.config.to_dict()
        states: Dict[str, int] = {}
        try:
            for j in self.store.jobs().values():
                states[j["state"]] = states.get(j["state"], 0) + 1
        except Exception:
            pass  # a torn store read must not break a scrape
        snap["jobs"] = states
        return snap

    def reset_harvest(self) -> None:
        """Zero the harvested-seconds counter (aligns the harvest window
        with a serving metrics ``reset_window`` for A/B measurement)."""
        with self._lock:
            self._harvested_busy_s = 0.0


def render_prometheus(snap: Dict[str, Any]) -> str:
    """``scheduler_*`` gauges from a :meth:`Scheduler.harvest_snapshot`
    (the worker ``/metrics`` section when a scheduler is attached)."""
    lines = ["# TYPE scheduler_harvested_busy_s gauge",
             f"scheduler_harvested_busy_s {snap['harvested_busy_s']}",
             f"scheduler_active {int(snap.get('active_job') is not None)}"]
    for c in ("completed_total", "failed_total", "preemptions_total",
              "resumes_total", "claims_won_total", "claims_lost_total",
              "admission_blocked_total", "cancelled_total"):
        if c in snap:
            lines.append(f"scheduler_{c} {snap[c]}")
    for state, n in sorted((snap.get("jobs") or {}).items()):
        lines.append(f'scheduler_jobs{{state="{state}"}} {n}')
    return "\n".join(lines) + "\n"
