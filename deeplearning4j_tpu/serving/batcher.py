"""Shape-bucketed continuous batcher with a pipelined executor.

The seed's ``ParallelInference`` coalesced concurrent requests into whatever
total row count happened to arrive — so every distinct coalesced size was a
fresh XLA compilation, and a long-running server would keep compiling for as
long as traffic kept producing new sizes. Here coalesced batches are padded
up to a fixed set of power-of-two row buckets that are AOT-warmed at model
load, so the number of compilations is bounded by ``buckets x replicas``,
not by traffic. Padding rows are dead weight (row-wise inference ops never
couple rows at inference time — BN uses running stats).

PR-1's executor was a single synchronous loop: coalesce -> host pad ->
forward -> **blocking readback** -> scatter, then back to coalescing. The
device idled during every host stage and the host idled during
execute+readback. This version splits it into stages that overlap:

1. **Coalescer/dispatcher** (one thread): blocking ``queue.get`` (no idle
   polling — shutdown uses a sentinel), coalesces a window, copies request
   rows into a *preallocated per-bucket pad buffer* (no per-batch
   ``np.zeros`` + ``np.concatenate``), checks deadlines at coalesce AND
   again at dispatch, then issues the forward on the least-loaded
   :class:`~deeplearning4j_tpu.serving.replica.ReplicaPool` replica
   WITHOUT blocking on the result — JAX async dispatch queues the work
   per device.
2. **In-flight window**: at most ``pipeline_depth`` dispatched batches may
   await readback (a semaphore — the backpressure that bounds memory and
   keeps deadline checks honest). ``pipeline_depth=0`` degenerates to the
   PR-1 synchronous loop (the A/B baseline ``bench.py --serving`` uses).
3. **Completion** (one thread): blocking readback, scatter rows to
   requests, record metrics (incl. the dispatch-to-completion histogram
   and per-replica batch counts), return the pad buffer to its pool.

A failure anywhere — an injected ``serving.batcher.forward`` /
``serving.batcher.complete`` chaos fault, a real device error at readback —
fails only that batch's requests; later batches keep flowing.

Exactness contract: a request of ``n`` rows served at bucket ``b`` returns
``model.output(pad_to_b(x))[:n]`` **bit-for-bit** — at a fixed program
shape a row's result is independent of its neighbors and of its offset in
the batch, and a replica executes the model's own jitted ``output`` trace
(same HLO, deterministic XLA codegen per backend), so this holds on every
replica (verified empirically in ``tests/test_serving.py``). Across
*different* program shapes XLA codegen may legitimately differ in the last
ulp (e.g. a 1-row matvec path vs the same row inside a 16-row matmul on
CPU), so "identical to a solo ``model.output`` call at the request's own
shape" holds to ~1 ulp, not bitwise — that is XLA numerics, not batching.
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from deeplearning4j_tpu.runtime import chaos, trace
from deeplearning4j_tpu.serving.admission import (
    AdmissionController,
    DeadlineExceeded,
    Overloaded,
    ServingShutdown,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.replica import Replica, ReplicaPool

ArrayOrDict = Union[np.ndarray, Dict[str, np.ndarray]]

logger = logging.getLogger(__name__)

_SENTINEL = object()  # queue wake-up token: shutdown/drain, never a request


def _batch_span(requests, name: str):
    """Stage span for a coalesced batch on a worker thread: parented to
    the FIRST traced request of the batch (a batch span cannot have N
    parents — the other requests are stamped with bucket/replica on their
    own spans instead). The shared no-op span when nothing is traced."""
    for r in requests:
        if r.span is not None and r.span.recording:
            return r.span.child(name)
    return trace.NOOP


def default_buckets(max_batch_size: int) -> List[int]:
    """Powers of two up to ``max_batch_size`` (plus the max itself)."""
    out, b = [], 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(int(max_batch_size))
    return sorted(set(out))


class _Request:
    __slots__ = ("x", "rows", "deadline", "enqueued_at", "event",
                 "result", "error", "quantized", "span")

    def __init__(self, x: ArrayOrDict, rows: int, deadline: Optional[float],
                 quantized: bool = False):
        self.x = x
        self.rows = rows
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.quantized = quantized  # policy-dtype request (ISSUE 8)
        # the submitting context's active span (ISSUE 9): batch stage
        # spans on the worker threads parent to it, and bucket/replica
        # annotations land on it — None while tracing is disabled
        self.span = trace.current_span()


class _StepRequest:
    """One session step awaiting the session coalescer (ISSUE 16): a
    single stream row plus its batch-1 carry tree. Duck-types the
    ``_Request`` fields ``_expire``/``_fail`` touch so the deadline and
    failure paths are shared with stateless traffic."""

    __slots__ = ("x", "carries", "rows", "deadline", "enqueued_at", "event",
                 "result", "error", "quantized", "span")

    def __init__(self, x, carries, deadline: Optional[float]):
        self.x = x
        self.carries = carries
        self.rows = 1
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.quantized = False
        self.span = trace.current_span()


class _InFlight:
    """One dispatched batch awaiting readback."""

    __slots__ = ("requests", "rows", "bucket", "replica", "out", "buffers",
                 "forward_at", "dispatched_at")

    def __init__(self, requests, rows, bucket, replica, out, buffers,
                 forward_at, dispatched_at):
        self.requests: List[_Request] = requests
        self.rows = rows
        self.bucket = bucket
        self.replica: Replica = replica
        self.out = out                    # device array(s), not yet read back
        self.buffers = buffers            # [(pool_key, np buffer), ...]
        self.forward_at = forward_at      # just before the forward was issued
        self.dispatched_at = dispatched_at  # when dispatch returned


class ContinuousBatcher:
    """Continuous batching over one model (MLN or ComputationGraph).

    Thread-safe: any number of threads call :meth:`submit` concurrently; a
    coalescer thread forms bucketed batches and dispatches them onto device
    replicas without blocking on readback; a completion thread scatters
    results. ``replicas=N`` serves from N device-resident parameter copies
    (least-loaded routing); ``pipeline_depth`` bounds the dispatched-but-
    unread batches in flight (0 = synchronous PR-1 behaviour).

    Inputs: a single array for ``MultiLayerNetwork``-style models, or a
    ``{input_name: array}`` dict for multi-input ``ComputationGraph``s.
    """

    def __init__(self, model, max_batch_size: int = 32,
                 batch_timeout_ms: float = 2.0, queue_limit: int = 256,
                 buckets: Optional[Sequence[int]] = None,
                 admission: Optional[AdmissionController] = None,
                 metrics: Optional[ServingMetrics] = None,
                 warmup_example: Optional[ArrayOrDict] = None,
                 replicas: int = 1, pipeline_depth: int = 2,
                 devices: Optional[Sequence] = None,
                 dtype_policy=None, plan=None):
        self.model = model
        if model.train_state is None:
            model.init()
        # multi-axis ParallelPlan (ISSUE 20): a "replica" becomes one
        # plan-slice (pipe/tensor device group); recorded in the warmup
        # manifest so a replayed warmup rebuilds the same slicing
        self.plan = plan
        # per-model/per-bucket serving dtype policy (ISSUE 8): warmup
        # pre-warms the policy's quantized (bucket, replica, dtype) pairs
        # alongside the float ones, quantized requests are counted and
        # latency-split in the metrics, and the policy rides the warmup
        # manifest so a restart prewarms the quantized executables too
        self.dtype_policy = dtype_policy
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout_s = float(batch_timeout_ms) / 1000.0
        self.buckets = sorted(set(int(b) for b in
                                  (buckets or default_buckets(max_batch_size))))
        self.pipeline_depth = max(0, int(pipeline_depth))
        self.admission = admission or AdmissionController(queue_limit=queue_limit)
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._pool = ReplicaPool(model, n_replicas=replicas, devices=devices,
                                 plan=plan)
        self.metrics = metrics or ServingMetrics(
            queue_depth_fn=self._queue.qsize,
            compile_count_fn=self.compile_count,
            inflight_fn=self._pool.total_in_flight)
        if self.dtype_policy is not None:
            self.metrics.set_dtype_policy(self.dtype_policy.label())
        self._graph_inputs = list(getattr(model.conf, "inputs", []) or [])
        self._warmed_pairs: List[tuple] = []  # (bucket, replica, dtype)
        # worker thread mints buckets while a control thread resizes
        self._warm_lock = threading.Lock()  # guards: _warmed_pairs
        # serializes whole-resize operations (two racing target-chasing
        # scale loops would thrash replicas)
        self.resize_lock = threading.Lock()  # guards: (whole-resize serialization)
        self._shutdown = False
        self._draining = False
        self._saw_sentinel = False
        self._carry: Optional[_Request] = None  # deferred overflow request
        # vs shutdown: no orphan enqueues after the drain flag flips
        self._submit_lock = threading.Lock()  # guards: _draining
        self._example: Optional[ArrayOrDict] = None  # 1-row zeros template
        self._batch_seq = itertools.count(1)  # failure keys (breaker dedup)
        # pad-buffer pools: (bucket, input, shape, dtype) -> free np buffers
        self._buf_lock = threading.Lock()  # guards: _buf_pool
        self._buf_pool: Dict[tuple, List[np.ndarray]] = {}
        # at most `depth` dispatched-unread batches; completion releases
        self._slots = (threading.BoundedSemaphore(self.pipeline_depth)
                       if self.pipeline_depth >= 1 else None)
        self._completion_q: "queue.Queue[_InFlight]" = queue.Queue()
        self._completion_lock = threading.Lock()  # guards: _completion_closed
        self._completion_closed = False  # set once shutdown drained the queue
        # session-step path (ISSUE 16): a parallel coalescer for stateful
        # rnnTimeStep traffic, disabled until enable_sessions(). Every
        # step batch executes at ONE fixed padded bucket — under the
        # Exactness contract above a row's result is then independent of
        # how steps happened to coalesce, so a serial oracle padded to the
        # same shape reproduces every stream bit-identically.
        self._session_q: Optional["queue.Queue"] = None
        self._session_bucket: Optional[int] = None
        self._session_template = None    # batch-1 zero-carry tree (numpy)
        self._session_call = None        # (params, mstate, carries, xb) -> (out, new)
        self._session_carry: Optional[_StepRequest] = None
        self._session_saw_sentinel = False
        self._session_worker: Optional[threading.Thread] = None
        if warmup_example is not None:
            self.warmup(warmup_example)
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="ContinuousBatcher")
        self._completer: Optional[threading.Thread] = None
        if self.pipeline_depth >= 1:
            self._completer = threading.Thread(
                target=self._complete_loop, daemon=True,
                name="ContinuousBatcher-complete")
            self._completer.start()
        self._worker.start()

    # -------------------------------------------------------------- replicas
    @property
    def replica_count(self) -> int:
        return len(self._pool)

    def add_replica(self) -> int:
        """Grow the pool by one device replica at runtime (ISSUE 10: the
        SLO-feedback autoscaler's replica lever). The new replica is
        warmed from the live :meth:`warmup_manifest` — every recorded
        bucket, including traffic-minted ones, and the dtype policy's
        quantized twins — BEFORE it is published for routing, so a
        scaled-up replica never compiles on live traffic (the same
        guarantee a restart gets from the persisted manifest). Safe to
        call from a control/HTTP thread while traffic flows: warmup runs
        on an unpublished replica, and routing only sees it after.
        Returns the new replica count."""
        rep = self._pool.create_replica()
        manifest = self.warmup_manifest()
        if manifest is not None:
            example = manifest.example()
            for b in manifest.buckets:
                self._pool.forward_blocking(
                    rep, self._zeros_with_rows(example, b))
                self._record_warmed(b, rep.index, example)
            qex = (self.dtype_policy.quantized_zeros(example)
                   if self.dtype_policy is not None else None)
            if qex is not None:
                for b in self.dtype_policy.buckets_for(manifest.buckets):
                    self._pool.forward_blocking(
                        rep, self._zeros_with_rows(qex, b))
                    self._record_warmed(b, rep.index, qex)
        return self._pool.publish_replica(rep)

    def remove_replica(self) -> int:
        """Shrink the pool by one replica (the newest; replica 0 stays).
        In-flight batches on the retired replica complete normally — only
        new routing stops. Raises ``ValueError`` at one replica (the
        autoscaler's ``min_replicas`` floor is enforced above this, but
        the batcher itself must never become replica-less). Returns the
        new replica count."""
        rep = self._pool.retire_replica()
        if rep is None:
            raise ValueError("cannot remove the last replica")
        # the manifest audit record describes the LIVE pool: drop the
        # retired replica's pairs so a restart does not over-warm (under
        # the warm lock — the worker thread may be minting a bucket and
        # appending concurrently; an unlocked rebuild would lose it)
        with self._warm_lock:
            self._warmed_pairs[:] = [p for p in self._warmed_pairs
                                     if p[1] != rep.index]
        return self.replica_count

    # ------------------------------------------------------------ warmup
    def warmup(self, example: ArrayOrDict) -> int:
        """AOT-compile every (bucket, replica) program with zero rows shaped
        like ``example`` (any leading row count), and preallocate one pad
        buffer per bucket. Returns the number of programs warmed. After
        this, steady-state traffic triggers no compilation. Every warmed
        (bucket, replica, dtype) pair is recorded for
        :meth:`warmup_manifest`."""
        chaos.inject("serving.batcher.warmup")
        example = self._normalize(example)[0]
        self._example = self._zeros_with_rows(example, 1)
        # the dtype policy's quantized twin of the example (None without a
        # policy): its (bucket, replica) pairs are warmed alongside the
        # float ones so quantized traffic never compiles on the serving
        # path, and its pad buffers get their own dtype-keyed pools
        qex = (self.dtype_policy.quantized_zeros(example)
               if self.dtype_policy is not None else None)
        n = 0
        for rep in self._pool.replicas:
            for b in self.buckets:
                self._pool.forward_blocking(
                    rep, self._zeros_with_rows(example, b))
                self._record_warmed(b, rep.index, example)
                n += 1
            if qex is not None:
                for b in self.dtype_policy.buckets_for(self.buckets):
                    self._pool.forward_blocking(
                        rep, self._zeros_with_rows(qex, b))
                    self._record_warmed(b, rep.index, qex)
                    n += 1
        for b in self.buckets:  # preallocate the pad buffers
            self._release_buffers(self._gather([], 0, b, template=example)[1])
        if qex is not None:
            for b in self.dtype_policy.buckets_for(self.buckets):
                self._release_buffers(self._gather([], 0, b,
                                                   template=qex)[1])
        return n

    def _record_warmed(self, bucket: int, replica: int,
                       example: Optional[ArrayOrDict] = None) -> None:
        example = example if example is not None else self._example
        if example is None:
            dt = "?"
        elif isinstance(example, dict):
            dt = ",".join(sorted({str(v.dtype)
                                  for v in example.values()}))
        else:
            dt = str(example.dtype)
        with self._warm_lock:
            self._warmed_pairs.append((int(bucket), int(replica), dt))

    def warmup_manifest(self):
        """Manifest of everything this batcher compiled — buckets
        (including any minted under live traffic), replica count, the
        input signature, and every recorded (bucket, replica, dtype) pair.
        ``None`` until the batcher has been warmed or has seen traffic
        (there is nothing to replay yet). Persisted next to model archives
        by the registry so a restart can replay the warmup against the
        persistent executable cache (``docs/coldstart.md``)."""
        from deeplearning4j_tpu.serving.manifest import WarmupManifest
        if self._example is None:
            return None
        with self._warm_lock:
            pairs = list(self._warmed_pairs)
        return WarmupManifest.from_example(
            self._example, buckets=list(self.buckets),
            replicas=self.replica_count,
            pairs=pairs,
            max_batch_size=self.max_batch_size,
            model=type(self.model).__name__,
            policy=(self.dtype_policy.to_dict()
                    if self.dtype_policy is not None else None),
            plan=(self.plan.describe() if self.plan is not None else None))

    @staticmethod
    def _zeros_with_rows(x: ArrayOrDict, rows: int) -> ArrayOrDict:
        if isinstance(x, dict):
            return {k: np.zeros((rows,) + v.shape[1:], v.dtype)
                    for k, v in x.items()}
        return np.zeros((rows,) + x.shape[1:], x.dtype)

    def compile_count(self) -> int:
        """XLA compilations behind this model's inference path: AOT
        executables minted by the replica pool (the fast-path ledger) plus
        jit-cache entry counts of every cached ``output`` function (the
        fallback/direct-call ledger). A warmed pipeline holds exactly
        ``len(buckets) x replica_count`` executables."""
        n = self._pool.aot_count()
        for key, fn in getattr(self.model, "_jit_cache", {}).items():
            k = str(key)
            # "output@*": the stateless fallback/direct-call ledger.
            # "rnn_stored_state@train=False@*" / "rnn_time_step@*": the
            # session-step program (ISSUE 16) — counted so the "zero
            # on-traffic compiles after warm" assertion covers session
            # traffic too.
            if (k.startswith("output@")
                    or k.startswith("rnn_stored_state@train=False@")
                    or k.startswith("rnn_time_step@")) \
                    and hasattr(fn, "_cache_size"):
                n += fn._cache_size()
        return n

    # ------------------------------------------------------------ submit
    def _normalize(self, x: ArrayOrDict):
        if isinstance(x, dict):
            xs = {k: np.asarray(v) for k, v in x.items()}
            rows = {v.shape[0] for v in xs.values()}
            if len(rows) != 1:
                raise ValueError(f"inconsistent leading dims across inputs: "
                                 f"{ {k: v.shape for k, v in xs.items()} }")
            return xs, rows.pop()
        xs = np.asarray(x)
        if xs.ndim == 0:
            raise ValueError("request must have a leading batch dimension")
        return xs, xs.shape[0]

    def _drain_ms_per_request(self) -> Optional[float]:
        """Recent per-request service estimate (mean batch latency spread
        over a full bucket) — the drain rate behind the ``Retry-After``
        hint on :class:`Overloaded` rejections. ``None`` until a batch has
        been measured."""
        hist = self.metrics.batch_latency
        if hist.count == 0:
            return None
        return hist.mean * 1000.0 / max(1, self.max_batch_size)

    def submit(self, x: ArrayOrDict, timeout_ms: Optional[float] = None):
        """Blocking inference; safe from many threads at once.

        Raises :class:`Overloaded` when the queue is full,
        :class:`DeadlineExceeded` when the deadline passed before the model
        ran the request, :class:`ServingShutdown` if shut down first.
        """
        chaos.inject("serving.batcher.submit")
        xs, rows = self._normalize(x)
        # read-only rows are the signature of the binary wire path: views
        # over the request frame (or a shared-memory segment) that land in
        # the pad buffer with exactly one copy — count them (ISSUE 18)
        if (any(not v.flags.writeable for v in xs.values())
                if isinstance(xs, dict) else not xs.flags.writeable):
            self.metrics.record_zero_copy(rows)
        with self._submit_lock:
            if self._shutdown or self._draining:
                raise ServingShutdown("batcher is shut down")
            try:
                self.admission.admit(self._queue.qsize(),
                                     self._drain_ms_per_request())
            except Overloaded:
                self.metrics.record_rejection("overload")
                trace.flag_current("shed")  # tail sampling keeps sheds
                raise
            quant = (self.dtype_policy is not None
                     and self.dtype_policy.is_quantized_request(xs))
            req = _Request(xs, rows, self.admission.deadline_for(timeout_ms),
                           quantized=quant)
            self.metrics.record_admitted(quantized=quant)
            self._queue.put(req)
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    # ----------------------------------------------------- session steps
    def enable_sessions(self, example: ArrayOrDict,
                        session_bucket: int = 8) -> None:
        """Switch on the stateful session-step path (ISSUE 16).

        ``example`` is ONE stream row of step input — shape ``(1, T, F)``
        — used to pin the carry dtype and AOT-warm the fixed session
        program on every replica before traffic. ``session_bucket`` is
        the single padded batch size every step batch executes at: a
        FIXED program shape, deliberately not the stateless bucket
        ladder, because cross-shape XLA codegen may differ in the last
        ulp and the session tier promises bit-identity to a serial
        ``rnn_time_step`` loop padded to the same shape. Idempotent."""
        if self._session_q is not None:
            return
        model = self.model
        if not hasattr(model, "rnn_zero_state"):
            raise ValueError("model has no recurrent-state API "
                             "(rnn_zero_state); sessions need an RNN")
        xs, rows = self._normalize(example)
        if isinstance(xs, dict):
            if len(xs) != 1:
                raise ValueError("session steps support single-input "
                                 "models only")
            xs = next(iter(xs.values()))
        if rows != 1:
            raise ValueError("session warmup example must be exactly one "
                             "stream row")
        outputs = list(getattr(model.conf, "outputs", []) or [])
        if self._graph_inputs and len(outputs) != 1:
            raise ValueError("session steps support single-output graphs "
                             "only")
        template = model.rnn_zero_state(1, like=xs)
        if not jax.tree.leaves(template):
            raise ValueError("model has no recurrent layers; use submit()")
        self._session_template = jax.tree.map(np.asarray, template)
        if self._graph_inputs:
            name = self._graph_inputs[0]
            raw = model._rnn_step_fn()

            def call(params, mstate, carries, xb, _n=name, _raw=raw):
                outs, new = _raw(params, mstate, {_n: xb}, carries)
                return outs[0], new
        else:
            raw = model._rnn_step_fn(training=False)

            def call(params, mstate, carries, xb, _raw=raw):
                return _raw(params, mstate, carries, xb, None)
        self._session_call = call
        self._session_bucket = max(1, int(session_bucket))
        # warm the one fixed shape on every replica now — first session
        # traffic must never pay a compile
        xb = np.zeros((self._session_bucket,) + xs.shape[1:], xs.dtype)
        carries = self._stack_carries([], self._session_bucket)
        for rep in list(self._pool.replicas):
            params, mstate = self._replica_state(rep)
            out, _ = self._session_call(params, mstate, carries, xb)
            np.asarray(out)  # block until the executable exists
        self._session_q = queue.Queue()
        self._session_worker = threading.Thread(
            target=self._run_sessions, daemon=True,
            name="ContinuousBatcher-session")
        self._session_worker.start()

    @property
    def session_bucket(self) -> Optional[int]:
        return self._session_bucket

    def session_state_template(self):
        """Fresh copy of the batch-1 zero-carry tree a new stream starts
        from (numpy leaves, carry dtype already pinned by warmup)."""
        if self._session_template is None:
            raise RuntimeError("sessions not enabled on this batcher")
        return jax.tree.map(np.copy, self._session_template)

    def _replica_state(self, rep):
        """(params, model_state) a session step executes against — the
        replica's device_put copies, or the model's host state for the
        fallback pseudo-replica."""
        if rep.params is not None:
            return rep.params, rep.model_state
        ts = self.model.train_state
        return ts.params, ts.model_state

    def _stack_carries(self, trees, bucket: int):
        """Gather per-stream batch-1 carry trees into one batch-``bucket``
        tree: concatenate along axis 0, zero-pad the tail rows with the
        template. Padding rows cannot perturb live rows — fixed program
        shape, row-independent results (Exactness contract)."""
        trees = list(trees) + [self._session_template] * (bucket - len(trees))
        return jax.tree.map(
            lambda *ls: np.concatenate([np.asarray(l) for l in ls], axis=0),
            *trees)

    def submit_step(self, x: ArrayOrDict, carries,
                    timeout_ms: Optional[float] = None):
        """Blocking session step: advance ONE stream row by one input
        chunk. ``carries`` is the stream's batch-1 carry tree (``None``
        for a fresh stream). Returns ``(out_row, new_carries)`` with
        numpy leaves. Steps coalesce with other streams' concurrent steps
        into the fixed session bucket; admission, deadlines and shutdown
        semantics are shared with :meth:`submit`."""
        if self._session_q is None:
            raise RuntimeError("sessions not enabled on this batcher "
                               "(call enable_sessions first)")
        chaos.inject("serving.batcher.submit")
        xs, rows = self._normalize(x)
        if isinstance(xs, dict):
            if len(xs) != 1:
                raise ValueError("session steps support single-input "
                                 "models only")
            xs = next(iter(xs.values()))
        if rows != 1:
            raise ValueError("a session step carries exactly one stream "
                             "row")
        with self._submit_lock:
            if self._shutdown or self._draining:
                raise ServingShutdown("batcher is shut down")
            try:
                self.admission.admit(self._session_q.qsize(),
                                     self._drain_ms_per_request())
            except Overloaded:
                self.metrics.record_rejection("overload")
                trace.flag_current("shed")
                raise
            req = _StepRequest(xs, carries,
                               self.admission.deadline_for(timeout_ms))
            self.metrics.record_admitted()
            self._session_q.put(req)
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def _collect_steps(self, first: _StepRequest) -> List[_StepRequest]:
        """Session-window coalescing: same one-deadline-per-window rule as
        :meth:`_collect`, capped at the fixed session bucket; a step whose
        input signature differs from the window's carries over."""
        batch = [first]
        sig = self._sig(first.x)
        deadline = time.monotonic() + self.batch_timeout_s
        while len(batch) < self._session_bucket:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._session_q.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is _SENTINEL:
                self._session_saw_sentinel = True
                break
            if self._sig(nxt.x) != sig:
                self._session_carry = nxt
                break
            batch.append(nxt)
        return batch

    def _dispatch_steps(self, batch: List[_StepRequest]) -> None:
        live = self._expire(batch, "session-dispatch")
        if not live:
            return
        bucket = self._session_bucket
        rows = len(live)
        replica = None
        t0 = time.monotonic()
        dsp = _batch_span(live, "batcher.session_step")
        try:
            with dsp:
                if dsp.recording:
                    dsp.set("bucket", bucket)
                    dsp.set("rows", rows)
                xb = np.zeros((bucket,) + live[0].x.shape[1:],
                              live[0].x.dtype)
                for i, r in enumerate(live):
                    xb[i] = r.x[0]
                carries = self._stack_carries(
                    [r.carries if r.carries is not None
                     else self._session_template for r in live], bucket)
                chaos.inject("serving.batcher.forward")
                replica = self._pool.acquire()
                params, mstate = self._replica_state(replica)
                out, new = self._session_call(params, mstate, carries, xb)
                out = np.asarray(out)            # blocking readback
                new = jax.tree.map(np.asarray, new)
                if dsp.recording:
                    dsp.set("replica", replica.index)
        except BaseException as e:
            # fail only this window — an injected fault or a bad step mix
            # must not kill the session coalescer
            if replica is not None:
                self._pool.release(replica)
            self._fail(live, e)
            return
        t1 = time.monotonic()
        self._pool.release(replica)
        self.metrics.record_batch(rows, bucket, t1 - t0,
                                  replica=replica.index)
        for i, r in enumerate(live):
            row_out = np.ascontiguousarray(out[i:i + 1])
            row_new = jax.tree.map(
                lambda l, _i=i: np.ascontiguousarray(l[_i:_i + 1]), new)
            r.result = (row_out, row_new)
            self.metrics.record_response(t1 - r.enqueued_at)
            r.event.set()

    def _run_sessions(self) -> None:
        while True:
            if self._shutdown:
                break
            if self._session_carry is not None:
                first, self._session_carry = self._session_carry, None
            elif self._session_saw_sentinel:
                break  # drained: every step before the sentinel is served
            else:
                first = self._session_q.get()
                if first is _SENTINEL:
                    break
            batch = self._collect_steps(first)
            try:
                self._dispatch_steps(batch)
            except BaseException as e:
                logger.exception("unexpected error dispatching a session "
                                 "step window")
                self._fail([r for r in batch if not r.event.is_set()], e)

    # ----------------------------------------------------------- coalesce
    @staticmethod
    def _sig(x: ArrayOrDict):
        """Coalescing signature: feature shape + dtype per input. Only
        same-signature requests may share a pad buffer — a dtype mismatch
        would silently cast one request's rows into the other's buffer
        dtype (the replaced np.concatenate promoted instead), and a shape
        mismatch would poison the whole window."""
        if isinstance(x, dict):
            return tuple(sorted((k, v.shape[1:], v.dtype.str)
                                for k, v in x.items()))
        return (x.shape[1:], x.dtype.str)

    def _collect(self, first: _Request) -> List[_Request]:
        """Coalesce: one deadline for the WHOLE window (seed bug: a fresh
        ``batch_timeout_s`` per ``queue.get`` meant worst-case added latency
        of ``max_batch_size x timeout`` under a slow trickle). A request
        that would push the batch past ``max_batch_size`` — or one whose
        shape/dtype signature differs from the window's — is carried into
        the next window instead of overflowing or poisoning this one."""
        batch = [first]
        total = first.rows
        sig = self._sig(first.x)
        deadline = time.monotonic() + self.batch_timeout_s
        while total < self.max_batch_size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is _SENTINEL:
                self._saw_sentinel = True
                break
            if (total + nxt.rows > self.max_batch_size
                    or self._sig(nxt.x) != sig):
                self._carry = nxt
                break
            batch.append(nxt)
            total += nxt.rows
        return batch

    def _bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        # oversized single request (rows > max bucket): round up to the next
        # power of two, remember it, and warm it on every replica NOW — the
        # creating request pays the compile once and the bound
        # `compiles <= buckets x replicas` stays truthful for later traffic
        # (only the worker thread touches self.buckets after construction)
        b = self.buckets[-1]
        while b < rows:
            b *= 2
        self.buckets = sorted(set(self.buckets + [b]))
        self._warm_bucket(b)
        return b

    def _warm_bucket(self, b: int) -> None:
        if self._example is None:
            return  # never warmed and no traffic yet: first dispatch compiles
        qex = (self.dtype_policy.quantized_zeros(self._example)
               if self.dtype_policy is not None
               and b in self.dtype_policy.buckets_for([b]) else None)
        for rep in self._pool.replicas:
            self._pool.forward_blocking(rep, self._zeros_with_rows(
                self._example, b))
            self._record_warmed(b, rep.index)
            if qex is not None:  # minted buckets stay policy-complete
                self._pool.forward_blocking(
                    rep, self._zeros_with_rows(qex, b))
                self._record_warmed(b, rep.index, qex)

    # ---------------------------------------------------------- pad buffers
    def _acquire_buf(self, bucket: int, name, like: np.ndarray):
        k = (bucket, name, like.shape[1:], like.dtype.str)
        with self._buf_lock:
            free = self._buf_pool.get(k)
            if free:
                return k, free.pop()
        return k, np.empty((bucket,) + like.shape[1:], like.dtype)

    def _release_buffers(self, buffers) -> None:
        # a buffer returns only after its batch's readback completed, so
        # device execution can no longer be reading it (safe even when the
        # backend aliased the host buffer instead of copying)
        cap = self.pipeline_depth + 2
        with self._buf_lock:
            for k, buf in buffers:
                free = self._buf_pool.setdefault(k, [])
                if len(free) < cap:
                    free.append(buf)

    def _gather(self, live: List[_Request], rows: int, bucket: int,
                template: Optional[ArrayOrDict] = None
                ) -> Tuple[ArrayOrDict, list]:
        """Copy request rows into a pooled per-bucket pad buffer and zero
        the tail — replaces PR-1's per-batch ``np.concatenate`` +
        ``np.zeros`` allocations. Bit-identical to pad(concat(rows))."""
        template = template if template is not None else live[0].x
        held = []
        if isinstance(template, dict):
            x = {}
            for name, v in template.items():
                k, buf = self._acquire_buf(bucket, name, v)
                ofs = 0
                for r in live:
                    buf[ofs:ofs + r.rows] = r.x[name]
                    ofs += r.rows
                if ofs < bucket:
                    buf[ofs:] = 0
                x[name] = buf
                held.append((k, buf))
            for r in live:
                r.x = None  # release borrowed wire/shm views (ISSUE 18)
            return x, held
        k, buf = self._acquire_buf(bucket, None, template)
        ofs = 0
        for r in live:
            buf[ofs:ofs + r.rows] = r.x
            ofs += r.rows
            # drop the row reference NOW: binary wire requests hand the
            # batcher read-only views over the frame (or a shared-memory
            # segment), and the segment may only be closed once no view
            # exports its buffer — holding x until the request is GC'd
            # would keep the mapping alive past the response (ISSUE 18)
            r.x = None
        if ofs < bucket:
            buf[ofs:] = 0
        return buf, [(k, buf)]

    # ------------------------------------------------------------ dispatch
    def _forward(self, x: ArrayOrDict):
        """Issue the forward on the least-loaded replica; returns
        ``(device_out, replica)`` WITHOUT blocking on readback."""
        chaos.inject("serving.batcher.forward")
        replica = self._pool.acquire()
        try:
            out = self._pool.dispatch(replica, x)
        except BaseException:
            self._pool.release(replica)
            raise
        return out, replica

    def _expire(self, batch: List[_Request], stage: str) -> List[_Request]:
        now = time.monotonic()
        live: List[_Request] = []
        for r in batch:
            if r.deadline is not None and now > r.deadline:
                r.error = DeadlineExceeded(
                    f"deadline passed {now - r.deadline:.3f}s before "
                    f"execution at the {stage} stage "
                    f"(queued {now - r.enqueued_at:.3f}s)")
                self.metrics.record_rejection("deadline")
                if r.span is not None:
                    r.span.flag("deadline")
                    r.span.event("expired", stage=stage)
                r.event.set()
            else:
                live.append(r)
        return live

    def _tag_failure(self, e: BaseException) -> None:
        """Stamp a per-batch key so the circuit breaker can count one
        faulted batch once, not once per coalesced request. Stamped
        UNCONDITIONALLY: a chaos policy may raise the same exception
        instance for every hit, and a stale key from an earlier batch
        would make the breaker dedup real repeated failures (and never
        open under a sustained fault)."""
        try:
            e._serving_failure_key = f"batch-{id(self)}-{next(self._batch_seq)}"
        except Exception:
            pass  # exceptions with __slots__: breaker falls back to per-request

    def _fail(self, requests: List[_Request], e: BaseException) -> None:
        self._tag_failure(e)
        for r in requests:
            r.error = e
            self.metrics.record_rejection("error")
            r.event.set()

    def _abort(self, requests: List[_Request], e: BaseException,
               buffers=(), replica=None, slot_held: bool = False,
               reuse_buffers: bool = False) -> None:
        """Fail ONE batch and release whatever it held. ``reuse_buffers``
        may only be True when the forward was never dispatched — a
        dispatched execution may still be reading an (aliased) pad buffer,
        so those are dropped for GC instead of returned to the pool."""
        if reuse_buffers:
            self._release_buffers(buffers)
        if replica is not None:
            self._pool.release(replica)
        if slot_held and self._slots is not None:
            self._slots.release()
        self._fail(requests, e)

    def _dispatch(self, batch: List[_Request]) -> None:
        live = self._expire(batch, "coalesce")
        if not live:
            return
        slot_held = False
        buffers: list = []
        out = replica = None
        try:
            if self._example is None:
                self._example = self._zeros_with_rows(live[0].x, 1)
            if self._slots is not None:
                # backpressure: wait for an in-flight slot (bounded poll
                # so a hard shutdown can't strand us here)
                while not self._slots.acquire(timeout=0.1):
                    if self._shutdown:
                        self._fail(live, ServingShutdown(
                            "batcher shut down before this batch was "
                            "dispatched"))
                        return
                slot_held = True
                # a slot wait can outlive a deadline: re-check at dispatch
                live = self._expire(live, "dispatch")
                if not live:
                    self._slots.release()
                    return
            rows = sum(r.rows for r in live)
            bucket = self._bucket_for(rows)      # may mint + warm a bucket
            # stage span (ISSUE 9): parented to the first traced request
            # of the batch; chaos at serving.batcher.forward and the AOT
            # hit/miss of the dispatch land on it, and every traced
            # request is stamped with its bucket + replica
            dsp = _batch_span(live, "batcher.dispatch")
            with dsp:
                if dsp.recording:
                    dsp.set("bucket", bucket)
                    dsp.set("rows", rows)
                    dsp.set("requests", len(live))
                x, buffers = self._gather(live, rows, bucket)
                forward_at = time.monotonic()
                # AotCache.call annotates "aot" hit/miss on this span
                out, replica = self._forward(x)
                if dsp.recording:
                    dsp.set("replica", replica.index)
                    for r in live:
                        if r.span is not None and r.span.recording:
                            r.span.set("bucket", bucket)
                            r.span.set("replica", replica.index)
        except BaseException as e:
            # fail only this batch — a bad request mix (inconsistent
            # feature shapes, missing dict input key), a failed bucket
            # warm, or an injected fault must not kill the coalescer
            # (PR-1 kept the equivalent _execute body inside try too)
            self._abort(live, e, buffers=buffers, replica=replica,
                        slot_held=slot_held, reuse_buffers=out is None)
            return
        rec = _InFlight(live, rows, bucket, replica, out, buffers,
                        forward_at, time.monotonic())
        if self._slots is None:
            self._complete(rec)          # synchronous (PR-1) mode
            return
        with self._completion_lock:
            if not self._completion_closed:
                self._completion_q.put(rec)
                return
        # shutdown already drained the completion queue (this worker
        # outlived its join timeout): nobody will ever read this record —
        # fail it here instead of stranding its callers
        self._abort(live, ServingShutdown(
            "batcher shut down before this batch could complete"),
            buffers=buffers, replica=replica, slot_held=True)

    # ---------------------------------------------------------- completion
    def _complete(self, rec: _InFlight) -> None:
        csp = _batch_span(rec.requests, "batcher.complete")
        try:
            with csp:
                if csp.recording:
                    csp.set("bucket", rec.bucket)
                    csp.set("replica", rec.replica.index)
                    csp.set("rows", rec.rows)
                chaos.inject("serving.batcher.complete")
                out = rec.out
                if isinstance(out, (list, tuple)):
                    out = [np.asarray(o) for o in out]   # blocking readback
                else:
                    out = np.asarray(out)
            t1 = time.monotonic()
            # readback done => the execution can no longer be reading the
            # pad buffers; only NOW may they return to the pool
            self._release_buffers(rec.buffers)
            self.metrics.record_batch(rec.rows, rec.bucket,
                                      t1 - rec.forward_at,
                                      replica=rec.replica.index)
            self.metrics.record_dispatch(t1 - rec.dispatched_at)
            ofs = 0
            for r in rec.requests:
                sl = slice(ofs, ofs + r.rows)
                r.result = ([o[sl] for o in out]
                            if isinstance(out, list) else out[sl])
                ofs += r.rows
                self.metrics.record_response(t1 - r.enqueued_at,
                                             quantized=r.quantized)
        except BaseException as e:
            # fault before/at readback: execution state unknown, so the
            # buffers are dropped for GC, not pooled (an aliased buffer
            # must never be rewritten under an in-flight execution)
            self._tag_failure(e)
            for r in rec.requests:
                r.error = e
                self.metrics.record_rejection("error")
        finally:
            self._pool.release(rec.replica)
            if self._slots is not None:
                self._slots.release()
            for r in rec.requests:
                r.event.set()

    def _complete_loop(self) -> None:
        while True:
            rec = self._completion_q.get()
            if rec is _SENTINEL:
                break
            self._complete(rec)

    # -------------------------------------------------------------- worker
    def _run(self) -> None:
        while True:
            if self._shutdown:
                break
            if self._carry is not None:
                first, self._carry = self._carry, None
            elif self._saw_sentinel:
                break  # drained: everything before the sentinel is served
            else:
                first = self._queue.get()  # blocking — no idle busy-wake
                if first is _SENTINEL:
                    break
            batch = self._collect(first)
            try:
                self._dispatch(batch)
            except BaseException as e:  # last resort: _dispatch fails its
                # own batch internally; whatever still escapes must fail
                # the batch, never kill the coalescer thread
                logger.exception("unexpected error dispatching a batch")
                self._fail([r for r in batch if not r.event.is_set()], e)

    # ---------------------------------------------------------- shutdown
    def shutdown(self, drain: bool = True, timeout_s: float = 5.0) -> None:
        """Stop the pipeline. ``drain=True`` (default) serves whatever is
        already queued AND waits for every in-flight batch to read back;
        either way every still-pending request gets an explicit
        :class:`ServingShutdown` error — no caller hangs (seed bug:
        queued-but-unbatched requests never got ``event.set()``)."""
        with self._submit_lock:
            if drain:
                self._draining = True
            else:
                self._shutdown = True
        self._queue.put(_SENTINEL)  # wake the blocking coalescer
        if self._session_q is not None:
            self._session_q.put(_SENTINEL)  # wake the session coalescer
        self._worker.join(timeout=timeout_s)
        if self._session_worker is not None:
            self._session_worker.join(timeout=timeout_s)
        if self._completer is not None:
            self._completion_q.put(_SENTINEL)
            self._completer.join(timeout=timeout_s)
            # No record may be left for a consumer that will never read it
            # ("no caller hangs" contract). Close the queue (a straggling
            # worker now fails its own batches at dispatch), then drain:
            # if the completer exited cleanly, finish stragglers inline;
            # if it is WEDGED (hung readback), do not attempt more
            # readbacks — fail the queued batches explicitly instead.
            with self._completion_lock:
                self._completion_closed = True
            wedged = self._completer.is_alive()
            while True:
                try:
                    rec = self._completion_q.get_nowait()
                except queue.Empty:
                    break
                if rec is _SENTINEL:
                    continue
                if wedged:
                    self._abort(rec.requests, ServingShutdown(
                        "batcher completion stage wedged at shutdown; "
                        "this batch was dispatched but never read back"),
                        buffers=rec.buffers, replica=rec.replica,
                        slot_held=True)
                else:
                    self._complete(rec)
        with self._submit_lock:
            self._shutdown = True
            self._draining = True
        leftovers = []
        if self._carry is not None:
            leftovers.append(self._carry)
            self._carry = None
        if self._session_carry is not None:
            leftovers.append(self._session_carry)
            self._session_carry = None
        drainable = [self._queue]
        if self._session_q is not None:
            drainable.append(self._session_q)
        for q in drainable:
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                if item is not _SENTINEL:
                    leftovers.append(item)
        for r in leftovers:
            r.error = ServingShutdown(
                "batcher shut down before this request was served")
            r.event.set()
        # a worker that outlived its join timeout may have re-parked in the
        # blocking get AFTER the drain above swallowed the first sentinel;
        # leave one more so it can never be parked forever
        if self._worker.is_alive():
            self._queue.put(_SENTINEL)
        if self._session_worker is not None and self._session_worker.is_alive():
            self._session_q.put(_SENTINEL)
