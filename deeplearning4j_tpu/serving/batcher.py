"""Shape-bucketed continuous batcher.

The seed's ``ParallelInference`` coalesced concurrent requests into whatever
total row count happened to arrive — so every distinct coalesced size was a
fresh XLA compilation, and a long-running server would keep compiling for as
long as traffic kept producing new sizes. Here coalesced batches are padded
up to a fixed set of power-of-two row buckets that are AOT-warmed at model
load, so the number of compilations is bounded by the bucket count, not by
traffic. Padding rows are dead weight (row-wise inference ops never couple
rows at inference time — BN uses running stats).

Exactness contract: a request of ``n`` rows served at bucket ``b`` returns
``model.output(pad_to_b(x))[:n]`` **bit-for-bit** — at a fixed program
shape a row's result is independent of its neighbors and of its offset in
the batch (verified empirically in ``tests/test_serving.py``). Across
*different* program shapes XLA codegen may legitimately differ in the last
ulp (e.g. a 1-row matvec path vs the same row inside a 16-row matmul on
CPU), so "identical to a solo ``model.output`` call at the request's own
shape" holds to ~1 ulp, not bitwise — that is XLA numerics, not batching.

Also fixes two seed bugs (ISSUE satellites):

- the coalesce window is ONE deadline for the whole batch, not a fresh
  ``batch_timeout_s`` per ``queue.get`` (worst case used to be
  ``max_batch_size x timeout`` of added latency under a slow trickle);
- ``shutdown()`` drains queued-but-unbatched requests and fails them with
  :class:`~deeplearning4j_tpu.serving.admission.ServingShutdown` instead of
  leaving their callers blocked forever.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.runtime import chaos
from deeplearning4j_tpu.serving.admission import (
    AdmissionController,
    DeadlineExceeded,
    Overloaded,
    ServingShutdown,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics

ArrayOrDict = Union[np.ndarray, Dict[str, np.ndarray]]


def default_buckets(max_batch_size: int) -> List[int]:
    """Powers of two up to ``max_batch_size`` (plus the max itself)."""
    out, b = [], 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(int(max_batch_size))
    return sorted(set(out))


class _Request:
    __slots__ = ("x", "rows", "deadline", "enqueued_at", "event",
                 "result", "error")

    def __init__(self, x: ArrayOrDict, rows: int, deadline: Optional[float]):
        self.x = x
        self.rows = rows
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class ContinuousBatcher:
    """Continuous batching over one model (MLN or ComputationGraph).

    Thread-safe: any number of threads call :meth:`submit` concurrently; a
    single worker thread coalesces, pads to a bucket, runs the model's own
    jitted ``output`` (sharing its compile cache) and scatters results.

    Inputs: a single array for ``MultiLayerNetwork``-style models, or a
    ``{input_name: array}`` dict for multi-input ``ComputationGraph``s.
    """

    def __init__(self, model, max_batch_size: int = 32,
                 batch_timeout_ms: float = 2.0, queue_limit: int = 256,
                 buckets: Optional[Sequence[int]] = None,
                 admission: Optional[AdmissionController] = None,
                 metrics: Optional[ServingMetrics] = None,
                 warmup_example: Optional[ArrayOrDict] = None):
        self.model = model
        if model.train_state is None:
            model.init()
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout_s = float(batch_timeout_ms) / 1000.0
        self.buckets = sorted(set(int(b) for b in
                                  (buckets or default_buckets(max_batch_size))))
        self.admission = admission or AdmissionController(queue_limit=queue_limit)
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self.metrics = metrics or ServingMetrics(
            queue_depth_fn=self._queue.qsize,
            compile_count_fn=self.compile_count)
        self._graph_inputs = list(getattr(model.conf, "inputs", []) or [])
        self._shutdown = False
        self._draining = False
        self._carry: Optional[_Request] = None  # deferred overflow request
        self._submit_lock = threading.Lock()  # vs shutdown: no orphan enqueues
        if warmup_example is not None:
            self.warmup(warmup_example)
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="ContinuousBatcher")
        self._worker.start()

    # ------------------------------------------------------------ warmup
    def warmup(self, example: ArrayOrDict) -> int:
        """AOT-compile every bucket size with zero rows shaped like
        ``example`` (any leading row count). Returns the number of buckets
        warmed. After this, steady-state traffic triggers no compilation."""
        chaos.inject("serving.batcher.warmup")
        example = self._normalize(example)[0]
        for b in self.buckets:
            self._forward(self._zeros_with_rows(example, b))
        return len(self.buckets)

    @staticmethod
    def _zeros_with_rows(x: ArrayOrDict, rows: int) -> ArrayOrDict:
        if isinstance(x, dict):
            return {k: np.zeros((rows,) + v.shape[1:], v.dtype)
                    for k, v in x.items()}
        return np.zeros((rows,) + x.shape[1:], x.dtype)

    def compile_count(self) -> int:
        """XLA compilations behind this model's inference path: the sum of
        jit-cache entry counts of every cached ``output`` function."""
        n = 0
        for key, fn in getattr(self.model, "_jit_cache", {}).items():
            if str(key).startswith("output@") and hasattr(fn, "_cache_size"):
                n += fn._cache_size()
        return n

    # ------------------------------------------------------------ submit
    def _normalize(self, x: ArrayOrDict):
        if isinstance(x, dict):
            xs = {k: np.asarray(v) for k, v in x.items()}
            rows = {v.shape[0] for v in xs.values()}
            if len(rows) != 1:
                raise ValueError(f"inconsistent leading dims across inputs: "
                                 f"{ {k: v.shape for k, v in xs.items()} }")
            return xs, rows.pop()
        xs = np.asarray(x)
        if xs.ndim == 0:
            raise ValueError("request must have a leading batch dimension")
        return xs, xs.shape[0]

    def submit(self, x: ArrayOrDict, timeout_ms: Optional[float] = None):
        """Blocking inference; safe from many threads at once.

        Raises :class:`Overloaded` when the queue is full,
        :class:`DeadlineExceeded` when the deadline passed before the model
        ran the request, :class:`ServingShutdown` if shut down first.
        """
        chaos.inject("serving.batcher.submit")
        xs, rows = self._normalize(x)
        with self._submit_lock:
            if self._shutdown or self._draining:
                raise ServingShutdown("batcher is shut down")
            try:
                self.admission.admit(self._queue.qsize())
            except Overloaded:
                self.metrics.record_rejection("overload")
                raise
            req = _Request(xs, rows, self.admission.deadline_for(timeout_ms))
            self.metrics.record_admitted()
            self._queue.put(req)
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    # ------------------------------------------------------------ worker
    def _collect(self, first: _Request) -> List[_Request]:
        """Coalesce: one deadline for the WHOLE window (seed bug: a fresh
        ``batch_timeout_s`` per ``queue.get`` meant worst-case added latency
        of ``max_batch_size x timeout`` under a slow trickle). A request
        that would push the batch past ``max_batch_size`` is carried into
        the next window instead of overflowing into a bigger bucket."""
        batch = [first]
        total = first.rows
        deadline = time.monotonic() + self.batch_timeout_s
        while total < self.max_batch_size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if total + nxt.rows > self.max_batch_size:
                self._carry = nxt
                break
            batch.append(nxt)
            total += nxt.rows
        return batch

    def _bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        # oversized single request (rows > max bucket): round up to the next
        # power of two and remember it, so the compile bound stays truthful
        # (only the worker thread touches self.buckets after construction)
        b = self.buckets[-1]
        while b < rows:
            b *= 2
        self.buckets = sorted(set(self.buckets + [b]))
        return b

    def _forward(self, x: ArrayOrDict):
        chaos.inject("serving.batcher.forward")
        if isinstance(x, dict):
            names = self._graph_inputs or sorted(x)
            return self.model.output(*[x[n] for n in names])
        return self.model.output(x)

    @staticmethod
    def _pad(x: ArrayOrDict, rows: int, bucket: int) -> ArrayOrDict:
        pad = bucket - rows
        if pad == 0:
            return x
        if isinstance(x, dict):
            return {k: np.concatenate(
                [v, np.zeros((pad,) + v.shape[1:], v.dtype)], axis=0)
                for k, v in x.items()}
        return np.concatenate(
            [x, np.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)

    @staticmethod
    def _concat(parts: List[ArrayOrDict]) -> ArrayOrDict:
        if isinstance(parts[0], dict):
            return {k: np.concatenate([p[k] for p in parts], axis=0)
                    for k in parts[0]}
        return np.concatenate(parts, axis=0)

    def _execute(self, batch: List[_Request]) -> None:
        now = time.monotonic()
        live: List[_Request] = []
        for r in batch:
            if r.deadline is not None and now > r.deadline:
                r.error = DeadlineExceeded(
                    f"deadline passed {now - r.deadline:.3f}s before "
                    f"execution (queued {now - r.enqueued_at:.3f}s)")
                self.metrics.record_rejection("deadline")
                r.event.set()
            else:
                live.append(r)
        if not live:
            return
        try:
            rows = sum(r.rows for r in live)
            bucket = self._bucket_for(rows)
            x = self._pad(self._concat([r.x for r in live]), rows, bucket)
            t0 = time.monotonic()
            out = self._forward(x)
            if isinstance(out, (list, tuple)):
                out = [np.asarray(o) for o in out]
            else:
                out = np.asarray(out)
            t1 = time.monotonic()
            self.metrics.record_batch(rows, bucket, t1 - t0)
            ofs = 0
            for r in live:
                sl = slice(ofs, ofs + r.rows)
                r.result = ([o[sl] for o in out]
                            if isinstance(out, list) else out[sl])
                ofs += r.rows
                self.metrics.record_response(t1 - r.enqueued_at)
        except BaseException as e:
            for r in live:
                r.error = e
                self.metrics.record_rejection("error")
        finally:
            for r in live:
                r.event.set()

    def _run(self) -> None:
        while True:
            if self._shutdown:
                break
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                try:
                    first = self._queue.get(timeout=0.05)
                except queue.Empty:
                    if self._draining:
                        break
                    continue
            self._execute(self._collect(first))

    # ---------------------------------------------------------- shutdown
    def shutdown(self, drain: bool = True, timeout_s: float = 5.0) -> None:
        """Stop the worker. ``drain=True`` (default) serves whatever is
        already queued first; either way every still-pending request gets an
        explicit :class:`ServingShutdown` error — no caller hangs (seed bug:
        queued-but-unbatched requests never got ``event.set()``)."""
        with self._submit_lock:
            if drain:
                self._draining = True
            else:
                self._shutdown = True
        self._worker.join(timeout=timeout_s)
        with self._submit_lock:
            self._shutdown = True
            self._draining = True
        leftovers = []
        if self._carry is not None:
            leftovers.append(self._carry)
            self._carry = None
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for r in leftovers:
            r.error = ServingShutdown(
                "batcher shut down before this request was served")
            r.event.set()
