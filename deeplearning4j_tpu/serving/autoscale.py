"""SLO-feedback autoscaler: the telemetry loop closed (ISSUE 10 tentpole;
ROADMAP item 2 — "grow or shrink ReplicaPool replicas, and fleet size,
from the router's burn-rate signals").

PR 9's :class:`~deeplearning4j_tpu.serving.slo.SLOMonitor` computes
per-model multi-window burn rates fleet-wide at the router; PR 10's
``serving/capacity.py`` accounts what a scaling decision would spend.
:class:`SLOAutoscaler` is the control loop that makes both pay their way:
a thread at the router that, each tick, reads the burn rates and the
capacity headroom and drives two levers —

- **replica resize**: ``POST /v1/models/<name>/replicas`` against the
  worker currently ranked #1 for the model (the one its traffic
  concentrates on under rendezvous routing) — the worker grows/shrinks
  its :class:`~deeplearning4j_tpu.serving.replica.ReplicaPool` at
  runtime, each new replica warmed from the live
  :class:`~deeplearning4j_tpu.serving.manifest.WarmupManifest` BEFORE it
  takes traffic (zero on-traffic compiles);
- **fleet resize**: :meth:`FleetSupervisor.add_worker` /
  :meth:`~deeplearning4j_tpu.serving.fleet.FleetSupervisor.remove_worker`
  with a cloned :class:`WorkerSpec` — the router's existing ``/readyz``
  prober readmits the newcomer, nothing new to integrate.

Control policy (``docs/observability.md`` has the runbook):

- **Multi-window burn**: scale-up requires the FAST window's burn rate
  over ``up_burn`` (trigger) AND the SLOW window's over ``confirm_burn``
  (confirm) — a one-second blip cannot trigger, a sustained breach
  cannot hide. The burn signal is ``max(availability_burn,
  latency_burn)``.
- **Hysteresis + cooldown**: scale-down requires BOTH windows under
  ``down_burn`` (strictly below the trigger band) and fires only after
  ``down_cooldown_s`` since the last action; scale-ups are themselves
  rate-limited by ``up_cooldown_s``. The gap between ``up_burn`` and
  ``down_burn`` plus the cooldowns make flapping impossible: there is no
  burn trajectory that alternates actions faster than the cooldowns.
- **Capacity guard**: before any scale-up the aggregated capacity
  accounting is consulted — a new replica costs the model's measured
  ``param_bytes + model_state_bytes`` on the target worker, and the
  guard refuses to scale past the memory budget
  (``memory_budget_bytes``, else the worker's measured device budget
  where the backend reports one). The refusal is itself a logged,
  explained decision.
- **Unwind discipline**: the autoscaler only scales down what IT scaled
  up (a per-model action stack), so a hand-provisioned baseline is never
  eroded below ``min_replicas``/the launch fleet.
- **Out of HBM != out of compute** (ISSUE 11): a capacity-guard refusal
  means the worker is memory-bound — more replicas there cannot help.
  The controller first REBALANCES PLACEMENT: page the model in on a
  worker with eviction-free headroom (``POST /v1/models/<m>/residency``;
  the router's placement-aware ranking then shifts the traffic), and
  only spawns a worker — new HBM — when no placed worker has room. The
  decision log's ``capacity.bound`` field (``"hbm"`` vs ``"compute"``)
  records which wall was hit.

Every decision — acted, refused by the guard, or deferred by a cooldown —
is an explained, traced event: a bounded log records the triggering
burn-rate snapshot (both windows), the capacity headroom consulted, the
action and its outcome, and the active trace id (decision spans carry the
``autoscale`` flag so tail sampling always keeps them). ``GET
/v1/autoscaler`` on the router serves the log, so "why did the fleet grow
at 14:32" is answerable after the fact.
"""

from __future__ import annotations

import dataclasses
import http.client
import itertools
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.runtime import journal, trace

logger = logging.getLogger(__name__)

__all__ = ["AutoscalerConfig", "SLOAutoscaler", "forecast_rate"]

#: per-process controller counter: each SLOAutoscaler's journal events
#: carry a unique controller id so two controllers in one process (unit
#: tests, drills) read back exactly their own decisions
_CONTROLLER_IDS = itertools.count(1)


@dataclasses.dataclass
class AutoscalerConfig:
    """Control-policy knobs (defaults are the production shape; drills
    and tests shrink the windows/cooldowns via the injectable clock)."""

    tick_s: float = 1.0
    #: burn-rate windows (must be members of the monitor's ``windows_s``)
    fast_window_s: int = 60
    slow_window_s: int = 300
    #: fast window triggers at this burn rate...
    up_burn: float = 2.0
    #: ...and the slow window must confirm at this one
    confirm_burn: float = 1.0
    #: both windows must sit under this (strictly below the trigger band:
    #: the hysteresis gap) before a scale-down is considered
    down_burn: float = 0.5
    up_cooldown_s: float = 30.0
    down_cooldown_s: float = 120.0
    #: a fast window with fewer requests than this cannot trigger (burn
    #: over 3 requests is noise, not an outage)
    min_requests: int = 8
    min_replicas: int = 1
    max_replicas: int = 8
    #: fleet lever: ``None`` disables worker scaling entirely
    max_workers: Optional[int] = None
    #: capacity guard budget; ``None`` falls back to the target worker's
    #: measured device budget (backends that report one), else unbounded
    memory_budget_bytes: Optional[int] = None
    #: when a scale-up is refused for MEMORY (out of HBM, not compute —
    #: ISSUE 11), first try to rebalance placement: page the model in on
    #: a worker with eviction-free headroom instead of spawning a worker
    rebalance_enabled: bool = True
    #: decision-log ring size
    log_capacity: int = 256
    #: socket budget for the replica lever (warmup compiles take seconds)
    lever_timeout_s: float = 120.0
    # ---- predictive scaling (ISSUE 12): act BEFORE the burn-rate breach
    #: master switch for the pre-breach signals below
    predictive: bool = True
    #: look-ahead horizon of the SLO-ring traffic forecast
    forecast_horizon_s: float = 15.0
    #: per-second history the trend is fitted over (clamped to the SLO
    #: monitor's ring horizon)
    forecast_window_s: int = 30
    #: forecast demand must exceed the estimated serveable rate by this
    #: factor before a pre-scale fires
    forecast_margin: float = 1.2
    #: admission-queue pressure (depth / limit) that predicts a breach —
    #: the queue is already measured for the ``Retry-After`` drain hints
    queue_pressure: float = 0.5
    #: scheduled pre-scaling windows: ``{"model": name-or-"*",
    #: "start_ts", "end_ts"}`` (unix seconds) — capacity ahead of a
    #: KNOWN traffic event, no signal required
    schedules: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def forecast_rate(counts: List[float], horizon_s: float
                  ) -> "tuple[float, float, float]":
    """Least-squares linear trend over per-second request counts ->
    ``(predicted_rate_at_now+horizon, slope_per_s, rate_now)``.
    ``rate_now`` is the mean of the newest quarter of the window, so one
    noisy second does not define "now"; fewer than 4 samples fit no
    trend (slope 0). Pure function — the forecast unit tests drive it
    with hand-built ramps."""
    n = len(counts)
    if n == 0:
        return 0.0, 0.0, 0.0
    tail = max(1, n // 4)
    rate_now = sum(counts[-tail:]) / tail
    if n < 4:
        return rate_now, 0.0, rate_now
    mean_x = (n - 1) / 2.0
    mean_y = sum(counts) / n
    sxx = sum((i - mean_x) ** 2 for i in range(n))
    sxy = sum((i - mean_x) * (counts[i] - mean_y) for i in range(n))
    slope = sxy / sxx if sxx else 0.0
    pred = mean_y + slope * ((n - 1) + float(horizon_s) - mean_x)
    return max(0.0, pred), slope, rate_now


class _ModelState:
    """Per-model controller state."""

    __slots__ = ("actions", "last_action_ts", "suppressed")

    def __init__(self):
        self.actions: List[tuple] = []   # stack of ("replica"|"worker", wid)
        self.last_action_ts = float("-inf")
        self.suppressed: Optional[str] = None  # dedup key for skip logging

    @property
    def level(self) -> int:
        return len(self.actions)


class SLOAutoscaler:
    """Closed-loop controller over a
    :class:`~deeplearning4j_tpu.serving.router.FleetRouter`'s burn-rate
    and capacity telemetry.

    ``router`` supplies the SLO monitor (fleet-wide by construction),
    the worker ranking, and the capacity aggregation; ``fleet`` (a
    :class:`~deeplearning4j_tpu.serving.fleet.FleetSupervisor`) enables
    the worker lever when given. ``replica_lever`` / ``worker_lever``
    are injectable for unit tests — production uses the HTTP scale
    endpoint and the supervisor.

    :meth:`start` runs :meth:`tick` on a daemon control thread named
    ``slo-autoscaler`` (covered by the conftest thread-leak guard);
    :meth:`tick` is public so drills can step the loop deterministically.
    """

    def __init__(self, router, fleet=None,
                 config: Optional[AutoscalerConfig] = None,
                 models: Optional[List[str]] = None,
                 capacity_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 replica_lever: Optional[Callable] = None,
                 worker_lever: Optional[Callable] = None,
                 residency_lever: Optional[Callable] = None,
                 election=None,
                 now_fn: Callable[[], float] = time.monotonic):
        self.router = router
        self.fleet = fleet
        #: lease election (ISSUE 12): with one attached, this controller
        #: only ACTS while it holds the lease — otherwise every decision
        #: is shadow-computed and logged with role="follower". None keeps
        #: the single-controller behaviour (always leader).
        self.election = election
        if election is not None and election.on_transition is None:
            election.on_transition = self._record_election
        self.config = config or AutoscalerConfig()
        cfg = self.config
        # coerce the window knobs: SLOMonitor.report keys windows as
        # f"{int(w)}s", so a float 60.0 here would pass the membership
        # check below (60.0 == 60) yet miss every lookup ("60.0s") and
        # silently disable the controller
        cfg.fast_window_s = int(cfg.fast_window_s)
        cfg.slow_window_s = int(cfg.slow_window_s)
        windows = getattr(router.slo, "windows_s", ())
        for w in (cfg.fast_window_s, cfg.slow_window_s):
            if w not in windows:
                raise ValueError(
                    f"autoscaler window {w}s is not one of the SLO "
                    f"monitor's windows {windows} — the burn rates it "
                    f"would read do not exist")
        if cfg.fast_window_s >= cfg.slow_window_s:
            raise ValueError(
                f"fast window ({cfg.fast_window_s}s) must be shorter than "
                f"the slow confirm window ({cfg.slow_window_s}s)")
        if cfg.down_burn >= min(cfg.up_burn, cfg.confirm_burn):
            raise ValueError(
                f"down_burn ({cfg.down_burn}) must sit strictly below the "
                f"trigger band (up {cfg.up_burn} / confirm "
                f"{cfg.confirm_burn}) — no hysteresis gap means flapping")
        self._models_filter = set(models) if models else None
        self._capacity_fn = (capacity_fn if capacity_fn is not None
                             else getattr(router, "fleet_capacity",
                                          lambda: {}))
        self._replica_lever = replica_lever or self._http_scale_replicas
        self._worker_lever = worker_lever
        self._residency_lever = residency_lever or self._http_page_in
        self._now = now_fn
        self._states: Dict[str, _ModelState] = {}
        self._lock = threading.Lock()  # guards: _states
        # decision records live in the EVENT JOURNAL (ISSUE 15): _log
        # emits one `autoscale.decision` event per entry and report()
        # reads them back — one source, no double bookkeeping. The
        # controller id scopes the read-back to THIS controller.
        self._cid = (f"{getattr(router, 'router_id', 'router')}"
                     f"#{next(_CONTROLLER_IDS)}")
        if not journal.enabled():
            # the decision log LIVES in the journal now: with it disabled
            # every decision still acts but /v1/autoscaler shows nothing
            logger.warning(
                "event journal disabled (DL4J_TPU_JOURNAL=0): autoscaler "
                "decisions will act but /v1/autoscaler's decision log "
                "will be empty")
        self.ticks = 0
        self._tick_capacity: Optional[Dict[str, Any]] = None
        self._worker_seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- levers
    def _http_scale_replicas(self, view, model: str, delta: int, span):
        """Production replica lever: the worker's scale endpoint, driven
        with a RELATIVE ``delta`` — the worker applies it to its own live
        replica count under its resize lock, so a stale (or missing)
        capacity scrape can never turn a scale-up into an absolute
        scale-down. The decision span's ids ride the headers so the
        worker-side ``worker.scale_replicas`` span joins the decision's
        trace."""
        host, port = view.address.rsplit(":", 1)
        conn = http.client.HTTPConnection(
            host, int(port), timeout=self.config.lever_timeout_s)
        headers = {"Content-Type": "application/json"}
        if span.recording:
            headers["X-Trace-Id"] = span.trace_id
            headers["X-Parent-Span-Id"] = span.span_id
        try:
            # the floor rides the request: the worker clamps the delta
            # target against its LIVE count, so min_replicas holds even
            # when the capacity scrape is stale
            conn.request("POST", f"/v1/models/{model}/replicas",
                         json.dumps({"delta": int(delta),
                                     "floor": int(self.config.min_replicas)}
                                    ).encode(), headers)
            resp = conn.getresponse()
            data = resp.read()
            try:
                body = json.loads(data.decode())
            except Exception:
                body = {"raw": data.decode(errors="replace")[:200]}
            return resp.status == 200, body
        finally:
            conn.close()

    def _http_page_in(self, view, model: str, span) -> tuple:
        """Placement-rebalance lever (ISSUE 11): page ``model`` in on
        ``view`` via the worker's residency endpoint — the worker with
        eviction-free headroom becomes a RESIDENT home for the model, and
        the router's placement-aware ranking shifts its traffic there
        before any worker is spawned."""
        host, port = view.address.rsplit(":", 1)
        conn = http.client.HTTPConnection(
            host, int(port), timeout=self.config.lever_timeout_s)
        headers = {"Content-Type": "application/json"}
        if span.recording:
            headers["X-Trace-Id"] = span.trace_id
            headers["X-Parent-Span-Id"] = span.span_id
        try:
            conn.request("POST", f"/v1/models/{model}/residency",
                         json.dumps({"state": "resident"}).encode(), headers)
            resp = conn.getresponse()
            data = resp.read()
            try:
                body = json.loads(data.decode())
            except Exception:
                body = {"raw": data.decode(errors="replace")[:200]}
            return resp.status == 200, body
        finally:
            conn.close()

    def _spawn_worker(self, base_view, span) -> tuple:
        """Production worker lever (scale-up): clone the busiest worker's
        spec under a fresh id and spawn it; the router's prober readmits
        it through ``/readyz``."""
        self._worker_seq += 1
        new_id = f"{base_view.worker_id}-as{self._worker_seq}"
        spec = self.fleet.clone_spec(base_view.worker_id, new_id)
        self.fleet.add_worker(spec)
        return True, {"worker_id": new_id}

    # --------------------------------------------------------- leadership
    def _role(self) -> str:
        """``"leader"`` when this controller may act (no election wired,
        or the lease is ours); ``"follower"`` otherwise. A lock-free read
        — safe on the tick path even while a chaos drill hangs the
        leader's heartbeat."""
        if self.election is None:
            return "leader"
        return "leader" if self.election.is_leader() else "follower"

    def _record_election(self, event: Dict[str, Any]) -> None:
        """Fold a lease transition into the decision log (ISSUE 12):
        every election — acquired, takeover, lost, released — is an
        explained ``/v1/autoscaler`` entry next to the decisions it
        gates. The entry is an ``autoscale.election`` JOURNAL event
        (ISSUE 15) — the black box and the ``/v1/autoscaler`` view read
        the same record."""
        entry = {
            "ts": event.get("ts", time.time()),
            "tick": self.ticks,
            "model": None,
            "action": f"election_{event.get('role')}",
            "ok": True,
            "role": event.get("role"),
            "worker": None,
            "level": None,
            "burn": None,
            "capacity": None,
            "trace_id": None,
            "detail": {k: event.get(k)
                       for k in ("holder", "seq", "reason", "id")},
        }
        journal.emit("autoscale.election", controller=self._cid,
                     entry=entry)
        logger.info("autoscaler election: %s -> %s (%s)",
                    event.get("id"), event.get("role"),
                    event.get("reason"))

    # ---------------------------------------------------------- burn math
    @staticmethod
    def _burn(window: Dict[str, Any]) -> float:
        return max(float(window.get("availability_burn_rate", 0.0)),
                   float(window.get("latency_burn_rate", 0.0)))

    def _capacity(self) -> Dict[str, Any]:
        """The tick's capacity snapshot, scraped lazily (only ticks that
        reach a decision pay for it) and at most once per tick."""
        if self._tick_capacity is None:
            try:
                self._tick_capacity = self._capacity_fn()
            except Exception:
                logger.exception("autoscaler capacity scrape failed")
                self._tick_capacity = {}
        return self._tick_capacity

    def _guard(self, model: str, view) -> tuple:
        """Capacity guard: can the target worker afford one more replica
        of ``model``? Returns ``(ok, headroom_record)`` — the record is
        logged with the decision either way, so every decision shows the
        headroom it consulted."""
        cfg = self.config
        cap = self._capacity()
        worker = (cap.get("workers") or {}).get(
            view.worker_id if view is not None else None, {})
        entry = (worker.get("models") or {}).get(model, {})
        needed = int(entry.get("param_bytes", 0)) + \
            int(entry.get("model_state_bytes", 0))
        in_use = int((worker.get("totals") or {}).get("device_bytes", 0))
        budget = cfg.memory_budget_bytes
        if budget is None:
            budget = (worker.get("process") or {}).get("device_budget_bytes")
        headroom = None if budget is None else int(budget) - in_use
        record = {
            "budget_bytes": budget,
            "device_bytes_in_use": in_use,
            "headroom_bytes": headroom,
            "replica_cost_bytes": needed,
            "replicas": entry.get("replicas"),
            "utilization": entry.get("utilization"),
            "queue": entry.get("queue"),
        }
        ok = headroom is None or headroom >= needed
        # classify the binding constraint (ISSUE 11): a guard refusal is
        # "out of HBM" — the fix is placement (evict/page elsewhere) or a
        # NEW worker's memory, never more replicas on this one; an
        # approved scale-up is "out of compute" (burn with memory to
        # spare). The decision log carries it so "why did the fleet grow"
        # distinguishes the two resource walls.
        record["bound"] = "compute" if ok else "hbm"
        return ok, record

    # ------------------------------------------------------------ the loop
    def tick(self) -> List[Dict[str, Any]]:
        """One control iteration over every tracked model; returns the
        decisions logged this tick (empty on a quiet tick)."""
        self.ticks += 1
        self._tick_capacity = None
        if self.election is not None:
            # one election step per tick (plus the election's own
            # heartbeat thread): a controller that just lost its lease
            # must learn so BEFORE deciding, not a heartbeat later
            self.election.ensure()
        try:
            report = self.router.slo.report(
                models=(sorted(self._models_filter)
                        if self._models_filter else None))
        except Exception:
            logger.exception("autoscaler SLO read failed")
            return []
        out = []
        for model in sorted(report):
            d = self._decide(model, report[model])
            if d is not None:
                out.append(d)
        return out

    def _decide(self, model: str, rep: Dict[str, Any]
                ) -> Optional[Dict[str, Any]]:
        cfg = self.config
        fast = rep.get("windows", {}).get(f"{cfg.fast_window_s}s")
        slow = rep.get("windows", {}).get(f"{cfg.slow_window_s}s")
        if fast is None or slow is None:
            return None
        burn_fast, burn_slow = self._burn(fast), self._burn(slow)
        with self._lock:  # report() iterates _states under the same lock
            st = self._states.setdefault(model, _ModelState())
        now = self._now()
        burn = {"fast_window_s": cfg.fast_window_s, "fast": fast,
                "slow_window_s": cfg.slow_window_s, "slow": slow,
                "burn_fast": burn_fast, "burn_slow": burn_slow}
        breach = (int(fast.get("requests", 0)) >= cfg.min_requests
                  and burn_fast >= cfg.up_burn
                  and burn_slow >= cfg.confirm_burn)
        recovered = (burn_fast <= cfg.down_burn
                     and burn_slow <= cfg.down_burn)
        if breach:
            if now - st.last_action_ts < cfg.up_cooldown_s:
                return self._log_suppressed(model, st, "up_cooldown", burn)
            return self._act(model, st, burn, direction=+1)
        if cfg.predictive:
            # pre-breach signals (ISSUE 12): queue pressure, traffic
            # forecast, scheduled windows. Checked BEFORE the recovery
            # branch — a 10x ramp can still read "recovered" on burn
            # alone, and scaling DOWN into a ramp is the one wrong move.
            sig = self._predictive_signal(model, fast)
            if sig is not None:
                if now - st.last_action_ts < cfg.up_cooldown_s:
                    return self._log_suppressed(model, st, "up_cooldown",
                                                burn)
                burn = {**burn, "predictive": sig}
                return self._act(model, st, burn, direction=+1,
                                 predictive=sig)
        if recovered and st.level > 0:
            if now - st.last_action_ts < cfg.down_cooldown_s:
                return self._log_suppressed(model, st, "down_cooldown", burn)
            return self._act(model, st, burn, direction=-1)
        st.suppressed = None
        return None

    def _predictive_signal(self, model: str, fast: Dict[str, Any]
                           ) -> Optional[Dict[str, Any]]:
        """The pre-breach scale-up signal (ISSUE 12), or ``None``:

        - **schedule** — a configured pre-scaling window covers now
          (checked first: planned capacity needs no live traffic at all);
        - **queue** — admission-queue pressure ``depth/limit`` at or over
          ``queue_pressure`` (the same queue the ``Retry-After`` drain
          hints are computed from): requests are already waiting, the
          latency burn just has not caught up yet;
        - **forecast** — the short-horizon linear trend over the SLO
          ring's per-second request counts exceeds the estimated
          serveable rate by ``forecast_margin``: the 10x step is scaled
          for BEFORE the burn-rate breach it would otherwise become.

        The forecast comparison is a *blend* (ISSUE 20 satellite), not
        two independent triggers: the serveable rate averages the
        utilization-implied estimate (current rate / busy fraction)
        with the fleet's admission-queue drain-rate capacity
        (``drain_rate_rps`` — summed ``1000 / drain_ms_per_request``
        across workers), and the predicted demand folds the standing
        queue backlog in as ``depth / horizon`` — a ramp arriving on
        top of an already-backed-up queue trips the signal earlier than
        either series would alone. When only one serveable estimate is
        available (near-idle fleet, or no drain sample yet) the blend
        degrades to that one; with neither there is no honest capacity
        estimate and no forecast signal."""
        cfg = self.config
        now_wall = time.time()
        for sched in (cfg.schedules or []):
            try:
                if sched.get("model") not in (model, "*", None):
                    continue
                if (float(sched["start_ts"]) <= now_wall
                        <= float(sched["end_ts"])):
                    return {"signal": "schedule",
                            "start_ts": float(sched["start_ts"]),
                            "end_ts": float(sched["end_ts"])}
            except (TypeError, KeyError, ValueError):
                continue  # malformed schedule entry: skip, never crash
        if int(fast.get("requests", 0)) < cfg.min_requests:
            return None  # too little traffic to predict from
        # the fleet-aggregated capacity schema (FleetRouter
        # .fleet_capacity): flattened queue_depth / queue_headroom /
        # busy_fraction summed across workers
        entry = (self._capacity().get("models") or {}).get(model) or {}
        try:
            depth = int(entry.get("queue_depth", 0))
            headroom = int(entry.get("queue_headroom_requests", 0))
        except (TypeError, ValueError):
            depth = headroom = 0
        limit = depth + headroom
        if limit > 0 and depth / limit >= cfg.queue_pressure:
            return {"signal": "queue", "queue_depth": depth,
                    "queue_limit": limit}
        recent = getattr(self.router.slo, "recent_counts", None)
        if recent is None:
            return None
        counts = recent(model, cfg.forecast_window_s)
        pred, slope, rate_now = forecast_rate(counts,
                                              cfg.forecast_horizon_s)
        if slope <= 0 or rate_now <= 0:
            return None
        try:
            busy = float(entry.get("busy_fraction", 0.0))
        except (TypeError, ValueError):
            busy = 0.0
        try:
            drain_rps = float(entry.get("drain_rate_rps", 0.0))
        except (TypeError, ValueError):
            drain_rps = 0.0
        util_serveable = (rate_now / min(1.0, max(busy, 1e-6))
                          if busy > 0.01 else None)
        if util_serveable is None and drain_rps <= 0:
            return None  # near-idle, no drain sample: nothing honest
        if util_serveable is not None and drain_rps > 0:
            serveable = (util_serveable + drain_rps) / 2.0
        elif util_serveable is not None:
            serveable = util_serveable
        else:
            serveable = drain_rps
        # the standing backlog must ALSO clear within the horizon: fold
        # it into demand so ramp-onto-backlog trips earlier than the
        # traffic trend alone would
        horizon = max(cfg.forecast_horizon_s, 1e-6)
        backlog_rate = depth / horizon if depth > 0 else 0.0
        demand = pred + backlog_rate
        if demand > serveable * cfg.forecast_margin:
            out = {"signal": "forecast",
                   "rate_now": round(rate_now, 3),
                   "predicted_rate": round(pred, 3),
                   "serveable_rate": round(serveable, 3),
                   "slope_per_s": round(slope, 4),
                   "horizon_s": cfg.forecast_horizon_s}
            if backlog_rate > 0:
                out["backlog_rate"] = round(backlog_rate, 3)
                out["predicted_demand"] = round(demand, 3)
            if drain_rps > 0:
                out["drain_rate_rps"] = round(drain_rps, 3)
            return out
        return None

    # ----------------------------------------------------------- decisions
    def _target_view(self, model: str):
        now = time.monotonic()
        for view in self.router.ranked_workers(model):
            if view.admittable(now):
                return view
        return None

    def _act(self, model: str, st: _ModelState, burn: Dict[str, Any],
             direction: int, predictive: Optional[Dict[str, Any]] = None
             ) -> Optional[Dict[str, Any]]:
        cfg = self.config
        # the decision span: flagged so tail sampling ALWAYS keeps it —
        # an autoscaling event is never a "healthy trace to drop"
        sp = (trace.server_span("autoscaler.decision")
              if trace.enabled() else trace.NOOP)
        with sp:
            if sp.recording:
                sp.flag("autoscale")
                sp.set("model", model)
                sp.set("direction", direction)
                if predictive is not None:
                    sp.set("predictive", predictive.get("signal"))
            if self._role() == "follower":
                # shadow decision (ISSUE 12): computed like the leader's,
                # logged with role="follower", levers NEVER touched — the
                # exactly-once guarantee two live routers depend on
                return self._log(
                    model, st,
                    ("follower_scale_up" if direction > 0
                     else "follower_scale_down"),
                    burn, None, span=sp, ok=False, role="follower",
                    detail="shadow decision: not the lease holder",
                    dedup=True)
            view = self._target_view(model)
            if view is None:
                return self._log_suppressed(model, st, "no_healthy_worker",
                                            burn, span=sp)
            ok_guard, headroom = self._guard(model, view)
            if direction > 0:
                return self._scale_up(model, st, burn, view, ok_guard,
                                      headroom, sp, predictive=predictive)
            return self._scale_down(model, st, burn, view, headroom, sp)

    def _fenced(self, model, st, burn, headroom, sp):
        """Last-instant lease re-check before a lever fires: a leader
        that lost its lease mid-decision must NOT act (the new leader may
        already be acting on the same signal). ``election.verify()``
        reads the lease FILE directly — lock-free, so it stays truthful
        even while the election's own heartbeat thread is hung inside a
        step (the ``serving.autoscale.lease`` chaos drill), which is
        exactly when the cached role lies. An arbitrary scheduler pause
        between this check and the lever remains possible (full fencing
        would need the seq token validated at the worker); the check
        closes every observable lost-lease window. Returns the
        suppression entry when fencing triggers, else ``None``."""
        if self.election is not None and not self.election.verify():
            return self._log(model, st, "suppressed_lost_lease", burn,
                             headroom, span=sp, ok=False, role="follower",
                             detail="lease lost between decision and "
                                    "lever; deferring to the new leader")
        return None

    def _scale_up(self, model, st, burn, view, ok_guard, headroom, sp,
                  predictive=None):
        cfg = self.config
        if headroom.get("replicas") is None:
            # no capacity entry for the target worker (scrape timed out
            # or the worker just joined): a controller must not act
            # blind — defer, explained, until the ledger is back
            return self._log(model, st, "suppressed_no_capacity", burn,
                             headroom, span=sp, ok=False,
                             detail=f"no capacity data for worker "
                                    f"{view.worker_id!r} this tick",
                             dedup=True)
        if not ok_guard:
            # OUT OF HBM, not out of compute (ISSUE 11): more replicas on
            # this worker cannot help. Rebalance placement first — page
            # the model in on a worker with eviction-free headroom, so
            # the router's placement ranking moves the traffic — and only
            # spawn a worker (new HBM) when no such worker exists.
            if cfg.rebalance_enabled:
                target = self._rebalance_target(model, view)
                if target is not None:
                    fenced = self._fenced(model, st, burn, headroom, sp)
                    if fenced is not None:
                        return fenced
                    try:
                        ok, detail = self._residency_lever(target, model, sp)
                    except Exception as e:
                        ok, detail = False, {"error": repr(e)}
                    if ok:
                        st.last_action_ts = self._now()
                        st.suppressed = None
                    return self._log(model, st, "rebalance_page_in", burn,
                                     headroom, span=sp, ok=ok,
                                     worker=target.worker_id, detail=detail)
            entry = self._worker_entry(model, st, burn, view, headroom, sp,
                                       reason="out of HBM on every placed "
                                              "worker")
            if entry is not None:
                return entry
            return self._log(model, st, "suppressed_capacity_guard",
                             burn, headroom, span=sp, ok=False,
                             detail="scale-up refused: out of HBM (replica "
                                    "cost exceeds memory headroom) and no "
                                    "rebalance target or worker headroom",
                             dedup=True)
        replicas = int(headroom["replicas"])
        if replicas < cfg.max_replicas:
            fenced = self._fenced(model, st, burn, headroom, sp)
            if fenced is not None:
                return fenced
            try:
                ok, detail = self._replica_lever(view, model, +1, sp)
            except Exception as e:
                ok, detail = False, {"error": repr(e)}
            if ok:
                st.actions.append(("replica", view.worker_id))
                st.last_action_ts = self._now()
                st.suppressed = None
            return self._log(model, st, "scale_up_replica", burn, headroom,
                             span=sp, ok=ok, worker=view.worker_id,
                             detail=detail, predictive=predictive)
        entry = self._worker_entry(model, st, burn, view, headroom, sp,
                                   reason="replicas at max")
        if entry is not None:
            return entry
        return self._log(model, st, "suppressed_at_max", burn, headroom,
                         span=sp, ok=False,
                         detail=f"replicas={replicas} at max_replicas="
                                f"{cfg.max_replicas} and no worker "
                                f"headroom", dedup=True)

    def _worker_entry(self, model, st, burn, view, headroom, sp, reason):
        """The fleet lever (spawn a cloned worker), shared by the
        compute-bound (replicas at max) and HBM-bound (no rebalance
        target) paths; ``None`` when the lever is unavailable."""
        cfg = self.config
        if not (self.fleet is not None and cfg.max_workers is not None
                and len(self.router.workers()) < cfg.max_workers):
            return None
        fenced = self._fenced(model, st, burn, headroom, sp)
        if fenced is not None:
            return fenced
        lever = self._worker_lever or self._spawn_worker
        try:
            ok, detail = lever(view, sp)
        except Exception as e:
            ok, detail = False, {"error": repr(e)}
        if ok:
            st.actions.append(("worker", detail.get("worker_id")))
            st.last_action_ts = self._now()
            st.suppressed = None
        if isinstance(detail, dict):
            detail = {**detail, "reason": reason}
        return self._log(model, st, "scale_up_worker", burn, headroom,
                         span=sp, ok=ok, worker=view.worker_id,
                         detail=detail)

    def _rebalance_target(self, model, view):
        """The best placement-rebalance target: an admittable worker
        (other than ``view``) that knows ``model`` COLD and has the most
        eviction-free headroom covering the model's bytes. ``None`` when
        no worker qualifies — or when the model is already RESIDENT
        elsewhere (routing, not this controller, should shift the
        traffic)."""
        cap = self._capacity()
        live = self.router.workers()
        now = time.monotonic()
        best = None
        best_headroom = None
        for wid, payload in sorted((cap.get("workers") or {}).items()):
            if wid == view.worker_id:
                continue
            w = live.get(wid)
            if w is None or not w.admittable(now):
                continue
            res = payload.get("residency")
            if not isinstance(res, dict):
                continue
            entry = (res.get("models") or {}).get(model)
            if not isinstance(entry, dict):
                continue
            if entry.get("state") == "resident":
                return None  # already placed elsewhere; routing handles it
            budget = res.get("hbm_budget_bytes")
            headroom = (float("inf") if budget is None else
                        int(budget) - int(res.get("resident_bytes", 0)))
            if headroom < int(entry.get("bytes", 0)):
                continue  # paging in here would evict someone else
            if best_headroom is None or headroom > best_headroom:
                best, best_headroom = w, headroom
        return best

    def _scale_down(self, model, st, burn, view, headroom, sp):
        fenced = self._fenced(model, st, burn, headroom, sp)
        if fenced is not None:
            return fenced
        kind, wid = st.actions[-1]
        if kind == "worker":
            try:
                self.fleet.remove_worker(wid)
                ok, detail = True, {"worker_id": wid}
            except Exception as e:
                ok, detail = False, {"error": repr(e)}
            if ok:
                st.actions.pop()
                st.last_action_ts = self._now()
                st.suppressed = None
            return self._log(model, st, "scale_down_worker", burn, headroom,
                             span=sp, ok=ok, worker=wid, detail=detail)
        # replica unwind: prefer the worker we scaled, fall back to the
        # current target if it has since been replaced. The lever is a
        # RELATIVE -1 applied to the worker's live count (floored at 1
        # by the endpoint itself), so a stale scrape cannot collapse a
        # multi-replica worker to the floor in one step.
        target = self.router.workers().get(wid) or view
        try:
            ok, detail = self._replica_lever(target, model, -1, sp)
        except Exception as e:
            ok, detail = False, {"error": repr(e)}
        if ok:
            st.actions.pop()
            st.last_action_ts = self._now()
            st.suppressed = None
        return self._log(model, st, "scale_down_replica", burn, headroom,
                         span=sp, ok=ok, worker=target.worker_id,
                         detail=detail)

    # ------------------------------------------------------------- logging
    def _log_suppressed(self, model, st, reason, burn, span=trace.NOOP):
        """A deferred decision is logged ONCE per streak (the first tick
        it would have acted), not once per tick — the log explains, it
        does not spam."""
        if st.suppressed == reason:
            return None
        st.suppressed = reason
        return self._log(model, st, f"suppressed_{reason}", burn, None,
                         span=span, ok=False,
                         detail=f"deferred by {reason}")

    def _log(self, model, st, action, burn, headroom, span=trace.NOOP,
             ok=True, worker=None, detail=None, dedup=False, role=None,
             predictive=None):
        if dedup:
            if st.suppressed == action:
                return None
            st.suppressed = action
        entry = {
            "ts": time.time(),
            "tick": self.ticks,
            "model": model,
            "action": action,
            "ok": bool(ok),
            "role": role or self._role(),
            "worker": worker,
            "level": st.level,
            "burn": burn,
            "capacity": headroom,
            "trace_id": span.trace_id,
            "detail": detail,
        }
        if predictive is not None:
            entry["predictive"] = predictive
        if span.recording:
            span.set("action", action)
            span.set("ok", bool(ok))
            span.event("decision", action=action, ok=bool(ok))
        # the decision IS a journal event (ISSUE 15): /v1/autoscaler and
        # the black box read the same record — no double bookkeeping
        journal.emit("autoscale.decision", _trace_id=span.trace_id,
                     controller=self._cid, entry=entry)
        logger.info("autoscaler: %s %s (ok=%s) burn_fast=%.2f "
                    "burn_slow=%.2f level=%d", action, model, ok,
                    burn["burn_fast"], burn["burn_slow"], st.level)
        return entry

    def decision_log(self) -> List[Dict[str, Any]]:
        """THIS controller's decision + election entries, oldest first,
        read back from the event journal (ISSUE 15: the journal is the
        single source; the deque it replaced is gone). Bounded by the
        configured ``log_capacity`` — and by the journal ring itself: a
        flood of OTHER event types can overwrite old decisions (the
        tradeoff of one shared black box; ``report()`` surfaces the
        ring's ``overwritten_total`` so a shortened log is explainable,
        and ``journal.enable(capacity=...)`` sizes the ring for long
        incidents)."""
        entries = [
            e["attrs"]["entry"]
            for e in journal.events(
                types=("autoscale.decision", "autoscale.election"))
            if e.get("attrs", {}).get("controller") == self._cid
            and isinstance(e.get("attrs", {}).get("entry"), dict)]
        cap = int(self.config.log_capacity)
        return entries[max(0, len(entries) - cap):]

    def report(self) -> Dict[str, Any]:
        """The ``/v1/autoscaler`` payload: config, controller state, and
        the bounded decision log (oldest first, journal-backed)."""
        now = self._now()
        decisions = self.decision_log()
        with self._lock:
            # the states snapshot under the lock: the control thread
            # setdefault()s new models mid-tick, and a dict resize
            # during an unlocked iteration would 500 the scrape
            states = {m: (s.level, s.last_action_ts)
                      for m, s in sorted(self._states.items())}
        out = {
            "config": self.config.to_dict(),
            "ticks": self.ticks,
            "running": self._thread is not None,
            # the log's provenance (ISSUE 15): journal-backed, with the
            # ring counters that explain a shortened history
            "decision_log_source": ("journal" if journal.enabled()
                                    else "journal_disabled"),
            "journal": journal.counters(),
            "role": self._role(),
            "models": {m: {"level": level,
                           "last_action_age_s": (
                               None if last_ts == float("-inf")
                               else round(now - last_ts, 3))}
                       for m, (level, last_ts) in states.items()},
            "decisions": decisions,
        }
        if self.election is not None:
            # the election record (ISSUE 12): who holds the lease, how
            # fresh its heartbeat is, and every transition this
            # controller observed
            try:
                out["election"] = self.election.snapshot()
            except Exception:
                out["election"] = {"error": "election snapshot failed"}
        return out

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "SLOAutoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="slo-autoscaler")
        self._thread.start()
        attach = getattr(self.router, "attach_autoscaler", None)
        if attach is not None:
            attach(self)
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.config.tick_s):
            try:
                self.tick()
            except Exception:
                logger.exception("autoscaler tick failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0,
                                          self.config.lever_timeout_s))
            self._thread = None

    def __enter__(self) -> "SLOAutoscaler":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()
