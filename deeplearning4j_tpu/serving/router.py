"""Fleet router: the fault-domain boundary in front of N ``ModelServer``
worker processes (ISSUE 7 tentpole; the reference's multi-JVM serving /
parameter-server routing tier, ``docs/fleet_serving.md``).

One ``ModelServer`` process as the whole fleet means any worker crash,
stall, or deploy is a full outage. :class:`FleetRouter` is the same
stdlib ``ThreadingHTTPServer`` idiom as ``serving/server.py``, one level
up — it owns no models, only a **health view** of the workers behind it:

- **Health**: an active prober polls every worker's ``/readyz``; passive
  signals (connection failures, 5xx, shed responses) feed a per-worker
  :class:`~deeplearning4j_tpu.serving.resilience.CircuitBreaker` — a
  byzantine worker (one that keeps erroring) is isolated without taking
  the fleet down, and re-admitted through the breaker's half-open probe.
- **Consistent routing**: workers are ranked per model by rendezvous
  (highest-random-weight) hashing, so one model's traffic concentrates on
  one healthy worker (warm caches, stable batching) and spreads only when
  health changes — no routing table to rebalance.
- **Hedging**: a request still unanswered after a p99-derived delay is
  *hedged* against the next-ranked worker; the first completed response
  wins bit-identically, the loser's completion is discarded and counted
  (``router_hedges_discarded_total``) — duplicate side effects are
  suppressed by the shared ``X-Request-Id``, and the hedge carries the
  REMAINING deadline (``X-Deadline-Ms``), never a fresh one.
- **Failover**: a worker dying mid-request (connection reset, SIGKILL
  under the chaos drill) fails the *attempt*, not the request — the
  router retries the untried next-ranked worker within the original
  deadline. A request is never silently dropped: it ends served, or with
  an explicit 503/504.
- **Load signals**: a worker's 503 ``Overloaded`` carries its
  ``Retry-After-Ms`` drain estimate; the router routes around that
  worker until the window passes instead of hammering it
  (``router_shed_skips_total`` counts the avoided forwards).
- **Zero-downtime rolling deploys**: :meth:`FleetRouter.rolling_deploy`
  drains one worker (stop new routing, wait in-flight), has the
  :class:`~deeplearning4j_tpu.serving.fleet.FleetSupervisor` relaunch it
  on the new archive (warmup-manifest prewarmed, persistent compile
  cache shared), re-admits it only after ``/readyz``, then moves to the
  next — client traffic sees a mix of old and new versions and zero
  errors, and readmitted workers compile nothing on live traffic.

Chaos points: ``serving.router.forward`` fires before every forward
attempt, ``serving.router.hedge`` as a hedge launches (catalogue in
``runtime/chaos.py``; drills in ``tests/test_router.py`` and
``bench.py --fleet``).

This module deliberately imports no jax — the router is pure host code
and can front workers from any process.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import threading
import time
import uuid
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from deeplearning4j_tpu.runtime import chaos, journal, trace
from deeplearning4j_tpu.serving import wire
from deeplearning4j_tpu.serving.metrics import LatencyHistogram
from deeplearning4j_tpu.serving.resilience import CircuitBreaker, CircuitState
from deeplearning4j_tpu.serving.slo import SLOMonitor

logger = logging.getLogger(__name__)

#: statuses that END a request at the client (retrying cannot change them:
#: 400/404 are the client's problem, 504 means the shared deadline — which
#: every attempt inherits via X-Deadline-Ms — has truly expired).
_TERMINAL = frozenset({200, 400, 404, 504})

#: headers the router must NOT copy from a worker response onto its own:
#: the router's HTTP layer emits its own framing (Content-Length) and
#: identity (Date, Server), and hop-by-hop headers never cross a proxy —
#: re-sending the worker's copy would emit duplicates that strict clients
#: and intermediaries reject as a protocol error.
_HOP_BY_HOP = frozenset({"content-length", "date", "server", "connection",
                         "transfer-encoding", "keep-alive"})


class StaticFleet:
    """The simplest thing a :class:`FleetRouter` can front: a fixed
    ``{worker_id: "host:port"}`` map (in-process workers, tests). The
    supervisor-backed twin is
    :class:`~deeplearning4j_tpu.serving.fleet.FleetSupervisor`."""

    def __init__(self, endpoints: Dict[str, str]):
        self._endpoints = dict(endpoints)

    def endpoints(self) -> Dict[str, str]:
        return dict(self._endpoints)


class RouterMetrics:
    """Router-level counters/gauges (thread-safe), rendered on the
    router's ``/metrics`` and surfaced through
    ``runtime.profiler.router_stats()``."""

    def __init__(self):
        # guards: requests_total, responses_total, errors_total, forwards_total, hedges_total, hedge_wins_total, hedges_discarded_total, failovers_total, shed_skips_total, deploys_total, session_requests_total, session_migrations_total, shadow_mirrors_total, shadow_diverged_total, canary_requests_total, rollbacks_total, wire_requests_total, wire_downgrades_total, shm_hops_total, shm_fallbacks_total, request_latency, worker_requests
        self._lock = threading.Lock()
        self.requests_total = 0
        self.session_requests_total = 0    # session-tier requests routed
        self.session_migrations_total = 0  # session repins (failover/drain)
        self.responses_total = 0        # 2xx returned to clients
        self.errors_total = 0           # non-2xx returned to clients
        self.forwards_total = 0         # attempts launched (incl. hedges)
        self.hedges_total = 0           # hedge attempts launched
        self.hedge_wins_total = 0       # winner was the hedge attempt
        self.hedges_discarded_total = 0  # duplicate completions suppressed
        self.failovers_total = 0        # failed attempts retried elsewhere
        self.shed_skips_total = 0       # workers skipped inside Retry-After
        self.deploys_total = 0
        self.shadow_mirrors_total = 0   # requests mirrored to a candidate
        self.shadow_diverged_total = 0  # mirrors that disagreed/corrupted
        self.canary_requests_total = 0  # requests pinned to a canary
        self.rollbacks_total = 0        # gated deploys auto-rolled back
        self.wire_requests_total = 0    # binary-framed client requests
        self.wire_downgrades_total = 0  # 415s that flipped a worker to JSON
        self.shm_hops_total = 0         # forwards whose payload rode shm
        self.shm_fallbacks_total = 0    # shm hops resent inline
        self.request_latency = LatencyHistogram()
        self.worker_requests: Dict[str, int] = {}

    def record(self, counter: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, counter, getattr(self, counter) + n)

    def record_response(self, status: int, latency_s: float) -> None:
        with self._lock:
            if 200 <= status < 300:
                self.responses_total += 1
                self.request_latency.observe(latency_s)
            else:
                self.errors_total += 1

    def record_forward(self, worker_id: str) -> None:
        with self._lock:
            self.forwards_total += 1
            self.worker_requests[worker_id] = \
                self.worker_requests.get(worker_id, 0) + 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "errors_total": self.errors_total,
                "forwards_total": self.forwards_total,
                "hedges_total": self.hedges_total,
                "hedge_wins_total": self.hedge_wins_total,
                "hedges_discarded_total": self.hedges_discarded_total,
                "failovers_total": self.failovers_total,
                "shed_skips_total": self.shed_skips_total,
                "deploys_total": self.deploys_total,
                "session_requests_total": self.session_requests_total,
                "session_migrations_total": self.session_migrations_total,
                "shadow_mirrors_total": self.shadow_mirrors_total,
                "shadow_diverged_total": self.shadow_diverged_total,
                "canary_requests_total": self.canary_requests_total,
                "rollbacks_total": self.rollbacks_total,
                "wire_requests_total": self.wire_requests_total,
                "wire_downgrades_total": self.wire_downgrades_total,
                "shm_hops_total": self.shm_hops_total,
                "shm_fallbacks_total": self.shm_fallbacks_total,
                "latency_p50_s": self.request_latency.percentile(50),
                "latency_p99_s": self.request_latency.percentile(99),
                "worker_requests": dict(self.worker_requests),
            }

    def render_prometheus(self, workers: Dict[str, "WorkerView"]) -> str:
        s = self.snapshot()
        lines = [
            "# TYPE router_requests_total counter",
            f"router_requests_total {s['requests_total']}",
            f"router_responses_total {s['responses_total']}",
            f"router_errors_total {s['errors_total']}",
            f"router_forwards_total {s['forwards_total']}",
            f"router_hedges_total {s['hedges_total']}",
            f"router_hedge_wins_total {s['hedge_wins_total']}",
            f"router_hedges_discarded_total {s['hedges_discarded_total']}",
            f"router_failovers_total {s['failovers_total']}",
            f"router_shed_skips_total {s['shed_skips_total']}",
            f"router_deploys_total {s['deploys_total']}",
            f"router_session_requests_total {s['session_requests_total']}",
            f"router_session_migrations_total "
            f"{s['session_migrations_total']}",
            f"router_shadow_mirrors_total {s['shadow_mirrors_total']}",
            f"router_shadow_diverged_total {s['shadow_diverged_total']}",
            f"router_canary_requests_total {s['canary_requests_total']}",
            f"router_rollbacks_total {s['rollbacks_total']}",
            f"router_wire_requests_total {s['wire_requests_total']}",
            f"router_wire_downgrades_total {s['wire_downgrades_total']}",
            f"router_shm_hops_total {s['shm_hops_total']}",
            f"router_shm_fallbacks_total {s['shm_fallbacks_total']}",
            f'router_latency_seconds{{quantile="0.5"}} '
            f"{s['latency_p50_s']}",
            f'router_latency_seconds{{quantile="0.99"}} '
            f"{s['latency_p99_s']}",
        ]
        for wid, n in sorted(s["worker_requests"].items()):
            lines.append(f'router_worker_requests_total{{worker="{wid}"}} '
                         f"{n}")
        now = time.monotonic()
        for wid, view in sorted(workers.items()):
            lines.append(f'router_worker_healthy{{worker="{wid}"}} '
                         f"{int(view.admittable(now))}")
            lines.append(f'router_worker_inflight{{worker="{wid}"}} '
                         f"{view.inflight}")
        return "\n".join(lines) + "\n"


class WorkerView:
    """The router's per-worker health view: one address, an active-probe
    readiness bit, a passive-signal breaker, a shed window from the
    worker's own ``Retry-After`` hints, and the in-flight count drains
    wait on."""

    def __init__(self, worker_id: str, address: str,
                 breaker: Optional[CircuitBreaker] = None):
        self.worker_id = worker_id
        self.address = address
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, window_s=30.0, reset_timeout_s=2.0)
        # breaker transitions land in the event journal under this scope
        # (ISSUE 15): the watchdog's breaker-flap rule counts them
        self.breaker.journal_scope = f"worker:{worker_id}"
        #: flips True after the one-shot /v1/metricsz warm-start scrape
        #: (ISSUE 12): a fresh view adopts the worker's OWN breaker
        #: verdict instead of re-learning a failure streak from traffic
        self.breaker_warmed = False
        self.ready = False
        self.draining = False
        #: a gated deploy's CANDIDATE (ISSUE 17): excluded from normal
        #: admission — it receives only the traffic the active
        #: DeliveryController assigns it (shadow mirrors, canary picks)
        self.candidate = False
        self.shed_until = 0.0           # monotonic end of the shed window
        #: negotiated transport (ISSUE 18): None = untried, True = the
        #: worker accepted a binary frame, False = it answered 415 and
        #: every later forward transcodes to JSON.  A restarted worker
        #: gets a fresh view, so it re-negotiates.
        self.wire_ok: Optional[bool] = None
        self.inflight = 0
        self.requests_total = 0
        self.failures_total = 0
        self.latency = LatencyHistogram()
        # guards: inflight, requests_total, failures_total, latency
        self._lock = threading.Lock()

    def admittable(self, now: Optional[float] = None) -> bool:
        """May new requests be routed here right now? (Half-open breaker
        probes are consumed at attempt time, not here.)"""
        now = time.monotonic() if now is None else now
        return (self.ready and not self.draining and not self.candidate
                and now >= self.shed_until
                and self.breaker.state is not CircuitState.OPEN)

    def shedding(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        return now < self.shed_until

    def begin(self) -> None:
        with self._lock:
            self.inflight += 1
            self.requests_total += 1

    def done(self, ok: bool, latency_s: Optional[float] = None) -> None:
        with self._lock:
            self.inflight -= 1
            if not ok:
                self.failures_total += 1
            elif latency_s is not None:
                self.latency.observe(latency_s)

    def snapshot(self) -> Dict[str, Any]:
        now = time.monotonic()
        # counters read under the lock so a scrape sees one consistent
        # view (inflight can never exceed requests_total in a snapshot)
        with self._lock:
            inflight = self.inflight
            requests_total = self.requests_total
            failures_total = self.failures_total
        return {"address": self.address, "ready": self.ready,
                "draining": self.draining, "candidate": self.candidate,
                "admittable": self.admittable(now),
                "shedding_ms": max(0.0, (self.shed_until - now) * 1000.0),
                "inflight": inflight,
                "requests_total": requests_total,
                "failures_total": failures_total,
                "breaker": self.breaker.snapshot()}


class _BreakerDeclined(Exception):
    """The worker's half-open breaker had no probe slot left at forward
    time — a retryable skip, not a worker fault."""


class _Attempt:
    """One forward attempt's outcome."""

    __slots__ = ("view", "hedged", "status", "headers", "data", "error",
                 "span")

    def __init__(self, view: WorkerView, hedged: bool):
        self.view = view
        self.hedged = hedged
        self.status: Optional[int] = None
        self.headers: Dict[str, str] = {}
        self.data: bytes = b""
        self.error: Optional[BaseException] = None
        self.span = trace.NOOP  # the attempt's router.attempt span

    @property
    def terminal(self) -> bool:
        return self.status in _TERMINAL

    @property
    def retryable(self) -> bool:
        """A failed attempt another worker might still serve: connection
        faults, 5xx, and shed (503) responses."""
        return not self.terminal


def _crc(data: bytes) -> str:
    return f"{zlib.crc32(data) & 0xffffffff:08x}"


class _Race:
    """Exactly-one-winner coordination for a primary attempt and its
    hedge. The first TERMINAL completion claims the request (its response
    goes to the client bit-for-bit); any completion after that is a
    duplicate — discarded and counted, the side-effect suppression the
    shared request id exists for."""

    def __init__(self, metrics: RouterMetrics):
        self._metrics = metrics
        self._cv = threading.Condition()  # guards: winner, launched, finished, failures
        self.winner: Optional[_Attempt] = None
        self.launched = 0
        self.finished = 0
        self.failures: List[_Attempt] = []

    def register_launch(self) -> None:
        with self._cv:
            self.launched += 1

    def complete(self, attempt: _Attempt) -> None:
        with self._cv:
            self.finished += 1
            if attempt.terminal:
                if self.winner is None:
                    self.winner = attempt
                    if attempt.span.recording:
                        # the winner's bit-identity: a body checksum any
                        # late duplicate can be compared against
                        attempt.span.set("winner", True)
                        attempt.span.set("body_crc32", _crc(attempt.data))
                    if attempt.hedged:
                        self._metrics.record("hedge_wins_total")
                else:
                    self._metrics.record("hedges_discarded_total")
                    if attempt.span.recording:
                        attempt.span.set("discarded", True)
                        attempt.span.set("body_crc32", _crc(attempt.data))
            else:
                if self.winner is not None and self.launched > 1:
                    # the loser of a hedge race that ended in failure is
                    # still a duplicate completion to account for
                    self._metrics.record("hedges_discarded_total")
                    if attempt.span.recording:
                        attempt.span.set("discarded", True)
                self.failures.append(attempt)
            self._cv.notify_all()

    def wait(self, timeout: Optional[float]) -> bool:
        """Wait until a winner exists or every launched attempt finished.
        Returns True when settled."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self.winner is None and self.finished < self.launched:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True


class FleetRouter:
    """HTTP front end over a worker fleet.

    ``fleet`` is anything with ``endpoints() -> {worker_id: "host:port"}``
    (:class:`StaticFleet` or a
    :class:`~deeplearning4j_tpu.serving.fleet.FleetSupervisor`; rolling
    deploys additionally need the supervisor's ``restart_worker``).

    Hedging: a request unanswered after ``hedge_delay_s()`` — the
    measured p99 forward latency times ``hedge_factor``, clamped to
    ``[hedge_min_ms, hedge_max_ms]``, or ``hedge_initial_ms`` until
    ``hedge_warm_count`` responses have been observed — is duplicated to
    the next-ranked worker. ``hedge_enabled=False`` disables it (the
    unhedged arm of ``bench.py --fleet``).
    """

    def __init__(self, fleet, default_timeout_ms: Optional[float] = None,
                 hedge_enabled: bool = True, hedge_factor: float = 1.0,
                 hedge_min_ms: float = 10.0, hedge_max_ms: float = 1000.0,
                 hedge_initial_ms: float = 75.0, hedge_warm_count: int = 32,
                 probe_interval_s: float = 0.25,
                 probe_timeout_s: float = 1.0,
                 connect_timeout_s: float = 2.0,
                 no_deadline_timeout_s: float = 60.0,
                 residency_refresh_s: float = 1.0,
                 slo: Optional[SLOMonitor] = None,
                 router_id: str = "router",
                 shm_enabled: Optional[bool] = None,
                 shm_min_bytes: int = wire.SHM_MIN_BYTES):
        self._fleet = fleet
        #: identity in a replicated router tier (ISSUE 12): the key this
        #: router registers under in the shared config's router roster,
        #: and what peers report it as
        self.router_id = str(router_id)
        #: shared FleetConfig (attach_config): peer discovery + the
        #: idempotency ledger config-versioned levers claim through
        self._config = None
        self._peer_view: Dict[str, Dict[str, Any]] = {}
        self.default_timeout_ms = default_timeout_ms
        self.hedge_enabled = bool(hedge_enabled)
        self.hedge_factor = float(hedge_factor)
        self.hedge_min_ms = float(hedge_min_ms)
        self.hedge_max_ms = float(hedge_max_ms)
        self.hedge_initial_ms = float(hedge_initial_ms)
        self.hedge_warm_count = int(hedge_warm_count)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.no_deadline_timeout_s = float(no_deadline_timeout_s)
        # keep-alive connection pool (ISSUE 18): EVERY router HTTP —
        # forwards, probes, scrapes, sessions, shadows — reuses sockets
        # instead of paying TCP setup per hop; invalidated per endpoint
        # on connection faults, breaker opens, and worker restarts
        self.pool = wire.ConnectionPool()
        # colocated shared-memory fast path (ISSUE 18): large binary
        # payloads to 127.0.0.1 workers ride a shm segment instead of
        # the loopback socket; DL4J_TPU_NO_SHM (or shm_enabled=False)
        # forces the socket path
        if shm_enabled is None:
            shm_enabled = not os.environ.get("DL4J_TPU_NO_SHM")
        self.shm_enabled = bool(shm_enabled)
        self.shm_min_bytes = int(shm_min_bytes)
        self.metrics = RouterMetrics()
        # fleet-wide SLO attainment + burn rates (ISSUE 9): the router
        # sees every client request whichever worker serves it, so ITS
        # monitor is the per-model fleet-wide signal the SLOAutoscaler
        # consumes (rendered on /metrics next to the worker aggregation;
        # injectable so drills can run short burn windows)
        self.slo = slo or SLOMonitor()
        # the attached SLOAutoscaler (ISSUE 10), serving /v1/autoscaler
        self.autoscaler = None
        # the attached AnomalyWatchdog (ISSUE 15): ticked by the probe
        # loop, rendered on /metrics, snapshotted into the debug bundle
        self.watchdog = None
        # placement view (ISSUE 11): {worker_id: {"models": {name: state},
        # "headroom_bytes": int|None}} refreshed by the probe loop from
        # the workers' /v1/capacity residency sections — what makes
        # ranked_workers() route cold-model traffic to the worker that
        # has the model RESIDENT (or the most eviction-free headroom)
        self.residency_refresh_s = float(residency_refresh_s)
        self._residency_view: Dict[str, Dict[str, Any]] = {}
        self._last_residency_refresh = 0.0
        self._views: Dict[str, WorkerView] = {}
        self._views_lock = threading.Lock()  # guards: _views
        # session affinity (ISSUE 16): {f"{model}/{sid}": worker_id}.
        # Local cache of the pins published through the shared config —
        # another router (or this one after a restart) adopts a pin from
        # cfg["sessions"] instead of re-deriving it, so a session never
        # ping-pongs between workers across router failover.
        self._session_pins: Dict[str, str] = {}
        self._pins_lock = threading.Lock()  # guards: _session_pins
        # gated delivery (ISSUE 17): the active per-deploy controller the
        # request path consults (shadow mirrors, canary picks), plus the
        # last finished drill's report for /v1/delivery
        self._delivery = None
        self._last_delivery_report: Optional[Dict[str, Any]] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._prober: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.port: Optional[int] = None
        self._sync_views()

    # ------------------------------------------------------------ fleet view
    def _sync_views(self) -> None:
        """Reconcile worker views with the fleet's current endpoints: new
        workers appear STARTING (not ready until probed), a restarted
        worker (same id, new address) gets a fresh breaker and must
        re-prove readiness, removed workers disappear."""
        endpoints = self._fleet.endpoints()
        with self._views_lock:
            for wid, addr in endpoints.items():
                view = self._views.get(wid)
                if view is None:
                    self._views[wid] = WorkerView(wid, addr)
                elif view.address != addr:
                    fresh = WorkerView(wid, addr)
                    fresh.draining = view.draining
                    fresh.candidate = view.candidate
                    self._views[wid] = fresh
                    # pooled keep-alives to the old address are dead
                    # weight at best, a stranger at worst
                    self.pool.invalidate(view.address)
            for wid in list(self._views):
                if wid not in endpoints:
                    self.pool.invalidate(self._views[wid].address)
                    del self._views[wid]

    def workers(self) -> Dict[str, WorkerView]:
        with self._views_lock:
            return dict(self._views)

    def ranked_workers(self, model: str) -> List[WorkerView]:
        """Every worker view, ranked for ``model``: rendezvous
        (highest-random-weight) hashing — deterministic, so one model's
        traffic concentrates on the same healthy worker across requests
        (and across router restarts) — refined by PLACEMENT when the
        fleet pages models (ISSUE 11): workers with the model RESIDENT
        rank first (rendezvous order among them), then cold workers by
        eviction-free headroom (budget minus resident bytes; an
        unbudgeted worker counts as infinite — loading there evicts
        nothing). Fleets whose residency view never mentions ``model``
        keep pure rendezvous order, so non-paging deployments are
        untouched."""
        def score(wid: str) -> int:
            h = hashlib.blake2b(f"{model}|{wid}".encode(), digest_size=8)
            return int.from_bytes(h.digest(), "big")
        views = self.workers()
        order = sorted(views, key=score, reverse=True)
        rv = getattr(self, "_residency_view", None)
        if rv and any(model in (rv.get(w) or {}).get("models", {})
                      for w in order):
            def placement(wid: str):
                info = rv.get(wid) or {}
                models = info.get("models", {})
                if models.get(model) == "resident":
                    return (0, 0.0)
                if model not in models:
                    # this worker does not KNOW the model (or reported no
                    # residency at all): it would 404 — terminal, no
                    # failover — so it must rank LAST, never outrank a
                    # cold-registered worker
                    return (2, 0.0)
                h = info.get("headroom_bytes")
                return (1, -(float("inf") if h is None else float(h)))
            order = sorted(order, key=placement)  # stable: rendezvous ties
        return [views[wid] for wid in order]

    def _refresh_residency(self) -> None:
        """Refresh the placement view from every ready worker's
        ``/v1/capacity`` residency section (throttled to
        ``residency_refresh_s`` by the probe loop; stale entries for
        vanished workers drop out). Workers without a residency section
        (stubs, older payloads) simply stay out of the view — ranking
        falls back to pure rendezvous."""
        view: Dict[str, Dict[str, Any]] = {}
        try:
            for wid, payload in self._scrape_workers("/v1/capacity").items():
                res = payload.get("residency")
                if not isinstance(res, dict):
                    continue
                models = {str(m): d.get("state")
                          for m, d in (res.get("models") or {}).items()
                          if isinstance(d, dict)}
                budget = res.get("hbm_budget_bytes")
                headroom = (None if budget is None else
                            int(budget) - int(res.get("resident_bytes", 0)))
                view[wid] = {"models": models, "headroom_bytes": headroom}
        except Exception:
            logger.exception("residency refresh failed; keeping last view")
            return
        self._residency_view = view

    def hedge_delay_s(self) -> float:
        """The p99-derived hedge trigger (see class docstring)."""
        hist = self.metrics.request_latency
        if hist.count < self.hedge_warm_count:
            ms = self.hedge_initial_ms
        else:
            ms = hist.percentile(99) * 1000.0 * self.hedge_factor
        return min(self.hedge_max_ms, max(self.hedge_min_ms, ms)) / 1000.0

    # ------------------------------------------------------------- probing
    def _probe_worker(self, view: WorkerView) -> bool:
        status, _, _ = self._http(view.address, "GET", "/readyz",
                                  timeout=self.probe_timeout_s)
        return status == 200

    def _probe_cycle(self) -> None:
        self._sync_views()
        for view in self.workers().values():
            was_ready = view.ready
            try:
                view.ready = self._probe_worker(view)
            except Exception:
                view.ready = False
            if view.ready != was_ready:
                # readiness TRANSITIONS are journal events (ISSUE 15):
                # kill -> unready and restart -> readmit are the
                # bookends of the incident drill's timeline. Each gets
                # its own flagged span so the event is trace-linked even
                # though no request context exists on the probe thread.
                sp = (trace.server_span("router.worker_transition")
                      if trace.enabled() else trace.NOOP)
                with sp:
                    if sp.recording:
                        sp.flag("fleet")
                        sp.set("worker", view.worker_id)
                        sp.set("ready", view.ready)
                    if view.ready:
                        journal.emit("router.worker_ready",
                                     worker=view.worker_id,
                                     address=view.address)
                    else:
                        journal.emit("router.worker_unready",
                                     worker=view.worker_id,
                                     address=view.address)
            if view.ready and not view.breaker_warmed:
                self._warm_start_breaker(view)
        wd = self.watchdog
        if wd is not None:
            wd.maybe_tick()
        now = time.monotonic()
        if now - self._last_residency_refresh >= self.residency_refresh_s:
            self._last_residency_refresh = now
            self._refresh_residency()
            self._refresh_peers()

    def _warm_start_breaker(self, view: WorkerView) -> None:
        """Warm-start a fresh :class:`WorkerView`'s passive breaker from
        the worker's own ``/v1/metricsz`` breaker states (ISSUE 12): a
        freshly (re)started router builds every breaker CLOSED, so
        without this it would happily route traffic into a worker its
        peers had already isolated — re-learning the failure streak at
        the clients' expense. One scrape decides: any model breaker the
        worker itself reports OPEN/HALF_OPEN pre-opens the router's
        passive breaker; re-admission then runs through the breaker's
        normal half-open probe, exactly as if this router had observed
        the failures first-hand."""
        try:
            status, _, data = self._http(view.address, "GET",
                                         "/v1/metricsz",
                                         timeout=self.probe_timeout_s)
        except Exception:
            return  # unreachable: the prober already handles that
        view.breaker_warmed = True
        if status != 200:
            return  # a stub without metricsz: nothing to adopt
        try:
            payload = json.loads(data.decode())
            states = {str((m.get("breaker") or {}).get("state"))
                      for m in (payload.get("models") or {}).values()
                      if isinstance(m, dict)}
        except Exception:
            return  # malformed payload: warm with no verdict to adopt
        if states & {"OPEN", "HALF_OPEN"}:
            view.breaker.warm_open()
            logger.warning(
                "worker %s reports open breaker(s) %s; warm-starting its "
                "passive breaker OPEN", view.worker_id,
                sorted(states & {"OPEN", "HALF_OPEN"}))

    # ----------------------------------------------------- config + peering
    def attach_config(self, config) -> None:
        """Attach the shared :class:`~deeplearning4j_tpu.serving
        .control_plane.FleetConfig` (ISSUE 12): enables peer discovery
        (``/v1/peers``, the ``/readyz`` peering section) and makes
        :meth:`rolling_deploy` idempotent + config-versioned through the
        applied-action ledger, so two live routers can never double-apply
        one deploy."""
        self._config = config

    def peers(self) -> Dict[str, str]:
        """Peer routers from the shared config's roster (everyone but
        us); empty without an attached config."""
        if self._config is None:
            return {}
        try:
            routers = self._config.routers()
        except Exception:
            return {}
        return {rid: addr for rid, addr in sorted(routers.items())
                if rid != self.router_id}

    def _refresh_peers(self) -> None:
        """Router-to-router ``/readyz`` peering: probe each peer on the
        residency-refresh cadence so any live router can answer "which of
        my peers is up" — the observability a supervisor or client needs
        to see a dead router from the survivors. Probes run CONCURRENTLY
        through the same fan-out helper as the worker scrapes: one hung
        peer (exactly what peering exists to surface) must not stall the
        probe loop that feeds the data path's own worker health."""
        peers = self.peers()
        view: Dict[str, Dict[str, Any]] = {
            rid: {"address": addr, "ready": False}
            for rid, addr in peers.items()}

        class _Peer:
            def __init__(self, rid, addr):
                self.worker_id = rid
                self.address = addr

        def probe(p):
            status, _, _ = self._http(p.address, "GET", "/readyz",
                                      timeout=self.probe_timeout_s)
            return status == 200

        results = self._fanout(
            probe, [_Peer(r, a) for r, a in peers.items()],
            self.probe_timeout_s)
        for rid, ok in results.items():
            view[rid]["ready"] = bool(ok)
        self._peer_view = view

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self._probe_cycle()
            except Exception:
                logger.exception("router probe cycle failed")

    # --------------------------------------------------------------- http
    def _http(self, address: str, method: str, path: str,
              body: Optional[bytes] = None,
              headers: Optional[Dict[str, str]] = None,
              timeout: Optional[float] = None
              ) -> Tuple[int, Dict[str, str], bytes]:
        # pooled keep-alive (ISSUE 18): a stale idle connection is
        # retried once on a fresh one inside the pool; a FRESH
        # connection's failure propagates exactly as the old
        # one-connection-per-request path did, so breaker evidence is
        # unchanged
        return self.pool.request(
            address, method, path, body=body, headers=headers,
            timeout=self.connect_timeout_s if timeout is None else timeout)

    # ------------------------------------------------------------ routing
    @staticmethod
    def _shed_window_ms(headers: Dict[str, str], body: bytes) -> float:
        h = {k.lower(): v for k, v in headers.items()}
        if "retry-after-ms" in h:
            try:
                return float(h["retry-after-ms"])
            except ValueError:
                pass
        if "retry-after" in h:
            try:
                return float(h["retry-after"]) * 1000.0
            except ValueError:
                pass
        try:
            ms = json.loads(body.decode()).get("retry_after_ms")
            return float(ms) if ms is not None else 0.0
        except Exception:
            return 0.0

    def _classify(self, attempt: _Attempt) -> None:
        """Feed an attempt's outcome into the worker's health view."""
        view = attempt.view
        if isinstance(attempt.error, _BreakerDeclined):
            return  # nothing was sent; neither fault nor success
        if attempt.error is not None:
            # connection-level fault: the worker is likely gone — fail
            # fast for subsequent requests; the prober re-admits it.
            # The readiness flip is journaled HERE (not only in the
            # probe loop): the data path usually sees a dead worker
            # first, and the probe's transition detector would then
            # find ready already False and record nothing (ISSUE 15).
            if view.ready:
                journal.emit("router.worker_unready",
                             worker=view.worker_id, address=view.address,
                             reason="connect_fault")
            view.ready = False
            view.breaker.record_failure()
            # any pooled keep-alive to this address shares whatever
            # killed this one — drop them all
            self.pool.invalidate(view.address)
            return
        if attempt.status == 503:
            # a load/health signal, not a worker fault: honor the shed
            # hint (Overloaded) or wait for the probe (circuit_open)
            window_ms = self._shed_window_ms(attempt.headers, attempt.data)
            if window_ms > 0:
                view.shed_until = max(view.shed_until,
                                      time.monotonic() + window_ms / 1000.0)
                journal.emit("router.shed_window", worker=view.worker_id,
                             window_ms=round(window_ms, 1))
            view.breaker.record_discard()
            return
        if attempt.status is not None and attempt.status >= 500:
            view.breaker.record_failure()
            if view.breaker.state is CircuitState.OPEN:
                # breaker open = stop talking to this worker; parked
                # keep-alives would outlive the verdict otherwise
                self.pool.invalidate(view.address)
            return
        view.breaker.record_success()

    @staticmethod
    def _error_reason(data: bytes) -> Optional[str]:
        try:
            return json.loads(data.decode()).get("reason")
        except Exception:
            return None

    def _send_attempt(self, view: WorkerView, name: str, body: bytes,
                      headers: Dict[str, str], timeout: Optional[float],
                      is_wire: bool) -> Tuple[int, Dict[str, str], bytes]:
        """One POST to one worker, choosing the transport: the colocated
        shared-memory fast path for large binary payloads (transparent
        inline resend on any shm trouble), else the pooled socket."""
        path = f"/v1/models/{name}/predict"
        if (is_wire and self.shm_enabled
                and view.address.startswith("127.0.0.1:")
                and len(body) >= self.shm_min_bytes):
            seg = None
            try:
                shm_body, seg = wire.frame_to_shm(
                    body, min_bytes=self.shm_min_bytes)
            except Exception:
                seg = None  # can't stage the segment: socket path
            if seg is not None:
                try:
                    status, h, data = self._http(
                        view.address, "POST", path, body=shm_body,
                        headers=headers, timeout=timeout)
                finally:
                    wire.release_shm(seg)
                if (status == 503 and
                        self._error_reason(data) == "wire_protocol_error"):
                    # the worker could not attach/validate the segment
                    # (or chaos rotted the re-framed bytes): resend the
                    # original, already-validated frame inline — the
                    # fast path must never cost an answer
                    self.metrics.record("shm_fallbacks_total")
                    return self._http(view.address, "POST", path,
                                      body=body, headers=headers,
                                      timeout=timeout)
                self.metrics.record("shm_hops_total")
                return status, h, data
        return self._http(view.address, "POST", path, body=body,
                          headers=headers, timeout=timeout)

    def _forward(self, race: _Race, view: WorkerView, name: str,
                 body: bytes, rid: str, deadline: Optional[float],
                 hedged: bool, span=trace.NOOP,
                 ctype: str = "application/json") -> None:
        """One attempt against one worker (runs on its own thread). When
        tracing, ``span`` is the attempt's ``router.attempt`` child span
        of the request's root — created by the CALLER before this thread
        launches, so the root can never finalize its trace while an
        attempt span is still unborn. Its span id rides
        ``X-Parent-Span-Id`` to the worker, whose ``worker.predict`` span
        parents to it, which is what lets the router-side aggregation
        merge the two processes' spans into one tree."""
        attempt = _Attempt(view, hedged)
        sp = span
        attempt.span = sp
        view.begin()
        t0 = time.monotonic()
        with sp:
            if sp.recording:
                sp.set("worker", view.worker_id)
                sp.set("hedged", hedged)
            try:
                chaos.inject("serving.router.forward")
                # consume the breaker slot only for attempts actually sent —
                # a half-open probe slot must never leak to a worker that was
                # merely *ranked* (that would wedge the breaker half-open)
                if not view.breaker.allow():
                    raise _BreakerDeclined(view.worker_id)
                remaining = None if deadline is None else deadline - t0
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("deadline expired before forward")
                send_body, send_ctype = body, ctype
                if ctype == wire.CONTENT_TYPE and view.wire_ok is False:
                    # cached negotiation verdict: this worker speaks
                    # JSON only — transcode the (already-validated)
                    # frame; dtype is pinned in the body so the answer
                    # stays bit-identical to the binary path
                    send_body, _tmo = wire.frame_to_json_body(body)
                    send_ctype = "application/json"
                headers = {"Content-Type": send_ctype,
                           "X-Request-Id": rid}
                if sp.recording:
                    headers["X-Trace-Id"] = sp.trace_id
                    headers["X-Parent-Span-Id"] = sp.span_id
                    if hedged:
                        # tail sampling decides per PROCESS: the worker
                        # can't see the router's hedge verdict, so the
                        # hedge attempt carries the flag and the worker's
                        # half of the trace self-keeps
                        headers["X-Trace-Flags"] = "hedged"
                if remaining is not None:
                    headers["X-Deadline-Ms"] = f"{remaining * 1000.0:.1f}"
                self.metrics.record_forward(view.worker_id)
                # a deadline-free request's socket timeout must cover a SLOW
                # predict, not just the connect — 2s here would misread a
                # healthy-but-busy worker as dead and cascade into 503s
                send_timeout = (self.no_deadline_timeout_s
                                if remaining is None else remaining + 0.25)
                status, resp_headers, data = self._send_attempt(
                    view, name, send_body, headers, send_timeout,
                    is_wire=send_ctype == wire.CONTENT_TYPE)
                if status == 415 and send_ctype == wire.CONTENT_TYPE:
                    # mid-stream downgrade: the worker declined binary
                    # RIGHT NOW (force-JSON restart, older build) —
                    # remember the verdict, transcode, and retry the
                    # SAME worker once within this attempt's budget
                    view.wire_ok = False
                    self.metrics.record("wire_downgrades_total")
                    journal.emit("router.wire_downgrade",
                                 worker=view.worker_id)
                    send_body, _tmo = wire.frame_to_json_body(body)
                    headers["Content-Type"] = "application/json"
                    status, resp_headers, data = self._http(
                        view.address, "POST",
                        f"/v1/models/{name}/predict", body=send_body,
                        headers=headers, timeout=send_timeout)
                elif status == 200 and send_ctype == wire.CONTENT_TYPE:
                    view.wire_ok = True
                attempt.status, attempt.headers, attempt.data = \
                    status, resp_headers, data
            except BaseException as e:
                attempt.error = e
            latency = time.monotonic() - t0
            self._classify(attempt)
            view.done(ok=attempt.status == 200,
                      latency_s=latency if attempt.status == 200 else None)
            if sp.recording:
                if attempt.error is not None:
                    sp.set("error", type(attempt.error).__name__)
                    if not isinstance(attempt.error, _BreakerDeclined):
                        sp.flag("fault")  # a failed attempt keeps the trace
                elif attempt.status is not None:
                    sp.set("status", attempt.status)
            # completion INSIDE the span scope: the race marks the winner
            # (bit-identity crc) or a discarded duplicate on this span
            # before it closes
            race.complete(attempt)

    def _eligible(self, ranked: List[WorkerView], tried: set,
                  now: float, span=trace.NOOP) -> List[WorkerView]:
        out = []
        for view in ranked:
            if view.worker_id in tried:
                continue
            if view.shedding(now):
                self.metrics.record("shed_skips_total")
                if span.recording:
                    span.event("shed_skip", worker=view.worker_id,
                               remaining_ms=round(
                                   (view.shed_until - now) * 1e3, 1))
                continue
            if view.admittable(now):
                out.append(view)
        return out

    def _launch(self, race: _Race, view: WorkerView, name: str, body: bytes,
                rid: str, deadline: Optional[float], hedged: bool,
                parent_span=trace.NOOP,
                ctype: str = "application/json") -> None:
        race.register_launch()
        # the attempt span is created HERE, on the handler thread, so the
        # request's trace counts it open before this thread even starts —
        # a root finishing first can then never split the trace in two
        sp = (parent_span.child("router.attempt") if parent_span.recording
              else trace.NOOP)
        threading.Thread(
            target=self._forward,
            args=(race, view, name, body, rid, deadline, hedged, sp, ctype),
            daemon=True, name=f"router-forward-{view.worker_id}").start()

    def _route_predict(self, name: str, raw: bytes, inbound_headers,
                       ctype: str = "application/json"
                       ) -> Tuple[int, Dict[str, str], bytes]:
        """The routing engine: ranked candidates -> hedged race ->
        failover loop until a terminal response or the deadline."""
        self.metrics.record("requests_total")
        t_start = time.monotonic()
        ctype = (ctype or "application/json").split(";")[0].strip()
        if ctype == wire.CONTENT_TYPE:
            # binary client (ISSUE 18): one full decode validates the
            # frame AT THE BOUNDARY (CRC over meta+payload — the router
            # never forwards rot) and yields timeout_ms without the JSON
            # path's full-body parse
            self.metrics.record("wire_requests_total")
            try:
                fr = wire.decode_frame(raw, expect_kind=wire.KIND_REQUEST)
                timeout_ms = fr.meta.get("timeout_ms",
                                         self.default_timeout_ms)
                fr.close()
            except wire.WireProtocolError as e:
                self.metrics.record_response(503, 0.0)
                return 503, {"Content-Type": "application/json"}, \
                    json.dumps({"error": "bad wire frame",
                                "reason": "wire_protocol_error",
                                "detail": str(e)}).encode()
        else:
            try:
                body = json.loads(raw.decode() or "{}")
                timeout_ms = body.get("timeout_ms", self.default_timeout_ms)
            except Exception:
                timeout_ms = self.default_timeout_ms
        inbound = {k: v for k, v in (inbound_headers or {}).items()}
        hdr_deadline = inbound.get("X-Deadline-Ms")
        if hdr_deadline is not None:
            try:
                hd = float(hdr_deadline)
                timeout_ms = hd if timeout_ms is None else min(timeout_ms, hd)
            except ValueError:
                pass
        deadline = (None if timeout_ms is None
                    else t_start + float(timeout_ms) / 1000.0)
        rid = inbound.get("X-Request-Id") or uuid.uuid4().hex
        ranked = self.ranked_workers(name)
        # gated delivery (ISSUE 17): the candidate worker never competes
        # for normal admission — it is pulled out of the ranking and
        # receives exactly the traffic the controller assigns it
        dc = self._delivery
        cand_view = None
        if dc is not None and dc.matches(name):
            cand_view = next((v for v in ranked
                              if v.worker_id == dc.candidate_worker), None)
            ranked = [v for v in ranked
                      if v.worker_id != dc.candidate_worker]
        tried: set = set()
        # the request's root span (ISSUE 9): attempt spans are its
        # children; the tail-sampling decision for the router's part of
        # the trace fires once the root AND every late child (a hedge
        # loser completing after the winner) have finished
        rsp = (trace.server_span("router.request",
                                 trace_id=inbound.get("X-Trace-Id"),
                                 parent_id=inbound.get("X-Parent-Span-Id"))
               if trace.enabled() else trace.NOOP)

        def finish(status: int, headers: Dict[str, str], data: bytes):
            latency_s = time.monotonic() - t_start
            self.metrics.record_response(status, latency_s)
            # a client-sent name must not grow fleet SLO state until it
            # has actually SERVED once (create only on 200) — otherwise
            # junk names during an outage could permanently occupy the
            # monitor's max_models slots and lock real models out of the
            # autoscaler signal; once tracked, failures count in full
            if status != 404:
                self.slo.record(name, ok=status == 200, latency_s=latency_s,
                                create=status == 200)
            headers = {k: v for k, v in headers.items()
                       if k.lower() not in _HOP_BY_HOP}
            headers["X-Request-Id"] = rid
            if rsp.recording:
                rsp.set("status", status)
                if status == 503:
                    rsp.flag("shed")
                elif status == 504:
                    rsp.flag("deadline")
                headers["X-Trace-Id"] = rsp.trace_id
            return status, headers, data

        def reply_json(status: int, obj: Dict[str, Any],
                       extra: Optional[Dict[str, str]] = None):
            return finish(status, {"Content-Type": "application/json",
                                   **(extra or {})},
                          json.dumps(obj).encode())

        with rsp:
            if rsp.recording:
                rsp.set("model", name)
                rsp.set("request_id", rid)
            if (cand_view is not None and cand_view.ready
                    and dc.take_canary()):
                # canary pick (ISSUE 17): one synchronous, NEVER-hedged
                # attempt against the candidate. A 200 serves the client
                # and feeds the canary's own SLO window; any failure is
                # absorbed — the request falls through to the incumbent
                # loop below, so the drill stays client-invisible.
                self.metrics.record("canary_requests_total")
                t_c = time.monotonic()
                race = _Race(self.metrics)
                race.register_launch()
                self._forward(race, cand_view, name, raw, rid, deadline,
                              hedged=False,
                              span=(rsp.child("router.attempt")
                                    if rsp.recording else trace.NOOP),
                              ctype=ctype)
                latency_c = time.monotonic() - t_c
                win = race.winner
                if win is not None and win.status == 200:
                    dc.observe_canary(ok=True, latency_s=latency_c)
                    if rsp.recording:
                        rsp.event("canary", worker=cand_view.worker_id)
                    return finish(win.status, win.headers, win.data)
                dc.observe_canary(ok=False, latency_s=latency_c)
                if rsp.recording:
                    rsp.event("canary_absorbed",
                              worker=cand_view.worker_id,
                              status=None if win is None else win.status)
            while True:
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    return reply_json(504, {
                        "error": "deadline exceeded",
                        "detail": f"request {rid} expired after "
                                  f"{(now - t_start) * 1000:.0f} ms spanning "
                                  f"{len(tried)} worker attempt(s)"})
                candidates = self._eligible(ranked, tried, now, span=rsp)
                if not candidates:
                    # a worker that shed THIS request is in `tried` but its
                    # shed window is still the actionable signal to surface
                    shed = [v for v in ranked if v.shedding(now)]
                    if shed:
                        wait_ms = min((v.shed_until - now) * 1000.0
                                      for v in shed)
                        return reply_json(503, {
                            "error": "overloaded", "reason": "overloaded",
                            "retry_after_ms": round(wait_ms, 1),
                            "detail": "every eligible worker is shedding"},
                            extra={"Retry-After-Ms": f"{wait_ms:.0f}"})
                    return reply_json(503, {
                        "error": "unavailable",
                        "reason": "no_healthy_workers",
                        "detail": f"no healthy worker for model {name!r} "
                                  f"({len(tried)} tried, "
                                  f"{len(ranked)} known)"})
                primary = candidates[0]
                hedge_view = candidates[1] if len(candidates) > 1 else None
                hedge_possible = self.hedge_enabled and hedge_view is not None
                race = _Race(self.metrics)
                if hedge_possible:
                    self._launch(race, primary, name, raw, rid, deadline,
                                 hedged=False, parent_span=rsp, ctype=ctype)
                else:
                    # no hedge can fire: run the attempt on the handler
                    # thread itself instead of paying a thread spawn per
                    # request just to block waiting on it
                    race.register_launch()
                    self._forward(race, primary, name, raw, rid, deadline,
                                  hedged=False,
                                  span=(rsp.child("router.attempt")
                                        if rsp.recording else trace.NOOP),
                                  ctype=ctype)
                tried.add(primary.worker_id)
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if hedge_possible:
                    delay = self.hedge_delay_s()
                    if remaining is not None:
                        delay = min(delay, max(0.0, remaining))
                    settled = race.wait(delay)
                    if not settled and race.winner is None:
                        chaos.inject("serving.router.hedge")
                        self.metrics.record("hedges_total")
                        journal.emit("router.hedge", model=name,
                                     request_id=rid,
                                     worker=hedge_view.worker_id,
                                     primary=primary.worker_id,
                                     delay_ms=round(delay * 1e3, 2))
                        if rsp.recording:
                            rsp.flag("hedged")
                            rsp.event("hedge",
                                      worker=hedge_view.worker_id,
                                      delay_ms=round(delay * 1e3, 2))
                        self._launch(race, hedge_view, name, raw, rid,
                                     deadline, hedged=True, parent_span=rsp,
                                     ctype=ctype)
                        tried.add(hedge_view.worker_id)
                race.wait(None if deadline is None
                          else max(0.0, deadline - time.monotonic()))
                if race.winner is not None:
                    win = race.winner
                    if (cand_view is not None and win.status == 200
                            and cand_view.ready and dc.take_shadow()):
                        # shadow mirror (ISSUE 17): an async duplicate to
                        # the candidate, compared off-path — it is never
                        # returned, never hedged, and never feeds the
                        # incumbents' breakers
                        self._launch_shadow(dc, cand_view, name, raw, rid,
                                            win.data,
                                            time.monotonic() - t_start,
                                            ctype=ctype)
                    return finish(win.status, win.headers, win.data)
                if race.finished < race.launched:
                    # deadline hit with attempts still in flight: their late
                    # completions are counted as discarded duplicates
                    return reply_json(504, {
                        "error": "deadline exceeded",
                        "detail": f"request {rid} expired with "
                                  f"{race.launched - race.finished} "
                                  f"attempt(s) still in flight"})
                # every launched attempt failed retryably -> fail over
                self.metrics.record("failovers_total", len(race.failures))
                journal.emit("router.failover", model=name, request_id=rid,
                             failed_attempts=len(race.failures),
                             workers=[a.view.worker_id
                                      for a in race.failures])
                if rsp.recording:
                    rsp.event("failover", failed_attempts=len(race.failures))

    # ------------------------------------------------------ gated delivery
    def _launch_shadow(self, dc, view: WorkerView, name: str, body: bytes,
                       rid: str, incumbent_body: bytes,
                       incumbent_latency_s: float,
                       ctype: str = "application/json") -> None:
        """Mirror one already-served request to the candidate on a
        detached thread. The comparison (top-1 agreement + latency
        delta) folds into the controller's :class:`ShadowComparator`;
        the response bytes ride through the ``serving.delivery.shadow``
        byte point CRC-framed, so injected wire rot is detected — a
        corrupt comparison counts against promotion, never silently
        passes."""
        self.metrics.record("shadow_mirrors_total")

        def run():
            t0 = time.monotonic()
            status, data, corrupt = 0, b"", False
            incumbent = incumbent_body
            try:
                chaos.inject("serving.delivery.shadow")
                status, resp_headers, data = self._http(
                    view.address, "POST", f"/v1/models/{name}/predict",
                    body=body,
                    headers={"Content-Type": ctype,
                             "X-Request-Id": rid, "X-Shadow": "1"},
                    timeout=self.no_deadline_timeout_s)
                if ctype == wire.CONTENT_TYPE:
                    # the comparator speaks JSON: decode binary
                    # responses to the JSON shape so shadow verdicts
                    # are protocol-invariant (a decode failure is a
                    # candidate protocol error, held against promotion)
                    incumbent = json.dumps(
                        wire.response_to_jsonable(incumbent_body)).encode()
                    if status == 200 and wire.CONTENT_TYPE in (
                            resp_headers.get("Content-Type", "")):
                        data = json.dumps(
                            wire.response_to_jsonable(data)).encode()
                framed = struct.pack("<I", zlib.crc32(data)) + data
                out = chaos.transform_bytes("serving.delivery.shadow",
                                            framed)
                if out is not framed:
                    if len(out) < 4:
                        corrupt = True
                    else:
                        (crc,) = struct.unpack("<I", out[:4])
                        data = out[4:]
                        corrupt = zlib.crc32(data) != crc
            except Exception:
                status = 0  # a connection fault is a candidate error
            diverged = dc.observe_shadow(
                incumbent, status, data, incumbent_latency_s,
                time.monotonic() - t0, corrupt=corrupt)
            if diverged:
                self.metrics.record("shadow_diverged_total")

        threading.Thread(
            target=run, daemon=True,
            name=f"router-forward-shadow-{view.worker_id}").start()

    # --------------------------------------------------------- session tier
    def _publish_pin(self, key: str, wid: str) -> None:
        with self._pins_lock:
            self._session_pins[key] = wid
        if self._config is not None:
            try:
                def fn(cfg):
                    pins = cfg.setdefault("sessions", {})
                    if pins.get(key) == wid:
                        return False  # no-op: don't burn a config version
                    pins[key] = wid
                self._config.mutate(fn)
            except Exception:
                logger.exception("session pin publication failed for %s",
                                 key)

    def _drop_pin(self, key: str) -> None:
        with self._pins_lock:
            self._session_pins.pop(key, None)
        if self._config is not None:
            try:
                def fn(cfg):
                    pins = cfg.setdefault("sessions", {})
                    if key not in pins:
                        return False
                    del pins[key]
                self._config.mutate(fn)
            except Exception:
                logger.exception("session pin removal failed for %s", key)

    def _pinned_worker(self, key: str) -> Optional[str]:
        with self._pins_lock:
            wid = self._session_pins.get(key)
        if wid is None and self._config is not None:
            try:
                wid = (self._config.snapshot().get("sessions")
                       or {}).get(key)
            except Exception:
                wid = None
            if wid is not None:
                with self._pins_lock:  # adopt the published pin
                    self._session_pins[key] = wid
        return wid

    def _session_target(self, name: str, sid: str):
        """The worker this session's traffic goes to: its pin while that
        worker is admittable, else a REPIN — session-key rendezvous over
        the admittable workers (deterministic, so two routers repin the
        same orphan identically), published through the shared config and
        journaled as ``session.migrate``. The repinned worker rehydrates
        the carry from the shared spill dir on the next step; nothing is
        dropped. Returns ``(view, migrated_from)``."""
        key = f"{name}/{sid}"
        wid = self._pinned_worker(key)
        now = time.monotonic()
        views = self.workers()
        view = views.get(wid) if wid is not None else None
        if view is not None and view.admittable(now):
            return view, None
        for cand in self.ranked_workers(key):
            if not cand.admittable(now):
                continue
            self._publish_pin(key, cand.worker_id)
            if wid is not None and cand.worker_id != wid:
                self.metrics.record("session_migrations_total")
                journal.emit("session.migrate", model=name, session=sid,
                             from_worker=wid, to_worker=cand.worker_id,
                             by=self.router_id)
            return cand, (wid if wid != cand.worker_id else None)
        return None, None

    def _route_session(self, method: str, path: str, name: str, sid: str,
                       op: str, raw: bytes, inbound_headers
                       ) -> Tuple[int, Dict[str, str], bytes]:
        """Session-tier routing (ISSUE 16): one pinned attempt at a time,
        NEVER hedged — a duplicated step would advance the carry twice
        and corrupt the stream; retries are safe only because the worker
        dedups by step index, and only after the previous attempt has
        FAILED, never concurrently with it. Connection-level faults fail
        over by repinning (the new worker rehydrates from the shared
        spill dir); everything else is relayed verbatim."""
        self.metrics.record("session_requests_total")
        t_start = time.monotonic()
        inbound = {k: v for k, v in (inbound_headers or {}).items()}
        timeout_ms = self.default_timeout_ms
        try:
            body = json.loads(raw.decode() or "{}")
            timeout_ms = body.get("timeout_ms", timeout_ms)
        except Exception:
            body = None
        hdr_deadline = inbound.get("X-Deadline-Ms")
        if hdr_deadline is not None:
            try:
                hd = float(hdr_deadline)
                timeout_ms = hd if timeout_ms is None else min(timeout_ms,
                                                               hd)
            except ValueError:
                pass
        deadline = (None if timeout_ms is None
                    else t_start + float(timeout_ms) / 1000.0)
        rid = inbound.get("X-Request-Id") or uuid.uuid4().hex
        if op == "create":
            # the router mints the session id so the pin exists BEFORE
            # the create reaches any worker — a crash between the two
            # leaves an unpinned create, never a pinned orphan the
            # client does not know about
            if not isinstance(body, dict):
                return (400, {"Content-Type": "application/json"},
                        json.dumps({"error": "malformed request body"})
                        .encode())
            sid = str(body.get("session_id") or uuid.uuid4().hex[:16])
            body["session_id"] = sid
            raw = json.dumps(body).encode()

        def finish(status, headers, data):
            self.metrics.record_response(status, time.monotonic() - t_start)
            headers = {k: v for k, v in headers.items()
                       if k.lower() not in _HOP_BY_HOP}
            headers["X-Request-Id"] = rid
            return status, headers, data

        tried: set = set()
        while True:
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                return finish(504, {"Content-Type": "application/json"},
                              json.dumps({
                                  "error": "deadline exceeded",
                                  "detail": f"session request {rid} expired "
                                            f"after {len(tried)} "
                                            f"attempt(s)"}).encode())
            view, _ = self._session_target(name, sid)
            if view is None or view.worker_id in tried:
                return finish(503, {"Content-Type": "application/json"},
                              json.dumps({
                                  "error": "unavailable",
                                  "reason": "no_healthy_workers",
                                  "detail": f"no admittable worker for "
                                            f"session {sid!r} "
                                            f"({len(tried)} tried)"})
                              .encode())
            headers = {"Content-Type": "application/json",
                       "X-Request-Id": rid}
            remaining = None if deadline is None else deadline - now
            if remaining is not None:
                headers["X-Deadline-Ms"] = f"{remaining * 1000.0:.1f}"
            view.begin()
            t0 = time.monotonic()
            try:
                chaos.inject("serving.router.forward")
                status, resp_headers, data = self._http(
                    view.address, method, path, body=raw, headers=headers,
                    timeout=(self.no_deadline_timeout_s
                             if remaining is None else remaining + 0.25))
            except BaseException:
                # connection fault: the pinned worker is likely gone —
                # repin and retry (safe: the step never reached the
                # carry, or its effect is deduped by the step index)
                view.done(ok=False)
                if view.ready:
                    journal.emit("router.worker_unready",
                                 worker=view.worker_id,
                                 address=view.address,
                                 reason="connect_fault")
                view.ready = False
                view.breaker.record_failure()
                tried.add(view.worker_id)
                continue
            ok = 200 <= status < 300
            view.done(ok=ok, latency_s=(time.monotonic() - t0) if ok
                      else None)
            if ok:
                view.breaker.record_success()
            elif status >= 500 and status != 503:
                view.breaker.record_failure()
            if op == "close" and status in (200, 404):
                self._drop_pin(f"{name}/{sid}")
            return finish(status, dict(resp_headers), data)

    # ------------------------------------------------------------ lifecycle
    def drain(self, worker_id: str, timeout_s: float = 30.0) -> None:
        """Stop routing new requests to ``worker_id`` and wait for its
        in-flight requests (including hedge losers) to finish."""
        view = self.workers().get(worker_id)
        if view is None:
            raise KeyError(f"unknown worker {worker_id!r}")
        view.draining = True
        deadline = time.monotonic() + timeout_s
        while view.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        if view.inflight > 0:
            raise TimeoutError(
                f"drain of {worker_id!r} timed out with "
                f"{view.inflight} request(s) still in flight")

    def readmit(self, worker_id: str) -> None:
        view = self.workers().get(worker_id)
        if view is not None:
            view.draining = False

    def await_ready(self, worker_id: str, timeout_s: float = 120.0) -> float:
        """Poll ``worker_id``'s ``/readyz`` directly (no probe-cycle
        latency) until 200; returns the wait. The worker stays DRAINING
        in the router until :meth:`readmit`."""
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        while time.monotonic() < deadline:
            self._sync_views()
            view = self.workers().get(worker_id)
            if view is not None:
                try:
                    if self._probe_worker(view):
                        view.ready = True
                        return time.monotonic() - t0
                except Exception:
                    pass
            time.sleep(0.05)
        raise TimeoutError(f"worker {worker_id!r} not ready after "
                           f"{timeout_s:.0f}s")

    def rolling_deploy(self, archive: str, version: Optional[int] = None,
                       drain_timeout_s: float = 30.0,
                       ready_timeout_s: float = 120.0,
                       strategy: str = "all",
                       model: Optional[str] = None,
                       golden_set=None, delivery_config=None,
                       gate=None) -> Dict[str, Any]:
        """Zero-downtime deploy of ``archive`` across the fleet, one
        worker at a time: drain -> supervisor relaunch on the new archive
        (manifest-prewarmed) -> ``/readyz`` -> readmit. Requires a
        supervisor-backed fleet (``restart_worker``). Returns a per-worker
        report (ready wait, restarts).

        ``strategy`` picks the drill (ISSUE 17): ``"all"`` is the classic
        every-worker roll above; ``"gated"`` is the staged-promotion
        pipeline — golden-set gate (cold, before any swap), one candidate
        worker shadowing then canarying live traffic under its own SLO
        window, fleet-wide roll only on a promote verdict, automatic
        drain-back to the incumbent archive on any breach
        (:meth:`_gated_deploy`; ``model`` is required, ``golden_set`` /
        ``delivery_config`` / ``gate`` override the archive's sidecar
        and the stock knobs).

        With a shared config attached (ISSUE 12) the deploy is
        IDEMPOTENT and config-versioned: the (archive, version) action is
        claimed in the applied-action ledger before any worker is
        touched, so the same deploy issued against two live routers runs
        exactly once — the loser returns a ``skipped`` report naming who
        applied it — and the completed deploy state is recorded in the
        config for every router (and every restarted router) to see."""
        if not hasattr(self._fleet, "restart_worker"):
            raise TypeError(
                "rolling_deploy needs a supervisor-backed fleet "
                "(FleetSupervisor); a StaticFleet cannot relaunch workers")
        if strategy == "gated":
            return self._gated_deploy(
                archive, version=version, model=model,
                golden_set=golden_set, delivery_config=delivery_config,
                gate=gate, drain_timeout_s=drain_timeout_s,
                ready_timeout_s=ready_timeout_s)
        if strategy != "all":
            raise ValueError(f"unknown deploy strategy {strategy!r} "
                             f"(expected 'all' or 'gated')")
        # the FULL path keys the claim: two different artifacts that
        # happen to share a filename must be two different actions
        action_id = (f"rolling_deploy:{os.path.abspath(archive)}"
                     f":v{version}")
        if self._config is not None:
            if not self._config.try_claim(
                    action_id, {"router": self.router_id,
                                "archive": archive, "version": version}):
                applied = self._config.applied(action_id)
                logger.info("rolling deploy %s already applied by %s; "
                            "skipping", action_id,
                            (applied or {}).get("router"))
                journal.emit("control.deploy_stage", stage="skipped",
                             archive=archive, version=version,
                             applied_by=(applied or {}).get("router"))
                return {"archive": archive, "version": version,
                        "skipped": True, "action_id": action_id,
                        "applied_by": applied}
            journal.emit("control.deploy_stage", stage="claimed",
                         archive=archive, version=version,
                         router=self.router_id)
        try:
            prewarm = getattr(self._fleet, "prewarm_manifest", None)
            if prewarm is not None:
                prewarm(archive)
            report: Dict[str, Any] = {"archive": archive, "workers": {}}
            # deploy over the SUPERVISOR's full roster, not just the live
            # views — a worker that is down mid-crash-relaunch right now
            # must still be moved to the new archive, or it comes back on
            # the old
            worker_ids = (sorted(self._fleet.worker_ids())
                          if hasattr(self._fleet, "worker_ids")
                          else sorted(self.workers()))
            for wid in worker_ids:
                # drain -> session fence (ISSUE 16: resident carries are
                # pushed to their spill files BEFORE the kill, so sessions
                # migrate instead of losing steps) -> relaunch -> readmit
                self._roll_worker(wid, archive, version,
                                  drain_timeout_s, ready_timeout_s, report)
        except BaseException:
            # a failed deploy must RELEASE its claim, or its own retry
            # (from any router) is skipped forever as "already applied"
            # while the fleet still runs the old archive
            if self._config is not None:
                try:
                    self._config.release_claim(action_id)
                except Exception:
                    logger.exception("claim rollback failed for %s",
                                     action_id)
            raise
        self.metrics.record("deploys_total")
        journal.emit("control.deploy_stage", stage="completed",
                     archive=archive, version=version,
                     workers=sorted(report["workers"]))
        if self._config is not None:
            try:
                def fn(cfg):
                    cfg["deploy"] = {"archive": archive, "version": version,
                                     "strategy": "all",
                                     "router": self.router_id,
                                     "action_id": action_id,
                                     "completed_at": time.time()}
                self._config.mutate(fn)
            except Exception:
                logger.exception("deploy-state publication failed")
        return report

    def _roll_worker(self, wid: str, archive: str, version,
                     drain_timeout_s: float, ready_timeout_s: float,
                     report: Dict[str, Any]) -> None:
        """One worker's classic roll step (drain -> session fence ->
        relaunch on ``archive`` -> ready -> readmit), shared by both
        deploy strategies."""
        if wid in self.workers():
            self.drain(wid, timeout_s=drain_timeout_s)
            view = self.workers().get(wid)
            if view is not None:
                try:
                    self._http(view.address, "POST", "/v1/sessions/drain",
                               body=b"{}",
                               headers={"Content-Type": "application/json"},
                               timeout=drain_timeout_s)
                except Exception:
                    logger.info("session spill fence skipped for %s "
                                "(unreachable)", wid)
            journal.emit("control.deploy_stage", stage="drained",
                         worker=wid, archive=archive)
        try:
            self._fleet.restart_worker(wid, archive=archive,
                                       version=version)
            ready_s = self.await_ready(wid, timeout_s=ready_timeout_s)
        finally:
            self.readmit(wid)
        journal.emit("control.deploy_stage", stage="readmitted",
                     worker=wid, archive=archive,
                     ready_s=round(ready_s, 3))
        report["workers"][wid] = {"ready_s": round(ready_s, 3)}

    def _gated_deploy(self, archive: str, version=None,
                      model: Optional[str] = None, golden_set=None,
                      delivery_config=None, gate=None,
                      drain_timeout_s: float = 30.0,
                      ready_timeout_s: float = 120.0) -> Dict[str, Any]:
        """The ``strategy="gated"`` pipeline (ISSUE 17,
        ``docs/fleet_serving.md``): golden-set gate (candidate loaded
        COLD through a real batcher, golden side answered by the live
        incumbents through this router — before any worker is touched),
        then one candidate worker earning traffic through shadow and
        ramped canary stages under its own SLO window, then either a
        fleet-wide roll (promote) or an automatic drain-back to the
        incumbent archive (rollback — returned as a ``rolled_back``
        report, not raised: a rollback is the pipeline WORKING). Gate
        failure raises; the incumbent never stops serving either way."""
        from deeplearning4j_tpu.serving import delivery as dmod
        import numpy as np
        if model is None:
            raise TypeError("gated deploy needs the model name the "
                            "archive serves (model=...)")
        if not hasattr(self._fleet, "worker_archive"):
            raise TypeError(
                "gated deploy needs a fleet exposing worker_archive() — "
                "rollback must know the incumbent artifact to restore")
        action_id = f"gated_deploy:{os.path.abspath(archive)}:v{version}"
        if self._config is not None:
            if not self._config.try_claim(
                    action_id, {"router": self.router_id,
                                "archive": archive, "version": version,
                                "strategy": "gated"}):
                applied = self._config.applied(action_id)
                logger.info("gated deploy %s already applied by %s; "
                            "skipping", action_id,
                            (applied or {}).get("router"))
                journal.emit("control.deploy_stage", stage="skipped",
                             archive=archive, version=version,
                             applied_by=(applied or {}).get("router"))
                return {"archive": archive, "version": version,
                        "skipped": True, "action_id": action_id,
                        "applied_by": applied}
            journal.emit("control.deploy_stage", stage="claimed",
                         archive=archive, version=version,
                         router=self.router_id, strategy="gated")
        dc = None
        try:
            # ---- stage 1: golden-set gate, before any swap -------------
            try:
                gs = golden_set or dmod.GoldenSet.for_archive(archive)
                if gs is None:
                    raise dmod.GateRefused(
                        f"gated deploy of {archive!r} has no golden set: "
                        f"declare one per-archive "
                        f"({dmod.GoldenSet.sidecar(archive)!r}) or pass "
                        f"golden_set= — an ungated swap is refused")
            except dmod.GateFailed as e:
                # a sidecar that cannot be trusted is a verdict too
                journal.emit("delivery.gate", model=model, archive=archive,
                             version=version, verdict="refused",
                             report=getattr(e, "report", {}))
                raise
            g = gs.gate(default=gate)

            def golden_fn(x):
                raw = json.dumps(
                    {"inputs": np.asarray(x).tolist()}).encode()
                status, _, data = self._route_predict(model, raw, {})
                if status != 200:
                    raise dmod.GateRefused(
                        f"golden side unavailable (incumbent fleet "
                        f"answered {status}) — the gate cannot run; "
                        f"deploy refused")
                return np.asarray(json.loads(data.decode())["outputs"])

            from deeplearning4j_tpu.serving.registry import ModelRegistry
            cold = ModelRegistry()
            try:
                served = cold.load(model, archive, save_manifest=False)
                report_g = g.check(
                    None, None, gs.inputs, labels=gs.labels,
                    golden_fn=golden_fn,
                    candidate_fn=lambda x: np.asarray(served.predict(x)))
            except dmod.GateFailed as e:
                journal.emit(
                    "delivery.gate", model=model, archive=archive,
                    version=version,
                    verdict=("refused" if isinstance(e, dmod.GateRefused)
                             else "fail"),
                    report=getattr(e, "report", {}))
                raise
            finally:
                try:
                    cold.shutdown()
                except Exception:
                    pass
            journal.emit("delivery.gate", model=model, archive=archive,
                         version=version, verdict="pass", report=report_g)

            # ---- stage 2+3: one candidate worker, shadow then canary ---
            prewarm = getattr(self._fleet, "prewarm_manifest", None)
            if prewarm is not None:
                prewarm(archive)
            report: Dict[str, Any] = {"archive": archive,
                                      "version": version,
                                      "strategy": "gated",
                                      "action_id": action_id,
                                      "workers": {}}
            worker_ids = sorted(self._fleet.worker_ids())
            cand_wid = worker_ids[0]
            incumbent_archive = self._fleet.worker_archive(cand_wid)
            dc = dmod.DeliveryController(
                model, archive, version, cand_wid,
                config=delivery_config, gate_report=report_g)
            # flag BEFORE the roll: _sync_views carries the flag across
            # the restart's address change and _roll_worker's readmit
            # then cannot hand the unproven candidate full traffic
            cv = self.workers().get(cand_wid)
            if cv is not None:
                cv.candidate = True
            self._roll_worker(cand_wid, archive, version,
                              drain_timeout_s, ready_timeout_s, report)
            cand_view = self.workers().get(cand_wid)
            if cand_view is not None:
                cand_view.candidate = True
            dc.transition("shadow")
            self._delivery = dc
            while not dc.decided:
                dc.tick()
                time.sleep(0.005)

            if dc.stage == "promote_ready":
                # ---- promote: candidate joins, the rest roll ----------
                self._delivery = None
                if cand_view is not None:
                    cand_view.candidate = False
                for wid in worker_ids[1:]:
                    self._roll_worker(wid, archive, version,
                                      drain_timeout_s, ready_timeout_s,
                                      report)
                dc.finish_promoted()
                self.metrics.record("deploys_total")
                journal.emit("control.deploy_stage", stage="completed",
                             archive=archive, version=version,
                             strategy="gated",
                             workers=sorted(report["workers"]))
                if self._config is not None:
                    try:
                        def fn(cfg):
                            cfg["deploy"] = {
                                "archive": archive, "version": version,
                                "strategy": "gated",
                                "router": self.router_id,
                                "action_id": action_id,
                                "completed_at": time.time()}
                        self._config.mutate(fn)
                    except Exception:
                        logger.exception("deploy-state publication failed")
                report["verdict"] = "promoted"
                report["delivery"] = dc.snapshot()
                return report

            # ---- rollback: drain the canary back to the incumbent -----
            # (a successful DEFENSE, reported not raised: the claim is
            # released so a fixed candidate can retry the same action)
            self._delivery = None
            self._roll_worker(cand_wid, incumbent_archive, None,
                              drain_timeout_s, ready_timeout_s, report)
            cand_view = self.workers().get(cand_wid)
            if cand_view is not None:
                cand_view.candidate = False
            dc.finish_rolled_back()
            self.metrics.record("rollbacks_total")
            if self._config is not None:
                try:
                    self._config.release_claim(action_id)
                except Exception:
                    logger.exception("claim rollback failed for %s",
                                     action_id)
            report["verdict"] = "rolled_back"
            report["cause"] = dc.rollback_cause
            report["delivery"] = dc.snapshot()
            return report
        except BaseException:
            self._delivery = None
            for v in self.workers().values():
                v.candidate = False
            if self._config is not None:
                try:
                    self._config.release_claim(action_id)
                except Exception:
                    logger.exception("claim rollback failed for %s",
                                     action_id)
            raise
        finally:
            if dc is not None:
                self._last_delivery_report = dc.snapshot()

    # ------------------------------------------- fleet scrape + trace merge
    def _fanout(self, fn, views, timeout_s: float,
                name: str = "trace-collector"):
        """Run ``fn(view)`` against every view concurrently (one short-
        lived thread per worker, joined before return — the conftest
        thread-leak guard watches the ``trace-collector`` prefix).
        Returns ``{worker_id: result}`` for the calls that returned
        non-None without raising."""
        results: Dict[str, Any] = {}
        lock = threading.Lock()  # guards: (results dict merge)

        def run(v):
            try:
                r = fn(v)
            except Exception:
                return  # an unreachable worker just drops out of the merge
            if r is not None:
                with lock:
                    results[v.worker_id] = r

        threads = [threading.Thread(target=run, args=(v,), daemon=True,
                                    name=f"{name}-{v.worker_id}")
                   for v in views]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout_s + 1.0)
        return results

    def _scrape_workers(self, path: str = "/v1/metricsz"
                        ) -> Dict[str, Dict[str, Any]]:
        """Every ready worker's JSON payload at ``path`` (``/v1/metricsz``
        counters + raw-bucket histograms, or the ISSUE 10 ``/v1/capacity``
        ledger), fetched in parallel."""
        views = [v for v in self.workers().values() if v.ready]

        def fetch(v):
            status, _, data = self._http(v.address, "GET", path,
                                         timeout=self.probe_timeout_s)
            return json.loads(data.decode()) if status == 200 else None

        return self._fanout(fetch, views, self.probe_timeout_s)

    def attach_autoscaler(self, autoscaler) -> None:
        """Register the :class:`~deeplearning4j_tpu.serving.autoscale
        .SLOAutoscaler` driving this router so ``/v1/autoscaler`` serves
        its decision log (called by ``SLOAutoscaler.start``)."""
        self.autoscaler = autoscaler

    def attach_watchdog(self, watchdog) -> None:
        """Register an :class:`~deeplearning4j_tpu.serving.blackbox
        .AnomalyWatchdog` (ISSUE 15): the probe loop ticks it on the
        control cadence, its incident gauges render on ``/metrics``, and
        its state rides into ``/v1/debug/bundle``."""
        self.watchdog = watchdog

    def fleet_journal(self, since: Optional[float] = None,
                      limit: Optional[int] = None,
                      types=None):
        """The fleet's merged event timeline (ISSUE 15): this router's
        journal plus every ready worker's ``/v1/journal``, merged
        wall-anchor-first (``journal.merge_events`` — a restarted
        worker's seq reset cannot reorder the view) and bounded exactly
        like ``/v1/traces``. Filters are forwarded to the workers so the
        fan-out fetch stays bounded, then re-applied after the merge.
        Returns ``(events, truncated)``."""
        params = []
        if since is not None:
            params.append(f"since={float(since)}")
        if limit is not None:
            params.append(f"limit={int(limit)}")
        if types:
            params.append("type=" + ",".join(sorted(types)))
        path = "/v1/journal" + ("?" + "&".join(params) if params else "")
        streams = [journal.events(since=since, limit=limit, types=types)]
        worker_truncated = False
        for payload in self._scrape_workers(path).values():
            streams.append(payload.get("events") or [])
            worker_truncated = worker_truncated or \
                bool(payload.get("truncated"))
        merged = journal.merge_events(streams)
        bounded, truncated = journal.bound_events(
            merged, since=since, limit=limit, types=types)
        return bounded, truncated or worker_truncated

    def fleet_capacity(self) -> Dict[str, Any]:
        """Fleet-wide capacity aggregation (ISSUE 10 tentpole): every
        ready worker's ``/v1/capacity`` ledger, aggregated the same way
        ``/v1/metricsz`` is — bytes/counters SUMMED per model,
        utilization carried as summed (busy_s, window_s) pairs divided
        once at the edge, dispatch histograms bucket-MERGED (percentiles
        of the merged histogram, never averaged percentiles). The
        per-worker payloads ride along under ``workers`` so the
        autoscaler's capacity guard can check the one worker it would
        scale."""
        scraped = self._scrape_workers("/v1/capacity")
        models: Dict[str, Dict[str, Any]] = {}
        hists: Dict[str, LatencyHistogram] = {}
        budget = in_use = None
        hbm_budget = resident_bytes = None
        placement: Dict[str, Dict[str, List[str]]] = {}
        paging_totals = {"page_ins_total": 0, "evictions_total": 0,
                         "page_in_queue_waits_total": 0,
                         "page_in_rejections_total": 0,
                         "page_in_failures_total": 0,
                         "resident_hits_total": 0, "cold_hits_total": 0}
        sessions_agg: Optional[Dict[str, Any]] = None
        util_agg = {"busy_s": 0.0, "harvested_busy_s": 0.0,
                    "device_window_s": 0.0, "replicas": 0}
        for wid, payload in sorted(scraped.items()):
            # idle-signal aggregation (ISSUE 19 satellite): the raw
            # summable busy/window terms are summed across workers and
            # the fractions derived ONCE at the edge, never averaged
            wu = payload.get("utilization")
            if isinstance(wu, dict):
                try:
                    inc_util = {
                        "busy_s": float(wu.get("busy_s", 0.0)),
                        "harvested_busy_s":
                            float(wu.get("harvested_busy_s", 0.0)),
                        "device_window_s":
                            float(wu.get("device_window_s", 0.0)),
                        "replicas": int(wu.get("replicas", 0))}
                except (TypeError, ValueError):
                    pass  # malformed utilization: skip, never the scrape
                else:
                    for k, v in inc_util.items():
                        util_agg[k] += v
            # session aggregation (ISSUE 16): residency/counters SUMMED;
            # spilled_files taken as the MAX because the spill dir is
            # shared fleet-wide — every worker counts the same files
            ses = payload.get("sessions")
            if isinstance(ses, dict):
                try:
                    inc_tracked = int(ses.get("tracked", 0))
                    inc_resident = int(ses.get("resident", 0))
                    inc_bytes = int(ses.get("resident_bytes", 0))
                    inc_spilled = int(ses.get("spilled_files", 0))
                    inc_counters = {
                        k: int(v)
                        for k, v in sorted((ses.get("counters")
                                            or {}).items())}
                except (TypeError, ValueError):
                    pass  # malformed sessions block: skip, never the scrape
                else:
                    if sessions_agg is None:
                        sessions_agg = {"tracked": 0, "resident": 0,
                                        "resident_bytes": 0,
                                        "spilled_files": 0, "counters": {}}
                    sessions_agg["tracked"] += inc_tracked
                    sessions_agg["resident"] += inc_resident
                    sessions_agg["resident_bytes"] += inc_bytes
                    sessions_agg["spilled_files"] = max(
                        sessions_agg["spilled_files"], inc_spilled)
                    for k, v in inc_counters.items():
                        sessions_agg["counters"][k] = (
                            sessions_agg["counters"].get(k, 0) + v)
            # residency aggregation (ISSUE 11): budgets/resident bytes
            # summed, per-model worker placement lists, paging counters
            res = payload.get("residency")
            if isinstance(res, dict):
                try:
                    if res.get("hbm_budget_bytes") is not None:
                        hbm_budget = ((hbm_budget or 0)
                                      + int(res["hbm_budget_bytes"]))
                    resident_bytes = ((resident_bytes or 0)
                                      + int(res.get("resident_bytes", 0)))
                    for m, d in sorted((res.get("models") or {}).items()):
                        slot = placement.setdefault(
                            m, {"resident_workers": [], "cold_workers": []})
                        key = ("resident_workers"
                               if d.get("state") == "resident"
                               else "cold_workers")
                        slot[key].append(wid)
                    pg = res.get("paging") or {}
                    for k in paging_totals:
                        paging_totals[k] += int(pg.get(k, 0))
                except (TypeError, ValueError):
                    pass  # malformed residency: skip it, never the scrape
            proc = payload.get("process") or {}
            if proc.get("device_budget_bytes") is not None:
                budget = (budget or 0) + int(proc["device_budget_bytes"])
            if proc.get("device_in_use_bytes") is not None:
                in_use = (in_use or 0) + int(proc["device_in_use_bytes"])
            for model, c in sorted((payload.get("models") or {}).items()):
                # parse the WHOLE entry first, apply increments only
                # after: a malformed field must skip the entry entirely,
                # not leave its bytes counted with zero busy time (which
                # would skew busy_fraction low — the very signal the
                # autoscaler's guard reads)
                try:
                    inc = {
                        "param_bytes": int(c["param_bytes"]),
                        "device_bytes_total": int(c["device_bytes_total"]),
                        "replicas": int(c["replicas"]),
                        "workers": 1,
                        "busy_s": float(c["utilization"]["busy_s"]),
                        "window_s": float(c["utilization"]["window_s"]),
                        "queue_depth": int(c["queue"]["depth"]),
                        "queue_headroom_requests":
                            int(c["queue"]["headroom_requests"]),
                        "aot_executables": int(c["aot_executables"]),
                    }
                    # drain-rate flatten (ISSUE 20 satellite): each
                    # worker's measured admission-queue drain estimate
                    # becomes a fleet-summed requests/s capacity figure
                    # the autoscaler's forecast blends with the
                    # utilization-implied serveable rate. Optional field
                    # (older payloads / no drain sample yet): missing or
                    # non-positive contributes 0, never skips the entry.
                    dm = c["queue"].get("drain_ms_per_request")
                    try:
                        inc["drain_rate_rps"] = (
                            1000.0 / float(dm)
                            if dm is not None and float(dm) > 0 else 0.0)
                    except (TypeError, ValueError):
                        inc["drain_rate_rps"] = 0.0
                    wire = c.get("dispatch_latency")
                    h = LatencyHistogram.from_wire(wire) if wire else None
                    if h is not None:
                        # merge checks bucket-bounds compatibility BEFORE
                        # mutating, so a raise here leaves hists untouched
                        if model in hists:
                            hists[model].merge(h)
                        else:
                            hists[model] = h
                except (KeyError, TypeError, ValueError):
                    continue  # malformed worker entry: skip, never break
                a = models.setdefault(model, {
                    "param_bytes": 0, "device_bytes_total": 0,
                    "replicas": 0, "workers": 0, "busy_s": 0.0,
                    "window_s": 0.0, "queue_depth": 0,
                    "queue_headroom_requests": 0, "aot_executables": 0,
                    "drain_rate_rps": 0.0})
                for k, v in inc.items():
                    a[k] += v
        for model, a in models.items():
            a["busy_fraction"] = round(
                a["busy_s"] / a["window_s"], 6) if a["window_s"] else 0.0
            a["drain_rate_rps"] = round(a["drain_rate_rps"], 4)
            h = hists.get(model)
            if h is not None:
                a["dispatch_p50_s"] = h.percentile(50)
                a["dispatch_p99_s"] = h.percentile(99)
                a["dispatch_count"] = h.count
        dw = util_agg["device_window_s"]
        util_agg["serving_busy_fraction"] = round(
            util_agg["busy_s"] / dw, 6) if dw > 0 else 0.0
        util_agg["device_idle_fraction"] = round(max(
            0.0, 1.0 - (util_agg["busy_s"] + util_agg["harvested_busy_s"])
            / dw), 6) if dw > 0 else 1.0
        util_agg["busy_s"] = round(util_agg["busy_s"], 6)
        util_agg["harvested_busy_s"] = round(
            util_agg["harvested_busy_s"], 6)
        util_agg["device_window_s"] = round(dw, 3)
        out = {
            "workers": scraped,
            "models": models,
            "process": {"device_budget_bytes": budget,
                        "device_in_use_bytes": in_use},
            "utilization": util_agg,
        }
        if placement or hbm_budget is not None:
            out["residency"] = {
                "hbm_budget_bytes": hbm_budget,
                "resident_bytes": resident_bytes or 0,
                "models": placement,
                "paging": paging_totals,
            }
        if sessions_agg is not None:
            out["sessions"] = sessions_agg
        return out

    def render_fleet_capacity(self) -> str:
        """``fleet_capacity_*`` gauges for the router's ``/metrics``."""
        agg = self.fleet_capacity()
        lines = ["# TYPE fleet_capacity_param_bytes gauge"]
        for model, a in sorted(agg["models"].items()):
            lbl = f'{{model="{model}"}}'
            lines.append(f"fleet_capacity_param_bytes{lbl} "
                         f"{a['param_bytes']}")
            lines.append(f"fleet_capacity_device_bytes{lbl} "
                         f"{a['device_bytes_total']}")
            lines.append(f"fleet_capacity_replicas{lbl} {a['replicas']}")
            lines.append(f"fleet_capacity_workers{lbl} {a['workers']}")
            lines.append(f"fleet_capacity_utilization_busy_fraction{lbl} "
                         f"{a['busy_fraction']}")
            lines.append(f"fleet_capacity_queue_headroom_requests{lbl} "
                         f"{a['queue_headroom_requests']}")
            lines.append(f"fleet_capacity_drain_rate_rps{lbl} "
                         f"{a['drain_rate_rps']}")
            if "dispatch_p99_s" in a:
                lines.append(
                    f'fleet_capacity_dispatch_seconds{{model="{model}",'
                    f'quantile="0.99"}} {a["dispatch_p99_s"]}')
        util = agg.get("utilization") or {}
        if util:
            lines.append(f"fleet_capacity_device_busy_s "
                         f"{util['busy_s']}")
            lines.append(f"fleet_capacity_harvested_busy_s "
                         f"{util['harvested_busy_s']}")
            lines.append(f"fleet_capacity_device_window_s "
                         f"{util['device_window_s']}")
            lines.append(f"fleet_capacity_serving_busy_fraction "
                         f"{util['serving_busy_fraction']}")
            lines.append(f"fleet_capacity_device_idle_fraction "
                         f"{util['device_idle_fraction']}")
        proc = agg["process"]
        if proc.get("device_budget_bytes") is not None:
            lines.append(f"fleet_capacity_device_budget_bytes "
                         f"{proc['device_budget_bytes']}")
        res = agg.get("residency")
        if res:
            if res.get("hbm_budget_bytes") is not None:
                lines.append(f"fleet_capacity_hbm_budget_bytes "
                             f"{res['hbm_budget_bytes']}")
            lines.append(f"fleet_capacity_resident_bytes "
                         f"{res.get('resident_bytes', 0)}")
            for m, slot in sorted((res.get("models") or {}).items()):
                lines.append(
                    f'fleet_capacity_resident_workers{{model="{m}"}} '
                    f"{len(slot.get('resident_workers', []))}")
            pg = res.get("paging") or {}
            for counter in ("page_ins_total", "evictions_total",
                            "page_in_queue_waits_total",
                            "page_in_failures_total"):
                if counter in pg:
                    lines.append(f"fleet_capacity_{counter} {pg[counter]}")
        ses = agg.get("sessions")
        if ses:
            lines.append(f"fleet_capacity_sessions_tracked "
                         f"{ses.get('tracked', 0)}")
            lines.append(f"fleet_capacity_sessions_resident "
                         f"{ses.get('resident', 0)}")
            lines.append(f"fleet_capacity_sessions_resident_bytes "
                         f"{ses.get('resident_bytes', 0)}")
            lines.append(f"fleet_capacity_sessions_spilled_files "
                         f"{ses.get('spilled_files', 0)}")
            cs = ses.get("counters") or {}
            for counter in ("steps_total", "rehydrates_total",
                            "migrations_total", "lost_total"):
                if counter in cs:
                    lines.append(f"fleet_capacity_sessions_{counter} "
                                 f"{cs[counter]}")
        return "\n".join(lines) + "\n"

    def render_fleet_metrics(self) -> str:
        """Fleet-wide ``/metrics`` section (ISSUE 9): worker counters
        summed and latency histograms MERGED across the fleet (bucket
        merge — percentiles of the merged histogram, never averaged
        percentiles), per-worker series kept under a ``worker=`` label,
        plus the router's fleet-wide SLO attainment and burn rates. One
        scrape of the router sees the whole fleet."""
        scraped = self._scrape_workers()
        agg_counters: Dict[tuple, float] = {}
        agg_hists: Dict[str, LatencyHistogram] = {}
        per_worker = []
        for wid, payload in sorted(scraped.items()):
            for model, snap in sorted((payload.get("models") or {}).items()):
                for cname, v in sorted((snap.get("counters") or {}).items()):
                    if not isinstance(v, (int, float)):
                        continue  # malformed counter: skip, never break
                    per_worker.append(
                        f'fleet_serving_{cname}{{model="{model}",'
                        f'worker="{wid}"}} {v}')
                    key = (model, cname)
                    agg_counters[key] = agg_counters.get(key, 0) + v
                hist_wire = (snap.get("histograms")
                             or {}).get("request_latency")
                if not hist_wire:
                    continue
                try:
                    h = LatencyHistogram.from_wire(hist_wire)
                    if model in agg_hists:
                        agg_hists[model].merge(h)
                    else:
                        agg_hists[model] = h
                except (KeyError, ValueError, TypeError):
                    pass  # malformed snapshot: skip, never break the scrape
        lines = ["# TYPE fleet_serving_requests_total counter",
                 f"fleet_workers_scraped {len(scraped)}"]
        for (model, cname), v in sorted(agg_counters.items()):
            lines.append(f'fleet_serving_{cname}{{model="{model}"}} {v}')
        for model, h in sorted(agg_hists.items()):
            lines.append(f'fleet_serving_latency_count{{model="{model}"}} '
                         f"{h.count}")
            for q in (50, 99):
                lines.append(
                    f'fleet_serving_latency_seconds{{model="{model}",'
                    f'quantile="0.{q}"}} {h.percentile(q)}')
        lines.extend(per_worker)
        slo_text = self.slo.render_prometheus()
        if slo_text:
            lines.append(slo_text.rstrip("\n"))
        try:
            lines.append(self.render_fleet_capacity().rstrip("\n"))
        except Exception:
            pass  # capacity must never be able to break a scrape
        return "\n".join(lines) + "\n"

    def _render_pool_metrics(self) -> str:
        """Keep-alive pool gauges for the router's ``/metrics``
        (ISSUE 18): how much TCP setup the pool is actually saving."""
        s = self.pool.snapshot()
        return "\n".join([
            f"router_pool_idle_connections {s['idle_connections']}",
            f"router_pool_created_total {s['created_total']}",
            f"router_pool_reused_total {s['reused_total']}",
            f"router_pool_discarded_total {s['discarded_total']}",
            f"router_pool_invalidated_total {s['invalidated_total']}",
        ]) + "\n"

    def _render_blackbox_metrics(self) -> str:
        """The ``journal_*`` + ``incident_*`` section of the router's
        ``/metrics`` (ISSUE 15)."""
        parts = [journal.render_prometheus().rstrip("\n")]
        wd = self.watchdog
        if wd is not None:
            try:
                parts.append(wd.render_prometheus().rstrip("\n"))
            except Exception:
                pass  # the black box must never break a scrape
        return "\n".join(parts) + "\n"

    def aggregate_traces(self, trace_id: Optional[str] = None,
                         limit: Optional[int] = None,
                         since: Optional[float] = None
                         ) -> List[Dict[str, Any]]:
        """The flight recorder's read side — see
        :meth:`aggregate_traces_bounded`; this convenience returns the
        (bounded) records alone."""
        return self.aggregate_traces_bounded(trace_id, limit, since)[0]

    def aggregate_traces_bounded(self, trace_id: Optional[str] = None,
                                 limit: Optional[int] = None,
                                 since: Optional[float] = None):
        """The flight recorder's read side: merge this router's kept
        traces with every ready worker's ``/v1/traces`` into one record
        per trace id — router attempt spans and the worker spans they
        parented (predict, batcher stages) come back as ONE tree
        (``trace.span_tree``). ``limit``/``since`` bound the result
        (ISSUE 10) — forwarded to the workers too, so the fan-out fetch
        itself stays bounded, then re-applied (with the hard
        response-size cap) after the merge. Returns
        ``(records, truncated)``."""
        records = list(trace.collector().traces())
        views = [v for v in self.workers().values() if v.ready]
        params = []
        if trace_id is not None:
            params.append(f"trace_id={trace_id}")
        if limit is not None:
            params.append(f"limit={int(limit)}")
        if since is not None:
            params.append(f"since={float(since)}")
        path = "/v1/traces" + ("?" + "&".join(params) if params else "")

        def fetch(v):
            status, _, data = self._http(v.address, "GET", path,
                                         timeout=self.probe_timeout_s)
            if status != 200:
                return None
            payload = json.loads(data.decode())
            return payload.get("traces", []), bool(payload.get("truncated"))

        worker_truncated = False
        for recs, trunc in self._fanout(fetch, views,
                                        self.probe_timeout_s).values():
            records.extend(recs or [])
            # a worker that already cut its response means the merged
            # view is incomplete even if the router-side bound trims
            # nothing further — the flag must survive the hop
            worker_truncated = worker_truncated or trunc
        merged = trace.merge_traces(records)
        if trace_id is not None:
            merged = [m for m in merged if m.get("trace_id") == trace_id]
        bounded, truncated = trace.bound_traces(merged, limit=limit,
                                                since=since)
        return bounded, truncated or worker_truncated

    # --------------------------------------------------------- GET handlers
    def _handle_get(self, path: str):
        if path.startswith("/v1/traces"):
            q = parse_qs(urlsplit(path).query)
            try:
                limit = (int(q["limit"][0]) if "limit" in q else None)
                since = (float(q["since"][0]) if "since" in q else None)
            except ValueError as e:
                return 400, {"error": f"bad limit/since query param: {e}"}
            merged, truncated = self.aggregate_traces_bounded(
                q.get("trace_id", [None])[0], limit=limit, since=since)
            if q.get("format", [None])[0] == "chrome":
                return 200, trace.to_chrome_trace(merged)
            return 200, {"traces": merged, "truncated": truncated}
        if path.startswith("/v1/journal"):
            # the black box's fleet read side (ISSUE 15): this router's
            # ring merged with every ready worker's, ordered and bounded
            q = parse_qs(urlsplit(path).query)
            try:
                limit = (int(q["limit"][0]) if "limit" in q else None)
                since = (float(q["since"][0]) if "since" in q else None)
            except ValueError as e:
                return 400, {"error": f"bad limit/since query param: {e}"}
            types = None
            if "type" in q:
                types = {t for v in q["type"] for t in v.split(",") if t}
            events, truncated = self.fleet_journal(since=since, limit=limit,
                                                   types=types)
            return 200, {"router_id": self.router_id, "events": events,
                         "truncated": truncated,
                         "counters": journal.counters()}
        if path == "/v1/debug/stacks":
            from deeplearning4j_tpu.serving import blackbox
            return 200, {"router_id": self.router_id,
                         "stacks": blackbox.stack_sample()}
        if path == "/v1/slo":
            # structured twin of the /metrics slo_* section — the signal
            # the autoscaler consumes, fleet-wide by construction
            return 200, {"windows_s": list(self.slo.windows_s),
                         "slo": self.slo.report()}
        if path == "/v1/delivery":
            # the gated-delivery drill's live view (ISSUE 17): the active
            # controller's stage/stats, else the last finished verdict
            dc = self._delivery
            if dc is not None:
                return 200, {"active": True, "delivery": dc.snapshot()}
            if self._last_delivery_report is not None:
                return 200, {"active": False,
                             "delivery": self._last_delivery_report}
            return 404, {"error": "no gated delivery has run here"}
        if path == "/v1/capacity":
            # fleet-wide capacity aggregation (sums + merged histograms)
            return 200, self.fleet_capacity()
        if path == "/v1/autoscaler":
            # the decision log: why the fleet grew/shrank, with the
            # triggering burn snapshots and the headroom consulted
            if self.autoscaler is None:
                return 404, {"error": "no autoscaler attached"}
            return 200, self.autoscaler.report()
        if path == "/healthz":
            return 200, {"status": "ok",
                         "workers": {wid: v.admittable()
                                     for wid, v in self.workers().items()}}
        if path == "/readyz":
            now = time.monotonic()
            admittable = {wid: v.admittable(now)
                          for wid, v in self.workers().items()}
            ready = any(admittable.values())
            out = {"ready": ready, "router_id": self.router_id,
                   "workers": admittable}
            if self._peer_view:
                # router-to-router peering (ISSUE 12): which peers this
                # router last saw ready — readiness itself stays a
                # function of OUR workers only
                out["peers"] = {rid: p["ready"]
                                for rid, p in self._peer_view.items()}
            return (200 if ready else 503), out
        if path == "/v1/peers":
            # the peering view in full (ISSUE 12): peer addresses +
            # last-probed readiness, and the shared-config health this
            # router routes from
            out = {"router_id": self.router_id,
                   "peers": dict(self._peer_view)}
            if self._config is not None:
                try:
                    out["config"] = self._config.counters()
                except Exception:
                    pass
            return 200, out
        if path == "/fleet":
            out = {
                "router_id": self.router_id,
                "workers": {wid: v.snapshot()
                            for wid, v in self.workers().items()},
                "hedge_delay_ms": round(self.hedge_delay_s() * 1000.0, 3),
                "metrics": self.metrics.snapshot()}
            if self._config is not None:
                try:
                    out["config"] = self._config.counters()
                except Exception:
                    pass
            return 200, out
        if path == "/v1/models" or path.startswith("/v1/models/"):
            # proxy the listing from the first admittable worker
            now = time.monotonic()
            for view in self.ranked_workers("__listing__"):
                if not view.admittable(now):
                    continue
                try:
                    status, _, data = self._http(
                        view.address, "GET", path,
                        timeout=self.probe_timeout_s)
                    return status, json.loads(data.decode())
                except Exception:
                    continue
            return 503, {"error": "unavailable",
                         "reason": "no_healthy_workers"}
        return 404, {"error": f"unknown path {path!r}"}

    # ------------------------------------------------------------ plumbing
    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        router = self
        self._stop.clear()
        self._probe_cycle()  # workers registered+probed before first request

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive (ISSUE 18): clients with connection
            # pools (MultiRouterClient, the bench) reuse this socket;
            # every _send sets Content-Length, which 1.1 requires
            protocol_version = "HTTP/1.1"
            timeout = 20.0
            # headers and body go out in separate writes; without
            # NODELAY, Nagle + delayed ACK stalls each response ~40ms
            disable_nagle_algorithm = True

            def _send(self, code: int, headers: Dict[str, str],
                      body: bytes):
                self.send_response(code)
                for k, v in headers.items():
                    self.send_header(k, str(v))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    text = (router.metrics.render_prometheus(
                                router.workers())
                            + router._render_pool_metrics()
                            + router.render_fleet_metrics()
                            + router._render_blackbox_metrics()).encode()
                    self._send(200, {"Content-Type":
                                     "text/plain; version=0.0.4"}, text)
                    return
                if self.path.startswith("/v1/debug/bundle"):
                    # one curl away from a postmortem (ISSUE 15): the
                    # fleet incident bundle, as a binary tar.gz
                    from deeplearning4j_tpu.serving import blackbox
                    try:
                        data = blackbox.fleet_bundle(router)
                    except Exception as e:
                        self._send(500,
                                   {"Content-Type": "application/json"},
                                   json.dumps({"error": repr(e)}).encode())
                        return
                    self._send(200, {
                        "Content-Type": "application/gzip",
                        "Content-Disposition": 'attachment; filename='
                                               '"debug-bundle.tar.gz"'},
                        data)
                    return
                code, obj = router._handle_get(self.path)
                self._send(code, {"Content-Type": "application/json"},
                           json.dumps(obj).encode())

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                if (self.path.startswith("/v1/models/")
                        and self.path.endswith("/predict")):
                    name = self.path[len("/v1/models/"):-len("/predict")]
                    code, headers, data = router._route_predict(
                        name, raw, self.headers,
                        ctype=self.headers.get("Content-Type"))
                elif (self.path.startswith("/v1/models/")
                        and "/sessions" in self.path):
                    # session tier (ISSUE 16): pinned, never hedged
                    name, _, tail = (self.path[len("/v1/models/"):]
                                     .partition("/sessions"))
                    parts = tail.strip("/").split("/") if tail.strip("/") \
                        else []
                    if not parts:
                        op, sid = "create", ""
                    elif len(parts) == 2 and parts[1] in ("step", "stream"):
                        op, sid = parts[1], parts[0]
                    else:
                        self._send(404, {"Content-Type": "application/json"},
                                   json.dumps({"error": f"unknown path "
                                               f"{self.path!r}"}).encode())
                        return
                    code, headers, data = router._route_session(
                        "POST", self.path, name, sid, op, raw, self.headers)
                elif self.path == "/v1/feedback":
                    # the flywheel's label intake (ISSUE 17): joined
                    # against the access log wherever it lives — the
                    # router accepts labels even when workers wrote the
                    # log, as long as they share the log file
                    from deeplearning4j_tpu.serving import delivery
                    code, obj = delivery.handle_feedback(raw)
                    headers = {"Content-Type": "application/json"}
                    data = json.dumps(obj).encode()
                else:
                    code, headers, data = 404, {
                        "Content-Type": "application/json"}, json.dumps(
                        {"error": f"unknown path {self.path!r}"}).encode()
                self._send(code, headers, data)

            def do_DELETE(self):
                if (self.path.startswith("/v1/models/")
                        and "/sessions/" in self.path):
                    name, _, sid = (self.path[len("/v1/models/"):]
                                    .partition("/sessions/"))
                    code, headers, data = router._route_session(
                        "DELETE", self.path, name, sid.strip("/"), "close",
                        b"", self.headers)
                else:
                    code, headers, data = 404, {
                        "Content-Type": "application/json"}, json.dumps(
                        {"error": f"unknown path {self.path!r}"}).encode()
                self._send(code, headers, data)

            def log_message(self, *a):
                pass

        # KeepAliveHTTPServer: stop() must sever parked keep-alive
        # connections, or pooled clients keep talking to a dead router
        self._httpd = wire.KeepAliveHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="FleetRouter")
        self._thread.start()
        self._prober = threading.Thread(target=self._probe_loop,
                                        daemon=True,
                                        name="FleetRouter-probe")
        self._prober.start()
        from deeplearning4j_tpu.runtime import profiler
        profiler.attach_router(self.metrics)
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()  # release the listener fd promptly
            self._httpd = None
        if self._prober:
            self._prober.join(timeout=5.0)
            self._prober = None
        # parked keep-alives hold worker-side handler threads open;
        # closing the pool releases both ends promptly
        self.pool.close()
