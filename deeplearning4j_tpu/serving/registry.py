"""Named/versioned model registry with hot-swap, warmup, and failure
containment.

The front door of the serving subsystem: models are registered under a name
(from a live ``MultiLayerNetwork``/``ComputationGraph``, a
``ModelSerializer`` zip archive, or a zoo class), each gets its own
:class:`~deeplearning4j_tpu.serving.batcher.ContinuousBatcher` +
:class:`~deeplearning4j_tpu.serving.metrics.ServingMetrics` + a per-model
:class:`~deeplearning4j_tpu.serving.resilience.CircuitBreaker` and
:class:`~deeplearning4j_tpu.serving.resilience.RetryPolicy`, and
``predict(name, x)`` routes traffic. Re-registering a name hot-swaps: the
replacement is built and AOT-warmed *before* the swap, then the old
batcher drains gracefully — in-flight and already-queued requests complete
against the old version, new traffic hits the new one, and no compilation
happens on the serving path during the cut-over.

Cold start (ISSUE 5, ``docs/coldstart.md``): archive loads replay the
:class:`~deeplearning4j_tpu.serving.manifest.WarmupManifest` recorded next
to the archive (and hot-swaps inherit the live entry's manifest), so a
restart pre-warms every (bucket, replica) pair the previous process
served — with the persistent executable cache
(:mod:`deeplearning4j_tpu.runtime.compile_cache`) enabled, each warmup
compile is a deserialization hit and time-to-first-ready
(``serving_warmup_seconds`` on ``/metrics``) collapses. Manifests are
refreshed at graceful undeploy/shutdown to capture traffic-minted buckets.

Failure semantics (chaos-hardened, ``tests/test_chaos.py``):

- **Hot-swap rollback**: an exception during the replacement's build or
  warmup propagates to the caller but leaves the OLD entry serving — the
  swap is committed only after the replacement is fully warmed, so a
  failed deploy never leaves a hole (or a half-swapped pair) in the
  registry.
- **Retry**: a transient batcher failure (model raised mid-batch) is
  retried with exponential backoff + full jitter, up to
  ``retry.max_attempts``. Explicit admission rejections (``Overloaded`` /
  ``DeadlineExceeded`` / ``ServingShutdown``) are never retried.
- **Circuit breaking**: repeated model failures open the per-model
  breaker; while open, ``predict`` sheds instantly with
  :class:`CircuitOpen` instead of queueing doomed work; after the reset
  timeout one probe request decides whether to close it again.
- **Health**: every served model exposes a
  :class:`~deeplearning4j_tpu.serving.resilience.HealthState` for
  ``/readyz`` (STARTING during build/warmup, READY, DEGRADED while the
  breaker is not closed, DRAINING during undeploy/shutdown).

HBM-budgeted paging (ISSUE 11, ``docs/fleet_serving.md``): under an
explicit budget (``DL4J_TPU_HBM_BUDGET_BYTES``, the constructor's
``hbm_budget_bytes``, or the measured device budget) the registry keeps
only part of its catalogue RESIDENT. Archive-backed entries page out to
COLD under cost-weighted-LRU eviction (``serving/paging.py``) — the
manifest is refreshed first, so the page-in replays every traffic-minted
bucket compile-free — and page back in on demand: :meth:`acquire`
resolves a name to a PINNED resident entry, triggering a single-flight
rehydration when cold (N concurrent requests for one cold model cause
exactly one load; the rest wait). A request whose deadline cannot cover
the wait gets :class:`~deeplearning4j_tpu.serving.admission
.PagingInProgress` with an honest measured-cost ``Retry-After`` instead
of a generic failure. Pins make eviction in-flight-safe: a model with an
active request is never unloaded mid-request. Room is *reserved* before
a load mints its device copies, so ``resident_bytes()`` never exceeds
the budget at any sample point even under concurrent page-ins.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.runtime import chaos, journal, trace
from deeplearning4j_tpu.serving import paging
from deeplearning4j_tpu.serving.admission import (
    HBMBudgetExceeded,
    PagingInProgress,
    ServingError,
    page_in_retry_after_ms,
)
from deeplearning4j_tpu.serving.batcher import ArrayOrDict, ContinuousBatcher
from deeplearning4j_tpu.serving.resilience import (
    CircuitBreaker,
    CircuitOpen,
    CircuitState,
    HealthState,
    RetryPolicy,
)

logger = logging.getLogger(__name__)


class ServedModel:
    """One registered (name, version) with its batcher, metrics, breaker,
    retry policy, and health state."""

    def __init__(self, name: str, version: int, model,
                 batcher: ContinuousBatcher,
                 breaker: Optional[CircuitBreaker] = None,
                 retry: Optional[RetryPolicy] = None):
        self.name = name
        self.version = int(version)
        self.model = model
        self.batcher = batcher
        self.breaker = breaker or CircuitBreaker()
        # journal events from this breaker name the model (ISSUE 15)
        self.breaker.journal_scope = f"model:{name}"
        self.retry = retry or RetryPolicy()
        self.loaded_at = time.time()
        self.archive_path: Optional[str] = None  # set by ModelRegistry.load
        self.gate_report: Optional[Dict[str, Any]] = None  # deploy_quantized
        self.device_bytes = 0  # measured at register (ISSUE 11 ledger)
        self._draining = False
        self._started = False  # flipped by the registry after the swap
        self._pins = 0         # in-flight requests holding this entry
        self._pin_lock = threading.Lock()  # guards: _pins
        self.batcher.metrics.attach_breaker(self.breaker)

    # ------------------------------------------------------------- pinning
    # In-flight-safe eviction (ISSUE 11): the registry pins an entry for
    # the duration of each request it routes (acquire() under the registry
    # lock), and the pager only evicts entries with zero pins — an active
    # replica is never unloaded mid-request.
    def pin(self) -> None:
        with self._pin_lock:
            self._pins += 1

    def unpin(self) -> None:
        with self._pin_lock:
            self._pins -= 1

    @property
    def pins(self) -> int:
        with self._pin_lock:
            return self._pins

    @property
    def metrics(self):
        return self.batcher.metrics

    @property
    def health(self) -> HealthState:
        if self._draining:
            return HealthState.DRAINING
        if not self._started:
            return HealthState.STARTING
        if self.breaker.state is not CircuitState.CLOSED:
            return HealthState.DEGRADED
        return HealthState.READY

    def predict(self, x: ArrayOrDict, timeout_ms: Optional[float] = None):
        """One request through the batcher, wrapped in the breaker and the
        retry policy. Raises :class:`CircuitOpen` when the breaker sheds,
        admission errors unretried, or the last model error after the
        retry budget is spent. Each attempt gets a fresh deadline."""
        last_err: Optional[BaseException] = None
        for attempt in range(self.retry.max_attempts):
            if not self.breaker.allow():
                self.metrics.record_rejection("circuit")
                raise CircuitOpen(
                    f"model {self.name!r} circuit is "
                    f"{self.breaker.state.name}; shedding request"
                ) from last_err
            try:
                out = self.batcher.submit(x, timeout_ms=timeout_ms)
            except ServingError:
                # explicit admission/drain rejection: not a model fault —
                # does not trip the breaker, is not retried, and must
                # return a half-open probe slot it may have consumed
                self.breaker.record_discard()
                raise
            except BaseException as e:
                # the batcher stamps one key per faulted batch so N
                # coalesced requests sharing a fault count once (see
                # CircuitBreaker.record_failure)
                self.breaker.record_failure(
                    key=getattr(e, "_serving_failure_key", None))
                last_err = e
                if attempt + 1 < self.retry.max_attempts:
                    self.metrics.record_retry()
                    self.retry.sleep_before_retry(attempt)
                continue
            self.breaker.record_success()
            return out
        raise last_err

    def describe(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "residency": paging.RESIDENT,
            "version": self.version,
            "model_type": type(self.model).__name__,
            "buckets": list(self.batcher.buckets),
            "max_batch_size": self.batcher.max_batch_size,
            "replicas": self.batcher.replica_count,
            "pipeline_depth": self.batcher.pipeline_depth,
            "loaded_at": self.loaded_at,
            "health": self.health.value,
            "breaker": self.breaker.snapshot(),
            "metrics": self.metrics.snapshot(),
        }


class _PageFlight:
    """Single-flight coordination for one cold model's page-in: the first
    requester (the leader) performs the load; every concurrent requester
    waits on the event. Exactly one rehydration per cold miss."""

    __slots__ = ("event", "error", "started_at")

    def __init__(self):
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        self.started_at = time.monotonic()


class ModelRegistry:
    """Thread-safe registry; the unit the HTTP server fronts.

    ``hbm_budget_bytes`` caps the summed measured device bytes of
    RESIDENT models (ISSUE 11 paging; default: the
    ``DL4J_TPU_HBM_BUDGET_BYTES`` env knob, else the measured device
    budget where the backend reports one, else unbounded — paging off)."""

    def __init__(self, hbm_budget_bytes: Optional[int] = None):
        # guards: _models, _residency, _reserved
        self._lock = threading.Lock()
        self._models: Dict[str, ServedModel] = {}
        # ------------------------------------------- paging state (ISSUE 11)
        self._explicit_budget = hbm_budget_bytes
        self._budget_resolved = False
        self._budget: Optional[int] = None
        self._residency: Dict[str, paging.Residency] = {}
        self._reserved: Dict[str, int] = {}  # in-build byte reservations
        # per-device reservation maps (ISSUE 20): the shard-aware twin of
        # _reserved, so the per-device budget check covers in-build loads
        self._reserved_maps: Dict[str, Dict[str, int]] = {}
        self._flights: Dict[str, _PageFlight] = {}
        self._flight_lock = threading.Lock()  # guards: _flights
        self.paging = paging.PagingMetrics()

    # ----------------------------------------------------------- HBM budget
    @property
    def hbm_budget_bytes(self) -> Optional[int]:
        """The resident-byte ceiling, resolved once: explicit constructor
        value, else ``DL4J_TPU_HBM_BUDGET_BYTES``, else the measured
        device budget (backends that report one), else ``None`` =
        unbounded (paging disabled; cold registration still works)."""
        if not self._budget_resolved:
            b = self._explicit_budget
            if b is None:
                b = paging.env_hbm_budget()
            if b is None:
                b = paging.measured_device_budget()
            self._budget = int(b) if b else None
            self._budget_resolved = True
        return self._budget

    def resident_bytes(self) -> int:
        """Summed measured device bytes of RESIDENT models — the ledger
        the budget caps (reservations for in-build loads included, so a
        sample taken mid-page-in still never exceeds the budget)."""
        with self._lock:
            return self._resident_bytes_locked()

    def _resident_bytes_locked(self, exclude: str = "") -> int:  # holds: _lock
        total = sum(int(r.bytes or 0) for n, r in self._residency.items()
                    if r.state == paging.RESIDENT and n != exclude)
        return total + sum(v for n, v in self._reserved.items()
                           if n != exclude)

    # ----------------------------------------------------------- register
    def register(self, name: str, model, version: Optional[int] = None,
                 warmup_example: Optional[ArrayOrDict] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 retry: Optional[RetryPolicy] = None,
                 manifest=None,
                 _archive_info=None,
                 **batcher_kw) -> ServedModel:
        """Serve ``model`` under ``name``. Re-registering an existing name
        hot-swaps (version auto-bumps unless given); the new batcher is
        warmed before it takes traffic and the old one drains gracefully —
        queued requests are served AND every already-dispatched in-flight
        batch reads back against the old version before its pipeline stops.
        A failure during the replacement's build/warmup leaves the old
        entry serving (rollback guarantee). ``batcher_kw`` forwards to
        :class:`ContinuousBatcher` (``max_batch_size``,
        ``batch_timeout_ms``, ``queue_limit``, ``buckets``, ``admission``,
        ``replicas``, ``pipeline_depth``).

        ``manifest`` takes a
        :class:`~deeplearning4j_tpu.serving.manifest.WarmupManifest` to
        REPLAY: the batcher is built with the recorded buckets/replicas and
        warmed from the recorded input signature, so the model reaches
        READY compiling at most the manifest's pairs (cache hits when the
        persistent executable cache is on) and nothing compiles on live
        traffic. A hot-swap with no explicit ``manifest``/
        ``warmup_example`` inherits the replaced entry's manifest, so the
        replacement pre-warms the full live bucket set. Explicit
        ``batcher_kw`` always wins over manifest-recorded values. Warmup
        wall time is recorded as ``serving_warmup_seconds``."""
        chaos.inject("serving.registry.register")
        if model.train_state is None:
            model.init()
        # a quantized model's embedded dtype policy is authoritative: the
        # batcher pre-warms its quantized (bucket, replica, dtype) pairs,
        # counts its traffic, and records it on the warmup manifest
        if "dtype_policy" not in batcher_kw:
            pol = getattr(model, "dtype_policy", None)
            if pol is not None:
                batcher_kw["dtype_policy"] = pol
        with self._lock:
            prev_entry = self._models.get(name)
        if manifest is None and warmup_example is None and prev_entry is not None:
            # hot-swap replay: warm the replacement with everything the
            # live entry is serving (incl. traffic-minted buckets)
            manifest = prev_entry.batcher.warmup_manifest()
        if manifest is not None:
            if warmup_example is None:
                warmup_example = manifest.example()
            batcher_kw.setdefault("buckets", list(manifest.buckets))
            batcher_kw.setdefault("replicas", manifest.replicas)
            batcher_kw.setdefault(
                "max_batch_size",
                manifest.max_batch_size or max(manifest.buckets))
        # Paging (ISSUE 11): RESERVE room under the HBM budget before the
        # batcher mints its device_put replica copies — the estimate is
        # the same per-replica leaf-byte math the capacity ledger later
        # measures, so the resident-byte ledger can never overshoot the
        # budget, even transiently under concurrent page-ins. Evicts
        # cost-weighted-LRU victims as needed.
        est = self._estimate_device_bytes(model, batcher_kw, manifest)
        est_map = self._estimate_per_device(model, batcher_kw, manifest)
        self._reserve_room(name, est, est_map=est_map)
        # recompile risk cached OUTSIDE the lock (it stats the manifest
        # path) so victim selection never touches the filesystem
        risk = (paging.recompile_risk(_archive_info[0])
                if _archive_info is not None else 1.0)
        # Build + AOT-warm OUTSIDE the lock and BEFORE the swap: if this
        # raises (bad config, warmup failure, injected chaos) nothing has
        # been swapped — the previous version, if any, keeps serving.
        t0 = time.monotonic()
        try:
            batcher = ContinuousBatcher(model, warmup_example=warmup_example,
                                        **batcher_kw)
        except BaseException:
            with self._lock:
                self._reserved.pop(name, None)
                self._reserved_maps.pop(name, None)
            logger.warning(
                "register(%r): replacement build/warmup failed; previous "
                "version (if any) keeps serving", name)
            raise
        served = ServedModel(name, 0, model, batcher,
                             breaker=breaker, retry=retry)
        served.metrics.set_warmup_seconds(time.monotonic() - t0)
        from deeplearning4j_tpu.serving import capacity
        dtype_bytes: Dict[str, int] = {}
        device_map: Dict[str, int] = {}
        try:
            dtype_bytes = capacity.served_device_dtype_bytes(served)
            served.device_bytes = sum(dtype_bytes.values())
            device_map = capacity.served_per_device_bytes(served)
        except Exception:
            served.device_bytes = est  # never let accounting fail a deploy
            device_map = dict(est_map)
        with self._lock:
            self._reserved.pop(name, None)
            self._reserved_maps.pop(name, None)
            prev = self._models.get(name)
            if version is None:
                version = prev.version + 1 if prev else 1
            served.version = int(version)
            self._models[name] = served
            served._started = True  # STARTING -> READY at the swap point
            res = self._residency.get(name)
            if res is None:
                res = paging.Residency(name)
                self._residency[name] = res
            res.state = paging.RESIDENT
            res.bytes = int(served.device_bytes)
            res.bytes_estimated = False
            # the measured per-dtype breakdown (ISSUE 12 satellite): what
            # makes eviction scoring dtype-aware — int8-resident models
            # carry their actual 4x-smaller footprint into retention()
            res.dtype_bytes = dict(dtype_bytes)
            # shard-aware per-device charges (ISSUE 20): what the
            # per-device HBM budget check holds each device to
            res.device_map = dict(device_map)
            res.version = served.version
            res.last_used = time.monotonic()
            if _archive_info is not None:
                # archive-backed (load/deploy_quantized): record the
                # rehydration recipe ATOMICALLY with the swap, so a
                # concurrent page-in never observes a resident model in a
                # briefly non-evictable state
                res.evictable = True
                res.archive_path = _archive_info[0]
                res.load_kwargs = dict(_archive_info[1])
                res.risk = risk
            else:
                # a live-net register has nothing to rehydrate from
                res.evictable = False
                res.archive_path = None
        if prev is not None:
            # hot-swap on the record (ISSUE 15): the black box shows the
            # version flip next to the deploy stages that caused it
            journal.emit("registry.hot_swap", model=name,
                         old_version=prev.version,
                         new_version=served.version,
                         device_bytes=served.device_bytes)
        from deeplearning4j_tpu.runtime import profiler
        if batcher.dtype_policy is not None:
            # profiler surface for the quantized-vs-f32 latency split
            profiler.attach_quant_metrics(name, served.metrics)
        else:
            # a plain model replacing a quantized one under the same name
            # must not leave the old split (and its batcher, via the bound
            # metrics callbacks) pinned on the profiler
            profiler.detach_quant_metrics(name)
        if prev is not None:
            prev._draining = True
            try:
                prev.batcher.shutdown(drain=True)
            except Exception:
                logger.exception(
                    "register(%r): drain of replaced v%d failed (new "
                    "version is serving)", name, prev.version)
        return served

    def load(self, name: str, path: str, load_updater: bool = False,
             replay_manifest: bool = True, save_manifest: bool = True,
             resident: bool = True, **kw) -> Optional[ServedModel]:
        """Register from a ``ModelSerializer`` zip archive (MLN or
        ComputationGraph — the archive metadata dispatches the type).

        Cold-start path (``docs/coldstart.md``): when a warmup manifest
        exists next to the archive (``<path>.warmup.json``) it is replayed
        — recorded buckets/replicas, warmup from the recorded input
        signature — so the model reaches READY without minting compiles on
        live traffic (and with the persistent executable cache enabled,
        without compiling at all). After warmup the up-to-date manifest is
        written back (best effort), so each restart records the bucket set
        the NEXT restart should pre-warm. ``replay_manifest=False`` forces
        the cold path; ``save_manifest=False`` skips the write-back.

        ``resident=False`` (ISSUE 11) registers the archive COLD without
        restoring it: the entry spends no HBM until the first request (or
        an explicit :meth:`page_in`) rehydrates it — the multi-tenant
        door: register thousands, stay under budget. Returns ``None`` in
        that case (there is no served model yet)."""
        load_kwargs = {k: v for k, v in kw.items()
                       if k not in ("manifest", "version")}
        load_kwargs.update(load_updater=load_updater,
                           replay_manifest=replay_manifest,
                           save_manifest=save_manifest)
        if not resident:
            self.register_cold(name, path,
                               version=kw.get("version"), **load_kwargs)
            return None
        from deeplearning4j_tpu.models.serializer import ModelSerializer
        from deeplearning4j_tpu.serving.manifest import WarmupManifest
        model = ModelSerializer.restore_model(path, load_updater=load_updater)
        manifest = kw.pop("manifest", None)
        if manifest is None and replay_manifest:
            manifest = WarmupManifest.load_for_archive(path)
        served = self.register(name, model, manifest=manifest,
                               _archive_info=(path, load_kwargs), **kw)
        served.archive_path = path if save_manifest else None
        if save_manifest:
            self.save_manifest(name)
        return served

    def register_cold(self, name: str, path: str,
                      version: Optional[int] = None,
                      **load_kwargs) -> "paging.Residency":
        """Register ``name`` as a COLD archive-backed entry WITHOUT
        loading it (ISSUE 11): no restore, no warmup, zero HBM. The byte
        cost is estimated from the warmup manifest's recorded
        ``device_bytes`` when the archive has been served before, else
        the archive file size; the first :meth:`acquire` (or an explicit
        :meth:`page_in`) rehydrates with ``load_kwargs`` forwarded to
        :meth:`load`. Raises ``ValueError`` when ``name`` is currently
        resident (evict or undeploy first)."""
        from deeplearning4j_tpu.serving.manifest import WarmupManifest
        m = WarmupManifest.load_for_archive(path)
        est = int(m.device_bytes) if m is not None and m.device_bytes else 0
        if est <= 0:
            try:
                # dtype-policy-aware (ISSUE 12 satellite): an archive's
                # file size reflects its STORAGE dtype; the budget must
                # reserve its RESIDENCY dtype (a dequantized-residency
                # quantized archive pages in ~4x its file size)
                est = paging.policy_adjusted_archive_bytes(
                    path, os.path.getsize(path))
            except OSError:
                est = 0
        load_kwargs.pop("version", None)
        risk = paging.recompile_risk(path)  # stat outside the lock
        with self._lock:
            if name in self._models:
                raise ValueError(
                    f"{name!r} is already resident; evict() or undeploy() "
                    f"before re-registering it cold")
            res = self._residency.get(name)
            if res is None:
                res = paging.Residency(name)
                self._residency[name] = res
            res.state = paging.COLD
            res.evictable = True
            res.archive_path = path
            res.load_kwargs = dict(load_kwargs)
            res.risk = risk
            res.bytes = int(est)
            res.bytes_estimated = True
            if version is not None:
                res.version = int(version)
            if m is not None and m.page_in_s and res.page_in_s <= 0:
                res.page_in_s = float(m.page_in_s)
        return res

    def deploy_quantized(self, name: str, path: str, eval_inputs,
                         eval_labels=None, golden=None, gate=None,
                         **kw) -> ServedModel:
        """Accuracy-gated deploy of a quantized archive over the serving
        f32 version of ``name`` (ISSUE 8, ``docs/quantization.md``).

        The gate runs BEFORE the hot-swap: the quantized model is
        evaluated on ``eval_inputs`` **through its real serving path**
        (request rows quantized per the policy, dequantized in-graph)
        against ``golden`` (default: the currently-serving model) using
        the ``evaluation/`` harness, with the threshold DECLARED in the
        archive's dtype policy (override via ``gate``). A failed gate
        raises :class:`~deeplearning4j_tpu.serving.quantize
        .AccuracyGateFailed` with the measured report attached and the
        old version keeps serving untouched — combined with
        :meth:`register`'s build/warmup rollback, a bad quantization can
        never take traffic. On success the quantized model hot-swaps in
        as the next version (old drains gracefully) and the gate report
        is kept on ``served.gate_report``."""
        from deeplearning4j_tpu.models.serializer import ModelSerializer
        from deeplearning4j_tpu.serving.quantize import (AccuracyGate,
                                                         QuantizedModel)
        chaos.inject("serving.registry.deploy_quantized")
        model = ModelSerializer.restore_model(path, load_updater=False)
        if not isinstance(model, QuantizedModel):
            raise ValueError(
                f"{path!r} is not a quantized archive; use load() for "
                f"plain archives")
        if golden is None:
            golden = self.get(name).model
        gate = gate or AccuracyGate.from_policy(model.dtype_policy)
        report = gate.check(golden, model, eval_inputs, labels=eval_labels)
        # a page-in of this archive must NOT re-run the gate (it already
        # passed): plain load() is the rehydration recipe, and the gate
        # report survives evictions on the residency record
        lkw = {k: v for k, v in kw.items() if k not in ("manifest",
                                                        "version")}
        served = self.register(name, model, _archive_info=(path, lkw), **kw)
        served.archive_path = path
        served.gate_report = report
        with self._lock:
            res = self._residency.get(name)
            if res is not None:
                res.gate_report = report
        self.save_manifest(name)
        return served

    def save_manifest(self, name: str,
                      archive_path: Optional[str] = None) -> Optional[str]:
        """Persist ``name``'s CURRENT warmup manifest next to its archive
        (or ``archive_path``), capturing buckets minted under live traffic
        since load. Called automatically at load, graceful undeploy, and
        shutdown, so the next restart pre-warms what this process actually
        served. Best effort: a read-only model dir costs only the
        optimization. Returns the manifest path, or ``None`` when there is
        nothing to record or nowhere to put it."""
        return self._persist_manifest(self.get(name), archive_path)

    def register_zoo(self, name: str, zoo_model, **kw) -> ServedModel:
        """Register a zoo entry: either an already-constructed ``ZooModel``
        instance (``registry.register_zoo("lenet", LeNet())``) or a zoo
        class name string looked up in ``deeplearning4j_tpu.zoo``."""
        if isinstance(zoo_model, str):
            import deeplearning4j_tpu.zoo as zoo
            zoo_model = getattr(zoo, zoo_model)()
        return self.register(name, zoo_model.init(), **kw)

    # ------------------------------------------------------------ routing
    def get(self, name: str) -> ServedModel:
        """The RESIDENT entry for ``name`` (introspection; the request
        path uses :meth:`acquire`, which also pages in and pins). Raises
        ``KeyError`` for unknown and for cold names — the message says
        which."""
        with self._lock:
            served = self._models.get(name)
            have = sorted(self._models)
            cold = (name in self._residency
                    and self._residency[name].state == paging.COLD)
        if served is None:
            if cold:
                raise KeyError(
                    f"no model registered under {name!r} (it is COLD — "
                    f"acquire()/page_in() rehydrates it); resident: {have}")
            raise KeyError(f"no model registered under {name!r}; have {have}")
        return served

    def acquire(self, name: str,
                timeout_ms: Optional[float] = None) -> ServedModel:
        """Resolve ``name`` to a PINNED resident entry, paging it in from
        its archive when COLD (ISSUE 11). The caller MUST ``unpin()`` the
        returned entry when its request finishes — the pin is what makes
        eviction in-flight-safe. Concurrent cold requests single-flight:
        one rehydration, everyone else waits in the page-in queue (up to
        ``timeout_ms``; a deadline that cannot cover the wait raises
        :class:`PagingInProgress` with the honest measured-cost
        ``Retry-After``). Raises ``KeyError`` for names that are neither
        resident nor cold-registered."""
        deadline = (None if timeout_ms is None
                    else time.monotonic() + float(timeout_ms) / 1000.0)
        cold_hit = False
        while True:
            with self._lock:
                served = self._models.get(name)
                res = self._residency.get(name)
                if served is not None:
                    served.pin()
                    if res is not None and not cold_hit:
                        # touch ONCE per request — a cold hit already
                        # touched in the cold branch below, and double
                        # counting would inflate cold models' retention
                        # weight over genuinely hotter resident ones
                        now = time.monotonic()
                        res.ewma.update(now)
                        res.last_used = now
                    self.paging.record_hit(resident=not cold_hit)
                    return served
                if res is None or res.archive_path is None:
                    have = sorted(self._models)
                    raise KeyError(
                        f"no model registered under {name!r}; have {have}")
                if not cold_hit:
                    now = time.monotonic()
                    res.ewma.update(now)
                    res.last_used = now
            cold_hit = True
            self._page_in(name, deadline)

    def predict(self, name: str, x: ArrayOrDict,
                timeout_ms: Optional[float] = None):
        """Route one request through ``name``'s served model (breaker +
        retry + batcher), paging a cold model in first (ISSUE 11). Raises
        ``KeyError`` for unknown names, ``Overloaded``/
        ``DeadlineExceeded``/``PagingInProgress`` under pressure,
        ``CircuitOpen`` while the breaker sheds — never hangs on a
        registered model. The deadline is spent ONCE: time passed
        waiting on a page-in is deducted from the budget the batcher
        sees, never granted twice."""
        deadline = (None if timeout_ms is None
                    else time.monotonic() + float(timeout_ms) / 1000.0)
        served = self.acquire(name, timeout_ms=timeout_ms)
        try:
            remaining = (None if deadline is None else
                         max(0.0, (deadline - time.monotonic()) * 1000.0))
            return served.predict(x, timeout_ms=remaining)
        finally:
            served.unpin()

    # ------------------------------------------------------ paging (ISSUE 11)
    def page_in(self, name: str,
                timeout_ms: Optional[float] = None) -> ServedModel:
        """Explicitly rehydrate a cold model (no-op when already
        resident; the residency endpoint's and the autoscaler placement
        rebalancer's lever). Blocks until resident; single-flight with
        any request-triggered page-in already underway."""
        deadline = (None if timeout_ms is None
                    else time.monotonic() + float(timeout_ms) / 1000.0)
        while True:
            with self._lock:
                served = self._models.get(name)
                if served is not None:
                    return served
                if name not in self._residency or \
                        self._residency[name].archive_path is None:
                    raise KeyError(
                        f"no archive-backed model registered under {name!r}")
            self._page_in(name, deadline)

    def _page_in(self, name: str, deadline: Optional[float]) -> None:
        """Single-flight page-in: the first caller (leader) performs the
        rehydration; concurrent callers wait on its flight. On return the
        model is resident (re-check and pin under the registry lock — an
        eviction may race) or an exception explains why not."""
        with self._flight_lock:
            fl = self._flights.get(name)
            leader = fl is None
            if leader:
                fl = _PageFlight()
                self._flights[name] = fl
        if leader:
            t0 = time.monotonic()
            try:
                loaded = self._rehydrate(name)
            except BaseException as e:
                fl.error = e
                self.paging.record_page_in_failure()
                raise
            finally:
                with self._flight_lock:
                    self._flights.pop(name, None)
                fl.event.set()
            if not loaded:
                return  # raced: someone else made it resident — a ~0s
                # "page-in" must not halve the measured cost estimate
            seconds = time.monotonic() - t0
            self.paging.record_page_in(seconds)
            with self._lock:
                res = self._residency.get(name)
                if res is not None:
                    res.record_page_in_cost(seconds)
                bytes_in = int(res.bytes) if res is not None else None
            # the pager's journal record (ISSUE 15): with registry.evict
            # events, the watchdog's page-in-thrash rule counts these
            journal.emit("registry.page_in", model=name,
                         seconds=round(seconds, 4), bytes=bytes_in)
            return
        # follower: wait in the page-in queue instead of failing — the
        # whole point of request-triggered paging (ISSUE 11). The wait is
        # bounded by the request's own deadline; the rejection hint is the
        # measured page-in cost minus what the flight already spent.
        t0 = time.monotonic()
        remaining = None if deadline is None else deadline - t0
        sp = trace.current_span()
        if remaining is not None and remaining <= 0:
            self.paging.record_rejection()
            raise PagingInProgress(
                f"model {name!r} is paging in and the request deadline has "
                f"already expired",
                retry_after_ms=self._page_in_hint_ms(name, fl))
        ok = fl.event.wait(remaining)
        waited = time.monotonic() - t0
        self.paging.record_queue_wait(waited)
        if sp is not None and sp.recording:
            sp.event("page_in_wait", model=name,
                     waited_ms=round(waited * 1e3, 2), completed=ok)
        if not ok:
            self.paging.record_rejection()
            raise PagingInProgress(
                f"model {name!r} is still paging in after a "
                f"{waited * 1e3:.0f} ms wait; deadline too short to keep "
                f"waiting", retry_after_ms=self._page_in_hint_ms(name, fl))
        if fl.error is not None:
            raise RuntimeError(
                f"page-in of {name!r} failed") from fl.error

    def _page_in_hint_ms(self, name: str, fl: _PageFlight) -> float:
        """Honest ``Retry-After`` for a rejected page-in waiter: measured
        page-in cost (1s default before the first measurement) minus the
        flight's elapsed time, floored (``admission
        .page_in_retry_after_ms``)."""
        with self._lock:
            res = self._residency.get(name)
            est_ms = (res.page_in_s * 1000.0
                      if res is not None and res.page_in_s > 0 else 1000.0)
        elapsed_ms = (time.monotonic() - fl.started_at) * 1000.0
        return page_in_retry_after_ms(est_ms, elapsed_ms)

    def _rehydrate(self, name: str) -> bool:
        """The leader's load: replay the archive + warmup manifest through
        the ordinary :meth:`load` path (room is reserved and victims are
        evicted inside :meth:`register`), traced as a ``registry.page_in``
        span under the triggering request so a cold hit's latency
        breakdown is one tree. Returns ``False`` when the model turned
        out to be resident already (raced with another loader)."""
        chaos.inject("serving.registry.page_in")
        with self._lock:
            res = self._residency.get(name)
            if res is None or res.archive_path is None:
                raise KeyError(
                    f"no archive-backed model registered under {name!r}")
            if name in self._models:
                return False  # raced: already resident
            path = res.archive_path
            version = res.version
            kwargs = dict(res.load_kwargs)
            gate_report = res.gate_report
        cur = trace.current_span()
        if cur is not None and cur.recording:
            sp = cur.child("registry.page_in")
        elif trace.enabled():
            sp = trace.server_span("registry.page_in")
        else:
            sp = trace.NOOP
        with sp:
            if sp.recording:
                sp.flag("page_in")
                sp.set("model", name)
            served = self.load(name, path, version=version, **kwargs)
            served.gate_report = gate_report
            if sp.recording:
                sp.set("bytes", served.device_bytes)
                sp.set("version", served.version)
        return True

    def evict(self, name: str) -> bool:
        """Page ``name`` out to COLD (ISSUE 11): refresh its warmup
        manifest (traffic-minted buckets included — what makes the next
        page-in compile-free), drain its batcher, and drop the device
        copies. Returns ``False`` — without touching anything — when it
        cannot right now: not resident, not archive-backed, or pinned by
        in-flight requests (eviction is in-flight-safe by construction)."""
        with self._lock:
            served = self._models.get(name)
            res = self._residency.get(name)
            if served is None or res is None or not res.evictable:
                return False
            if served.pins > 0:
                return False
            del self._models[name]
            res.state = paging.COLD
            res.bytes = int(served.device_bytes) or res.bytes
            res.bytes_estimated = False
            res.evictions += 1
            res.gate_report = served.gate_report or res.gate_report
        cur = trace.current_span()
        if cur is not None and cur.recording:
            sp = cur.child("registry.evict")
        elif trace.enabled():
            sp = trace.server_span("registry.evict")
        else:
            sp = trace.NOOP
        with sp:
            if sp.recording:
                sp.flag("evict")
                sp.set("model", name)
                sp.set("bytes", served.device_bytes)
            journal.emit("registry.evict", model=name,
                         bytes=int(served.device_bytes or 0))
            served._draining = True
            try:
                served.batcher.shutdown(drain=True)
            except Exception:
                logger.exception("evict(%r): drain failed; the device "
                                 "copies are dropped regardless", name)
            # AFTER the drain, like undeploy: a queued oversized request
            # may mint a bucket while draining and the manifest must
            # record it for the page-in to replay
            self._persist_manifest(served)
        from deeplearning4j_tpu.runtime import profiler
        profiler.detach_quant_metrics(name)
        self.paging.record_eviction()
        logger.info("evicted %r to cold (%d bytes freed)", name,
                    served.device_bytes)
        return True

    def _estimate_device_bytes(self, model, batcher_kw: Dict[str, Any],
                               manifest) -> int:
        """What registering ``model`` will cost in device bytes: host
        param + model-state leaf bytes times the replica count the
        batcher will build — the same math ``capacity
        .served_device_bytes`` measures afterwards, so reservation equals
        measurement."""
        from deeplearning4j_tpu.serving.capacity import _leaf_bytes
        ts = getattr(model, "train_state", None)
        host = (sum(_leaf_bytes(getattr(ts, "params", None)).values())
                + sum(_leaf_bytes(getattr(ts, "model_state", None)).values()))
        replicas = batcher_kw.get("replicas")
        if not replicas and manifest is not None:
            replicas = manifest.replicas
        return host * max(1, int(replicas or 1))

    def _estimate_per_device(self, model, batcher_kw: Dict[str, Any],
                             manifest) -> Dict[str, int]:
        """Shard-aware reservation estimate (ISSUE 20): the per-device
        charges registering ``model`` will place. A classic pool puts one
        whole copy per replica on one device each (round-robin, mirroring
        ``ReplicaPool``); a plan-sliced pool spreads each replica group's
        copy across its slice devices, so an oversized model reserves
        small per-device shards instead of its full tree on one device.
        Approximate by construction — the post-build measurement
        (``capacity.served_per_device_bytes``) replaces it."""
        from deeplearning4j_tpu.serving.capacity import _leaf_bytes
        ts = getattr(model, "train_state", None)
        host = (sum(_leaf_bytes(getattr(ts, "params", None)).values())
                + sum(_leaf_bytes(getattr(ts, "model_state", None)).values()))
        replicas = batcher_kw.get("replicas")
        if not replicas and manifest is not None:
            replicas = manifest.replicas
        replicas = max(1, int(replicas or 1))
        plan = batcher_kw.get("plan")
        devices = batcher_kw.get("devices")
        if devices is None:
            import jax
            devices = jax.devices()
        out: Dict[str, int] = {}
        if plan is None:
            for i in range(replicas):
                d = str(devices[i % len(devices)])
                out[d] = out.get(d, 0) + host
            return out
        gs = max(1, plan.devices_per_replica())
        n_groups = max(1, len(devices) // gs)
        per_dev = -(-host // gs)  # even-shard approximation, rounded up
        for i in range(replicas):
            g = i % n_groups
            for d in devices[g * gs:(g + 1) * gs]:
                out[str(d)] = out.get(str(d), 0) + per_dev
        return out

    def _resident_per_device_locked(self, exclude: str = ""
                                    ) -> Optional[Dict[str, int]]:  # holds: _lock
        """Per-device resident charges (measured maps + in-build
        reservation maps), or ``None`` when any counted entry lacks a
        map — the caller then falls back to the summed-total check, so
        accounting gaps degrade to the conservative pre-plan behavior."""
        out: Dict[str, int] = {}
        for n, r in self._residency.items():
            if r.state != paging.RESIDENT or n == exclude:
                continue
            if not r.device_map:
                if int(r.bytes or 0) > 0:
                    return None
                continue
            for d, b in r.device_map.items():
                out[d] = out.get(d, 0) + int(b)
        for n, m in self._reserved_maps.items():
            if n == exclude:
                continue
            for d, b in m.items():
                out[d] = out.get(d, 0) + int(b)
        return out

    def _reserve_room(self, name: str, est: int,
                      est_map: Optional[Dict[str, int]] = None) -> None:
        """Block until the load fits under the HBM budget (evicting
        cost-weighted-LRU victims), then reserve the bytes under ``name``
        so a concurrent load cannot double-book the same headroom. No-op
        without a budget. Raises :class:`HBMBudgetExceeded` when no
        victim frees enough room within a bounded wait (every candidate
        pinned or non-evictable).

        The budget is held PER DEVICE (ISSUE 20): with per-device charge
        maps available for every counted entry, the check is
        ``max_d(in_use_d + est_d) <= budget`` — a plan-sliced replica's
        small per-device shards fit where its summed tree would not.
        When maps are missing (legacy entries, failed measurement) the
        check degrades to the summed-total comparison, which can only be
        more conservative."""
        budget = self.hbm_budget_bytes
        if budget is None:
            return
        give_up = time.monotonic() + 10.0
        while True:
            with self._lock:
                in_use = self._resident_bytes_locked(exclude=name)
                in_use_map = (self._resident_per_device_locked(exclude=name)
                              if est_map else None)
                if in_use_map is not None:
                    fits = all(in_use_map.get(d, 0) + b <= budget
                               for d, b in est_map.items())
                else:
                    fits = in_use + est <= budget
                if fits:
                    # a hot-swap replaces the OLD version's bytes, which
                    # stay counted (and loaded) until the swap: reserve
                    # only the DELTA so the ledger (old + reservation)
                    # never reads over budget mid-build. The physical
                    # transient of old+new copies is the hot-swap's
                    # pre-existing build-before-swap cost.
                    res = self._residency.get(name)
                    old = (int(res.bytes or 0) if res is not None
                           and res.state == paging.RESIDENT else 0)
                    self._reserved[name] = max(0, int(est) - old)
                    if est_map:
                        oldm = (res.device_map if res is not None
                                and res.state == paging.RESIDENT else {})
                        self._reserved_maps[name] = {
                            d: max(0, int(b) - int((oldm or {}).get(d, 0)))
                            for d, b in est_map.items()}
                    return
                victim = self._pick_victim_locked(exclude=name)
                # can waiting ever help? yes while something evictable is
                # resident (pins are transient) or another load holds a
                # reservation (it will land as an evictable model, or
                # release its bytes on failure). Otherwise fail fast.
                could_ever = any(
                    n != name and (r := self._residency.get(n)) is not None
                    and r.evictable
                    for n in self._models) or any(
                    n != name for n in self._reserved)
            if victim is not None:
                if self.evict(victim):
                    continue
            if not could_ever or time.monotonic() >= give_up:
                raise HBMBudgetExceeded(
                    f"cannot fit {name!r} ({est} bytes) under the HBM "
                    f"budget ({budget} bytes, {in_use} in use) — "
                    + ("every evictable model is pinned by in-flight "
                       "requests" if could_ever else
                       "nothing evictable remains (the model alone "
                       "exceeds the budget, or every resident entry is "
                       "live-registered)"))
            time.sleep(0.005)  # pins are request-scoped; retry shortly

    def _pick_victim_locked(self, exclude: str = "") -> Optional[str]:  # holds: _lock
        """The cost-weighted-LRU victim among evictable, unpinned
        resident models (``Residency.retention`` — dtype-aware: scored
        on the measured per-dtype device bytes, so an int8-resident
        model outweighs an equally-trafficked f32 one 4:1 per byte; LRU
        tie-break). Caller holds ``self._lock``."""
        now = time.monotonic()
        best = None
        for n, served in self._models.items():
            if n == exclude:
                continue
            res = self._residency.get(n)
            if res is None or not res.evictable or served.pins > 0:
                continue
            key = (res.retention(now), res.last_used, n)
            if best is None or key < best:
                best = key
        return best[2] if best is not None else None

    def refresh_device_bytes(self, name: str) -> int:
        """Re-measure a resident model's device bytes and update the
        ledger — called after a runtime replica resize (the scale
        endpoint), which mints or drops ``device_put`` copies the
        register-time measurement cannot know about. If the new footprint
        pushed past the budget, other models are paged out best-effort
        (the resize already happened — refusing it is the autoscaler
        guard's job, keeping the ledger honest is ours). Returns the
        measured bytes (0 when ``name`` is not resident)."""
        with self._lock:
            served = self._models.get(name)
        if served is None:
            return 0
        from deeplearning4j_tpu.serving import capacity
        try:
            dtype_bytes = capacity.served_device_dtype_bytes(served)
            measured = sum(dtype_bytes.values())
            device_map = capacity.served_per_device_bytes(served)
        except Exception:
            return served.device_bytes
        with self._lock:
            served.device_bytes = measured
            res = self._residency.get(name)
            if res is not None:
                res.bytes = measured
                res.bytes_estimated = False
                res.dtype_bytes = dict(dtype_bytes)
                res.device_map = dict(device_map)
        budget = self.hbm_budget_bytes
        if budget is not None:
            while True:
                with self._lock:
                    over = self._resident_bytes_locked() > budget
                    victim = (self._pick_victim_locked(exclude=name)
                              if over else None)
                if victim is None:
                    if over:
                        logger.warning(
                            "replica resize of %r left the registry %d "
                            "bytes over the HBM budget with nothing "
                            "evictable", name,
                            self.resident_bytes() - budget)
                    break
                if not self.evict(victim):
                    break
        return measured

    def residency_snapshot(self) -> Dict[str, Any]:
        """The pager's ledger for ``/v1/capacity``'s ``residency``
        section: budget, resident bytes (reservations included), per-name
        state, and the paging counters — what the paging drill samples to
        prove the budget is never exceeded."""
        budget = self.hbm_budget_bytes  # resolve outside the lock
        now = time.monotonic()
        with self._lock:
            models = {n: r.snapshot(now)
                      for n, r in sorted(self._residency.items())}
            resident = self._resident_bytes_locked()
            per_device = self._resident_per_device_locked()
        return {
            "hbm_budget_bytes": budget,
            "resident_bytes": resident,
            # shard-aware per-device charges (ISSUE 20): the paging drill
            # asserts max(per_device_bytes) <= budget at every sample
            "per_device_bytes": per_device or {},
            "models": models,
            "paging": self.paging.snapshot(),
        }

    # ---------------------------------------------------------- lifecycle
    def names(self) -> List[str]:
        """Every registered name — resident AND cold (a cold model is
        registered and servable; it just is not loaded right now)."""
        with self._lock:
            return sorted(set(self._models) | set(self._residency))

    def resident_names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def describe(self) -> List[Dict[str, Any]]:
        with self._lock:
            served = list(self._models.values())
            cold = [(n, r) for n, r in sorted(self._residency.items())
                    if n not in self._models and r.archive_path is not None]
        out = [s.describe() for s in served]
        now = time.monotonic()
        for n, r in cold:
            out.append({"name": n, "residency": paging.COLD,
                        "version": r.version, "archive": r.archive_path,
                        **{k: v for k, v in r.snapshot(now).items()
                           if k != "state"}})
        return out

    def health(self) -> Dict[str, str]:
        """Per-model health map for ``/readyz``. Cold archive-backed
        entries report ``"cold"`` — they are SERVABLE (a request pages
        them in), so a worker whose whole catalogue happens to be paged
        out at this instant (eviction churn, a page-in mid-build) must
        not drop out of the fleet: pulled from routing, it could never
        receive the request that would page a model back in."""
        with self._lock:
            served = list(self._models.values())
            cold = [n for n, r in self._residency.items()
                    if n not in self._models and r.archive_path is not None]
        out = {s.name: s.health.value for s in served}
        for n in cold:
            out[n] = "cold"
        return out

    @staticmethod
    def ready_from(health: Dict[str, str]) -> bool:
        """Readiness derived from ONE health snapshot: at least one model
        registered and every model READY or cold-servable (a DEGRADED/
        DRAINING/STARTING model fails readiness so an orchestrator routes
        traffic elsewhere; liveness is separate; a COLD model is ready by
        construction — the request path rehydrates it)."""
        return bool(health) and all(
            v in (HealthState.READY.value, "cold")
            for v in health.values())

    def ready(self) -> bool:
        return self.ready_from(self.health())

    def _persist_manifest(self, served: ServedModel,
                          archive_path: Optional[str] = None
                          ) -> Optional[str]:
        """The one manifest-persistence implementation behind
        :meth:`save_manifest`, eviction, and the graceful undeploy/
        shutdown refresh (which captures traffic-minted buckets for the
        next restart). Stamps the measured device bytes and page-in cost
        (ISSUE 11) so a cold registration of this archive knows its HBM
        cost without restoring it."""
        from deeplearning4j_tpu.serving.manifest import manifest_path
        target = archive_path or served.archive_path
        recorded = served.batcher.warmup_manifest()
        if target is None or recorded is None:
            return None
        recorded.device_bytes = int(served.device_bytes or 0)
        with self._lock:
            res = self._residency.get(served.name)
            if res is not None and res.page_in_s > 0:
                recorded.page_in_s = round(res.page_in_s, 4)
        path = manifest_path(target)
        try:
            recorded.save(path)
        except OSError:
            logger.warning("could not persist warmup manifest for %r to %s",
                           served.name, path, exc_info=True)
            return None
        # a manifest now exists next to the archive: refresh the cached
        # recompile risk the eviction policy reads
        risk = paging.recompile_risk(target)
        with self._lock:
            res = self._residency.get(served.name)
            if res is not None:
                res.risk = risk
        return path

    def undeploy(self, name: str, drain: bool = True) -> None:
        """Remove ``name`` entirely — resident or cold (unlike
        :meth:`evict`, which keeps the cold entry servable)."""
        with self._lock:
            served = self._models.pop(name, None)
            res = self._residency.pop(name, None)
        if served is None:
            if res is not None:
                return  # cold entry: nothing loaded, nothing to drain
            raise KeyError(f"no model registered under {name!r}")
        served._draining = True
        served.batcher.shutdown(drain=drain)
        if drain:
            # AFTER the drain: a queued oversized request may mint a bucket
            # while draining, and the manifest must record it
            self._persist_manifest(served)
        from deeplearning4j_tpu.runtime import profiler
        profiler.detach_quant_metrics(name)

    def shutdown(self, drain: bool = True) -> None:
        with self._lock:
            served = list(self._models.values())
            self._models.clear()
            self._residency.clear()
            self._reserved.clear()
            self._reserved_maps.clear()
        from deeplearning4j_tpu.runtime import profiler
        for s in served:
            s._draining = True
            s.batcher.shutdown(drain=drain)
            if drain:
                self._persist_manifest(s)
            profiler.detach_quant_metrics(s.name)
