"""Named/versioned model registry with hot-swap, warmup, and failure
containment.

The front door of the serving subsystem: models are registered under a name
(from a live ``MultiLayerNetwork``/``ComputationGraph``, a
``ModelSerializer`` zip archive, or a zoo class), each gets its own
:class:`~deeplearning4j_tpu.serving.batcher.ContinuousBatcher` +
:class:`~deeplearning4j_tpu.serving.metrics.ServingMetrics` + a per-model
:class:`~deeplearning4j_tpu.serving.resilience.CircuitBreaker` and
:class:`~deeplearning4j_tpu.serving.resilience.RetryPolicy`, and
``predict(name, x)`` routes traffic. Re-registering a name hot-swaps: the
replacement is built and AOT-warmed *before* the swap, then the old
batcher drains gracefully — in-flight and already-queued requests complete
against the old version, new traffic hits the new one, and no compilation
happens on the serving path during the cut-over.

Cold start (ISSUE 5, ``docs/coldstart.md``): archive loads replay the
:class:`~deeplearning4j_tpu.serving.manifest.WarmupManifest` recorded next
to the archive (and hot-swaps inherit the live entry's manifest), so a
restart pre-warms every (bucket, replica) pair the previous process
served — with the persistent executable cache
(:mod:`deeplearning4j_tpu.runtime.compile_cache`) enabled, each warmup
compile is a deserialization hit and time-to-first-ready
(``serving_warmup_seconds`` on ``/metrics``) collapses. Manifests are
refreshed at graceful undeploy/shutdown to capture traffic-minted buckets.

Failure semantics (chaos-hardened, ``tests/test_chaos.py``):

- **Hot-swap rollback**: an exception during the replacement's build or
  warmup propagates to the caller but leaves the OLD entry serving — the
  swap is committed only after the replacement is fully warmed, so a
  failed deploy never leaves a hole (or a half-swapped pair) in the
  registry.
- **Retry**: a transient batcher failure (model raised mid-batch) is
  retried with exponential backoff + full jitter, up to
  ``retry.max_attempts``. Explicit admission rejections (``Overloaded`` /
  ``DeadlineExceeded`` / ``ServingShutdown``) are never retried.
- **Circuit breaking**: repeated model failures open the per-model
  breaker; while open, ``predict`` sheds instantly with
  :class:`CircuitOpen` instead of queueing doomed work; after the reset
  timeout one probe request decides whether to close it again.
- **Health**: every served model exposes a
  :class:`~deeplearning4j_tpu.serving.resilience.HealthState` for
  ``/readyz`` (STARTING during build/warmup, READY, DEGRADED while the
  breaker is not closed, DRAINING during undeploy/shutdown).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.runtime import chaos
from deeplearning4j_tpu.serving.admission import ServingError
from deeplearning4j_tpu.serving.batcher import ArrayOrDict, ContinuousBatcher
from deeplearning4j_tpu.serving.resilience import (
    CircuitBreaker,
    CircuitOpen,
    CircuitState,
    HealthState,
    RetryPolicy,
)

logger = logging.getLogger(__name__)


class ServedModel:
    """One registered (name, version) with its batcher, metrics, breaker,
    retry policy, and health state."""

    def __init__(self, name: str, version: int, model,
                 batcher: ContinuousBatcher,
                 breaker: Optional[CircuitBreaker] = None,
                 retry: Optional[RetryPolicy] = None):
        self.name = name
        self.version = int(version)
        self.model = model
        self.batcher = batcher
        self.breaker = breaker or CircuitBreaker()
        self.retry = retry or RetryPolicy()
        self.loaded_at = time.time()
        self.archive_path: Optional[str] = None  # set by ModelRegistry.load
        self.gate_report: Optional[Dict[str, Any]] = None  # deploy_quantized
        self._draining = False
        self._started = False  # flipped by the registry after the swap
        self.batcher.metrics.attach_breaker(self.breaker)

    @property
    def metrics(self):
        return self.batcher.metrics

    @property
    def health(self) -> HealthState:
        if self._draining:
            return HealthState.DRAINING
        if not self._started:
            return HealthState.STARTING
        if self.breaker.state is not CircuitState.CLOSED:
            return HealthState.DEGRADED
        return HealthState.READY

    def predict(self, x: ArrayOrDict, timeout_ms: Optional[float] = None):
        """One request through the batcher, wrapped in the breaker and the
        retry policy. Raises :class:`CircuitOpen` when the breaker sheds,
        admission errors unretried, or the last model error after the
        retry budget is spent. Each attempt gets a fresh deadline."""
        last_err: Optional[BaseException] = None
        for attempt in range(self.retry.max_attempts):
            if not self.breaker.allow():
                self.metrics.record_rejection("circuit")
                raise CircuitOpen(
                    f"model {self.name!r} circuit is "
                    f"{self.breaker.state.name}; shedding request"
                ) from last_err
            try:
                out = self.batcher.submit(x, timeout_ms=timeout_ms)
            except ServingError:
                # explicit admission/drain rejection: not a model fault —
                # does not trip the breaker, is not retried, and must
                # return a half-open probe slot it may have consumed
                self.breaker.record_discard()
                raise
            except BaseException as e:
                # the batcher stamps one key per faulted batch so N
                # coalesced requests sharing a fault count once (see
                # CircuitBreaker.record_failure)
                self.breaker.record_failure(
                    key=getattr(e, "_serving_failure_key", None))
                last_err = e
                if attempt + 1 < self.retry.max_attempts:
                    self.metrics.record_retry()
                    self.retry.sleep_before_retry(attempt)
                continue
            self.breaker.record_success()
            return out
        raise last_err

    def describe(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "version": self.version,
            "model_type": type(self.model).__name__,
            "buckets": list(self.batcher.buckets),
            "max_batch_size": self.batcher.max_batch_size,
            "replicas": self.batcher.replica_count,
            "pipeline_depth": self.batcher.pipeline_depth,
            "loaded_at": self.loaded_at,
            "health": self.health.value,
            "breaker": self.breaker.snapshot(),
            "metrics": self.metrics.snapshot(),
        }


class ModelRegistry:
    """Thread-safe registry; the unit the HTTP server fronts."""

    def __init__(self):
        self._lock = threading.Lock()
        self._models: Dict[str, ServedModel] = {}

    # ----------------------------------------------------------- register
    def register(self, name: str, model, version: Optional[int] = None,
                 warmup_example: Optional[ArrayOrDict] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 retry: Optional[RetryPolicy] = None,
                 manifest=None,
                 **batcher_kw) -> ServedModel:
        """Serve ``model`` under ``name``. Re-registering an existing name
        hot-swaps (version auto-bumps unless given); the new batcher is
        warmed before it takes traffic and the old one drains gracefully —
        queued requests are served AND every already-dispatched in-flight
        batch reads back against the old version before its pipeline stops.
        A failure during the replacement's build/warmup leaves the old
        entry serving (rollback guarantee). ``batcher_kw`` forwards to
        :class:`ContinuousBatcher` (``max_batch_size``,
        ``batch_timeout_ms``, ``queue_limit``, ``buckets``, ``admission``,
        ``replicas``, ``pipeline_depth``).

        ``manifest`` takes a
        :class:`~deeplearning4j_tpu.serving.manifest.WarmupManifest` to
        REPLAY: the batcher is built with the recorded buckets/replicas and
        warmed from the recorded input signature, so the model reaches
        READY compiling at most the manifest's pairs (cache hits when the
        persistent executable cache is on) and nothing compiles on live
        traffic. A hot-swap with no explicit ``manifest``/
        ``warmup_example`` inherits the replaced entry's manifest, so the
        replacement pre-warms the full live bucket set. Explicit
        ``batcher_kw`` always wins over manifest-recorded values. Warmup
        wall time is recorded as ``serving_warmup_seconds``."""
        chaos.inject("serving.registry.register")
        if model.train_state is None:
            model.init()
        # a quantized model's embedded dtype policy is authoritative: the
        # batcher pre-warms its quantized (bucket, replica, dtype) pairs,
        # counts its traffic, and records it on the warmup manifest
        if "dtype_policy" not in batcher_kw:
            pol = getattr(model, "dtype_policy", None)
            if pol is not None:
                batcher_kw["dtype_policy"] = pol
        with self._lock:
            prev_entry = self._models.get(name)
        if manifest is None and warmup_example is None and prev_entry is not None:
            # hot-swap replay: warm the replacement with everything the
            # live entry is serving (incl. traffic-minted buckets)
            manifest = prev_entry.batcher.warmup_manifest()
        if manifest is not None:
            if warmup_example is None:
                warmup_example = manifest.example()
            batcher_kw.setdefault("buckets", list(manifest.buckets))
            batcher_kw.setdefault("replicas", manifest.replicas)
            batcher_kw.setdefault(
                "max_batch_size",
                manifest.max_batch_size or max(manifest.buckets))
        # Build + AOT-warm OUTSIDE the lock and BEFORE the swap: if this
        # raises (bad config, warmup failure, injected chaos) nothing has
        # been swapped — the previous version, if any, keeps serving.
        t0 = time.monotonic()
        try:
            batcher = ContinuousBatcher(model, warmup_example=warmup_example,
                                        **batcher_kw)
        except BaseException:
            logger.warning(
                "register(%r): replacement build/warmup failed; previous "
                "version (if any) keeps serving", name)
            raise
        served = ServedModel(name, 0, model, batcher,
                             breaker=breaker, retry=retry)
        served.metrics.set_warmup_seconds(time.monotonic() - t0)
        with self._lock:
            prev = self._models.get(name)
            if version is None:
                version = prev.version + 1 if prev else 1
            served.version = int(version)
            self._models[name] = served
            served._started = True  # STARTING -> READY at the swap point
        from deeplearning4j_tpu.runtime import profiler
        if batcher.dtype_policy is not None:
            # profiler surface for the quantized-vs-f32 latency split
            profiler.attach_quant_metrics(name, served.metrics)
        else:
            # a plain model replacing a quantized one under the same name
            # must not leave the old split (and its batcher, via the bound
            # metrics callbacks) pinned on the profiler
            profiler.detach_quant_metrics(name)
        if prev is not None:
            prev._draining = True
            try:
                prev.batcher.shutdown(drain=True)
            except Exception:
                logger.exception(
                    "register(%r): drain of replaced v%d failed (new "
                    "version is serving)", name, prev.version)
        return served

    def load(self, name: str, path: str, load_updater: bool = False,
             replay_manifest: bool = True, save_manifest: bool = True,
             **kw) -> ServedModel:
        """Register from a ``ModelSerializer`` zip archive (MLN or
        ComputationGraph — the archive metadata dispatches the type).

        Cold-start path (``docs/coldstart.md``): when a warmup manifest
        exists next to the archive (``<path>.warmup.json``) it is replayed
        — recorded buckets/replicas, warmup from the recorded input
        signature — so the model reaches READY without minting compiles on
        live traffic (and with the persistent executable cache enabled,
        without compiling at all). After warmup the up-to-date manifest is
        written back (best effort), so each restart records the bucket set
        the NEXT restart should pre-warm. ``replay_manifest=False`` forces
        the cold path; ``save_manifest=False`` skips the write-back."""
        from deeplearning4j_tpu.models.serializer import ModelSerializer
        from deeplearning4j_tpu.serving.manifest import WarmupManifest
        model = ModelSerializer.restore_model(path, load_updater=load_updater)
        manifest = kw.pop("manifest", None)
        if manifest is None and replay_manifest:
            manifest = WarmupManifest.load_for_archive(path)
        served = self.register(name, model, manifest=manifest, **kw)
        served.archive_path = path if save_manifest else None
        if save_manifest:
            self.save_manifest(name)
        return served

    def deploy_quantized(self, name: str, path: str, eval_inputs,
                         eval_labels=None, golden=None, gate=None,
                         **kw) -> ServedModel:
        """Accuracy-gated deploy of a quantized archive over the serving
        f32 version of ``name`` (ISSUE 8, ``docs/quantization.md``).

        The gate runs BEFORE the hot-swap: the quantized model is
        evaluated on ``eval_inputs`` **through its real serving path**
        (request rows quantized per the policy, dequantized in-graph)
        against ``golden`` (default: the currently-serving model) using
        the ``evaluation/`` harness, with the threshold DECLARED in the
        archive's dtype policy (override via ``gate``). A failed gate
        raises :class:`~deeplearning4j_tpu.serving.quantize
        .AccuracyGateFailed` with the measured report attached and the
        old version keeps serving untouched — combined with
        :meth:`register`'s build/warmup rollback, a bad quantization can
        never take traffic. On success the quantized model hot-swaps in
        as the next version (old drains gracefully) and the gate report
        is kept on ``served.gate_report``."""
        from deeplearning4j_tpu.models.serializer import ModelSerializer
        from deeplearning4j_tpu.serving.quantize import (AccuracyGate,
                                                         QuantizedModel)
        chaos.inject("serving.registry.deploy_quantized")
        model = ModelSerializer.restore_model(path, load_updater=False)
        if not isinstance(model, QuantizedModel):
            raise ValueError(
                f"{path!r} is not a quantized archive; use load() for "
                f"plain archives")
        if golden is None:
            golden = self.get(name).model
        gate = gate or AccuracyGate.from_policy(model.dtype_policy)
        report = gate.check(golden, model, eval_inputs, labels=eval_labels)
        served = self.register(name, model, **kw)
        served.archive_path = path
        served.gate_report = report
        self.save_manifest(name)
        return served

    def save_manifest(self, name: str,
                      archive_path: Optional[str] = None) -> Optional[str]:
        """Persist ``name``'s CURRENT warmup manifest next to its archive
        (or ``archive_path``), capturing buckets minted under live traffic
        since load. Called automatically at load, graceful undeploy, and
        shutdown, so the next restart pre-warms what this process actually
        served. Best effort: a read-only model dir costs only the
        optimization. Returns the manifest path, or ``None`` when there is
        nothing to record or nowhere to put it."""
        return self._persist_manifest(self.get(name), archive_path)

    def register_zoo(self, name: str, zoo_model, **kw) -> ServedModel:
        """Register a zoo entry: either an already-constructed ``ZooModel``
        instance (``registry.register_zoo("lenet", LeNet())``) or a zoo
        class name string looked up in ``deeplearning4j_tpu.zoo``."""
        if isinstance(zoo_model, str):
            import deeplearning4j_tpu.zoo as zoo
            zoo_model = getattr(zoo, zoo_model)()
        return self.register(name, zoo_model.init(), **kw)

    # ------------------------------------------------------------ routing
    def get(self, name: str) -> ServedModel:
        with self._lock:
            served = self._models.get(name)
            have = sorted(self._models)
        if served is None:
            raise KeyError(f"no model registered under {name!r}; have {have}")
        return served

    def predict(self, name: str, x: ArrayOrDict,
                timeout_ms: Optional[float] = None):
        """Route one request through ``name``'s served model (breaker +
        retry + batcher). Raises ``KeyError`` for unknown names,
        ``Overloaded``/``DeadlineExceeded`` under pressure,
        ``CircuitOpen`` while the breaker sheds — never hangs on a
        registered model."""
        return self.get(name).predict(x, timeout_ms=timeout_ms)

    # ---------------------------------------------------------- lifecycle
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def describe(self) -> List[Dict[str, Any]]:
        with self._lock:
            served = list(self._models.values())
        return [s.describe() for s in served]

    def health(self) -> Dict[str, str]:
        """Per-model health map for ``/readyz``."""
        with self._lock:
            served = list(self._models.values())
        return {s.name: s.health.value for s in served}

    @staticmethod
    def ready_from(health: Dict[str, str]) -> bool:
        """Readiness derived from ONE health snapshot: at least one model
        registered and every model READY (a DEGRADED/DRAINING/STARTING
        model fails readiness so an orchestrator routes traffic
        elsewhere; liveness is separate)."""
        return bool(health) and all(v == HealthState.READY.value
                                    for v in health.values())

    def ready(self) -> bool:
        return self.ready_from(self.health())

    @staticmethod
    def _persist_manifest(served: ServedModel,
                          archive_path: Optional[str] = None
                          ) -> Optional[str]:
        """The one manifest-persistence implementation behind
        :meth:`save_manifest` and the graceful undeploy/shutdown refresh
        (which captures traffic-minted buckets for the next restart)."""
        from deeplearning4j_tpu.serving.manifest import manifest_path
        target = archive_path or served.archive_path
        recorded = served.batcher.warmup_manifest()
        if target is None or recorded is None:
            return None
        path = manifest_path(target)
        try:
            recorded.save(path)
        except OSError:
            logger.warning("could not persist warmup manifest for %r to %s",
                           served.name, path, exc_info=True)
            return None
        return path

    def undeploy(self, name: str, drain: bool = True) -> None:
        with self._lock:
            served = self._models.pop(name, None)
        if served is None:
            raise KeyError(f"no model registered under {name!r}")
        served._draining = True
        served.batcher.shutdown(drain=drain)
        if drain:
            # AFTER the drain: a queued oversized request may mint a bucket
            # while draining, and the manifest must record it
            self._persist_manifest(served)
        from deeplearning4j_tpu.runtime import profiler
        profiler.detach_quant_metrics(name)

    def shutdown(self, drain: bool = True) -> None:
        with self._lock:
            served = list(self._models.values())
            self._models.clear()
        from deeplearning4j_tpu.runtime import profiler
        for s in served:
            s._draining = True
            s.batcher.shutdown(drain=drain)
            if drain:
                self._persist_manifest(s)
            profiler.detach_quant_metrics(s.name)
