"""Named/versioned model registry with hot-swap and per-model warmup.

The front door of the serving subsystem: models are registered under a name
(from a live ``MultiLayerNetwork``/``ComputationGraph``, a
``ModelSerializer`` zip archive, or a zoo class), each gets its own
:class:`~deeplearning4j_tpu.serving.batcher.ContinuousBatcher` +
:class:`~deeplearning4j_tpu.serving.metrics.ServingMetrics`, and
``predict(name, x)`` routes traffic. Re-registering a name hot-swaps: the
replacement is built and AOT-warmed *before* the swap, then the old
batcher drains gracefully — in-flight and already-queued requests complete
against the old version, new traffic hits the new one, and no compilation
happens on the serving path during the cut-over.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.serving.batcher import ArrayOrDict, ContinuousBatcher


class ServedModel:
    """One registered (name, version) with its batcher and metrics."""

    def __init__(self, name: str, version: int, model, batcher: ContinuousBatcher):
        self.name = name
        self.version = int(version)
        self.model = model
        self.batcher = batcher
        self.loaded_at = time.time()

    @property
    def metrics(self):
        return self.batcher.metrics

    def describe(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "version": self.version,
            "model_type": type(self.model).__name__,
            "buckets": list(self.batcher.buckets),
            "max_batch_size": self.batcher.max_batch_size,
            "loaded_at": self.loaded_at,
            "metrics": self.metrics.snapshot(),
        }


class ModelRegistry:
    """Thread-safe registry; the unit the HTTP server fronts."""

    def __init__(self):
        self._lock = threading.Lock()
        self._models: Dict[str, ServedModel] = {}

    # ----------------------------------------------------------- register
    def register(self, name: str, model, version: Optional[int] = None,
                 warmup_example: Optional[ArrayOrDict] = None,
                 **batcher_kw) -> ServedModel:
        """Serve ``model`` under ``name``. Re-registering an existing name
        hot-swaps (version auto-bumps unless given); the new batcher is
        warmed before it takes traffic and the old one drains gracefully.
        ``batcher_kw`` forwards to :class:`ContinuousBatcher`
        (``max_batch_size``, ``batch_timeout_ms``, ``queue_limit``,
        ``buckets``, ``admission``)."""
        if model.train_state is None:
            model.init()
        batcher = ContinuousBatcher(model, warmup_example=warmup_example,
                                    **batcher_kw)
        with self._lock:
            prev = self._models.get(name)
            if version is None:
                version = prev.version + 1 if prev else 1
            served = ServedModel(name, version, model, batcher)
            self._models[name] = served
        if prev is not None:
            prev.batcher.shutdown(drain=True)
        return served

    def load(self, name: str, path: str, load_updater: bool = False,
             **kw) -> ServedModel:
        """Register from a ``ModelSerializer`` zip archive (MLN or
        ComputationGraph — the archive metadata dispatches the type)."""
        from deeplearning4j_tpu.models.serializer import ModelSerializer
        model = ModelSerializer.restore_model(path, load_updater=load_updater)
        return self.register(name, model, **kw)

    def register_zoo(self, name: str, zoo_model, **kw) -> ServedModel:
        """Register a zoo entry: either an already-constructed ``ZooModel``
        instance (``registry.register_zoo("lenet", LeNet())``) or a zoo
        class name string looked up in ``deeplearning4j_tpu.zoo``."""
        if isinstance(zoo_model, str):
            import deeplearning4j_tpu.zoo as zoo
            zoo_model = getattr(zoo, zoo_model)()
        return self.register(name, zoo_model.init(), **kw)

    # ------------------------------------------------------------ routing
    def get(self, name: str) -> ServedModel:
        with self._lock:
            served = self._models.get(name)
            have = sorted(self._models)
        if served is None:
            raise KeyError(f"no model registered under {name!r}; have {have}")
        return served

    def predict(self, name: str, x: ArrayOrDict,
                timeout_ms: Optional[float] = None):
        """Route one request through ``name``'s batcher. Raises ``KeyError``
        for unknown names, ``Overloaded``/``DeadlineExceeded`` under
        pressure — never hangs on a registered model."""
        return self.get(name).batcher.submit(x, timeout_ms=timeout_ms)

    # ---------------------------------------------------------- lifecycle
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def describe(self) -> List[Dict[str, Any]]:
        with self._lock:
            served = list(self._models.values())
        return [s.describe() for s in served]

    def undeploy(self, name: str, drain: bool = True) -> None:
        with self._lock:
            served = self._models.pop(name, None)
        if served is None:
            raise KeyError(f"no model registered under {name!r}")
        served.batcher.shutdown(drain=drain)

    def shutdown(self, drain: bool = True) -> None:
        with self._lock:
            served = list(self._models.values())
            self._models.clear()
        for s in served:
            s.batcher.shutdown(drain=drain)
