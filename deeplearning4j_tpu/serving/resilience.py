"""Failure handling for the serving subsystem: circuit breaking, retries,
health states.

PR 1 gave serving admission control (load is handled); this module handles
*failures*: a model that starts throwing must not take every request down
with it, a transient fault must not surface to the client when one cheap
retry would absorb it, and orchestration needs an honest readiness signal.

- :class:`CircuitBreaker` — per-model three-state breaker. CLOSED counts
  consecutive-within-window failures; at ``failure_threshold`` it OPENs
  (requests shed instantly with :class:`CircuitOpen`, no compute wasted on
  a known-bad model). After ``reset_timeout_s`` it goes HALF_OPEN and
  admits up to ``half_open_probes`` probe requests: a probe success closes
  the breaker, a probe failure re-opens it and restarts the timer.
- :class:`RetryPolicy` — bounded retries with exponential backoff and
  **full jitter** (delay ~ U[0, min(cap, base * 2^attempt)]), the
  decorrelated schedule that avoids retry stampedes. Seedable so tests
  and chaos drills replay exactly.
- :class:`HealthState` — the per-model lifecycle surfaced on ``/readyz``:
  STARTING (build/warmup in progress), READY, DEGRADED (breaker not
  closed), DRAINING (undeploy/shutdown in progress).

Admission rejections (``Overloaded`` / ``DeadlineExceeded`` /
``ServingShutdown``) are *load* signals, not model faults: they never trip
the breaker and are never retried here.
"""

from __future__ import annotations

import enum
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.runtime import journal
from deeplearning4j_tpu.serving.admission import ServingError


class CircuitOpen(ServingError):
    """Request shed because the model's circuit breaker is open."""


class CircuitState(enum.Enum):
    CLOSED = 0
    HALF_OPEN = 1
    OPEN = 2


class HealthState(enum.Enum):
    STARTING = "starting"
    READY = "ready"
    DEGRADED = "degraded"
    DRAINING = "draining"


class CircuitBreaker:
    """Three-state breaker (thread-safe).

    ``failure_threshold`` failures within ``window_s`` (a success clears
    the count — i.e. consecutive-within-window semantics) open the
    circuit. ``clock`` is injectable so tests drive transitions without
    sleeping.

    Every state TRANSITION emits a ``breaker.open`` / ``breaker.half_open``
    / ``breaker.close`` event into the fleet journal (ISSUE 15) tagged
    with ``journal_scope`` — ``"model:<name>"`` for the registry's
    per-model breakers, ``"worker:<id>"`` for the router's passive
    per-worker views — so a flapping breaker is visible in the black box
    and the watchdog's breaker-flap rule has something to count. Steady
    state emits nothing (the serving hot path records successes without
    a transition).
    """

    def __init__(self, failure_threshold: int = 5, window_s: float = 30.0,
                 reset_timeout_s: float = 5.0, half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.window_s = float(window_s)
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_probes = int(half_open_probes)
        self._clock = clock
        #: who this breaker protects, for journal events (set by the
        #: owner; None = emit unscoped)
        self.journal_scope: Optional[str] = None
        # guards: _state, _failures, _seen_keys, _opened_at, _probes_issued, opens_total
        self._lock = threading.Lock()
        self._state = CircuitState.CLOSED
        self._failures: List[float] = []  # timestamps within window
        self._seen_keys: Dict[str, float] = {}  # batch-failure dedup
        self._opened_at: Optional[float] = None
        self._probes_issued = 0
        self.opens_total = 0

    # ------------------------------------------------------------ internal
    def _prune(self, now: float) -> None:  # holds: _lock
        cutoff = now - self.window_s
        self._failures = [t for t in self._failures if t > cutoff]

    def _tick(self, now: float) -> None:  # holds: _lock
        """OPEN -> HALF_OPEN once the reset timeout elapses."""
        if (self._state is CircuitState.OPEN
                and now - self._opened_at >= self.reset_timeout_s):
            self._state = CircuitState.HALF_OPEN
            self._probes_issued = 0
            journal.emit("breaker.half_open", scope=self.journal_scope)

    # ------------------------------------------------------------- queries
    @property
    def state(self) -> CircuitState:
        with self._lock:
            self._tick(self._clock())
            return self._state

    def allow(self) -> bool:
        """May a request proceed right now? HALF_OPEN admits at most
        ``half_open_probes`` in-flight probes (counted here)."""
        with self._lock:
            now = self._clock()
            self._tick(now)
            if self._state is CircuitState.CLOSED:
                return True
            if self._state is CircuitState.OPEN:
                return False
            if self._probes_issued < self.half_open_probes:
                self._probes_issued += 1
                return True
            return False

    # ------------------------------------------------------------ outcomes
    def record_success(self) -> None:
        with self._lock:
            self._tick(self._clock())
            if self._state is CircuitState.HALF_OPEN:
                self._state = CircuitState.CLOSED
                journal.emit("breaker.close", scope=self.journal_scope)
            self._failures.clear()

    def record_discard(self) -> None:
        """The allowed request ended in an admission rejection (Overloaded
        / DeadlineExceeded / ServingShutdown) — neither a model success nor
        a model failure. Returns a half-open probe slot so an admission
        rejection during HALF_OPEN cannot leak the probe and wedge the
        breaker in a permanent shedding state."""
        with self._lock:
            if (self._state is CircuitState.HALF_OPEN
                    and self._probes_issued > 0):
                self._probes_issued -= 1

    def record_failure(self, key: Optional[str] = None) -> None:
        """``key`` (optional) dedups shared faults: the pipelined batcher
        stamps one key per faulted *batch*, so a single mid-flight failure
        that takes down N coalesced requests counts once toward the
        threshold, not N times — one bad batch must not read as an outage.
        Distinct batches (e.g. each retry attempt) get distinct keys and
        still count individually."""
        with self._lock:
            now = self._clock()
            self._tick(now)
            if key is not None:
                cutoff = now - self.window_s
                self._seen_keys = {k: t for k, t in self._seen_keys.items()
                                   if t > cutoff}
                if key in self._seen_keys:
                    return
                self._seen_keys[key] = now
            if self._state is CircuitState.HALF_OPEN:
                # failed probe: back to OPEN, restart the timer
                self._state = CircuitState.OPEN
                self._opened_at = now
                self.opens_total += 1
                journal.emit("breaker.open", scope=self.journal_scope,
                             reason="probe_failed",
                             opens_total=self.opens_total)
                return
            if self._state is CircuitState.OPEN:
                return
            self._failures.append(now)
            self._prune(now)
            if len(self._failures) >= self.failure_threshold:
                self._state = CircuitState.OPEN
                self._opened_at = now
                self.opens_total += 1
                journal.emit("breaker.open", scope=self.journal_scope,
                             reason="failure_threshold",
                             failures=len(self._failures),
                             opens_total=self.opens_total)
                self._failures.clear()

    def warm_open(self) -> None:
        """Adopt an externally observed OPEN verdict (ISSUE 12: a fresh
        router warm-starts its passive per-worker breaker from the
        worker's own ``/v1/metricsz`` breaker states instead of
        re-learning the failure streak from live traffic). A no-op unless
        CLOSED — an already OPEN/HALF_OPEN breaker keeps its own timer,
        so a warm-start can never reset an in-progress recovery probe."""
        with self._lock:
            now = self._clock()
            self._tick(now)
            if self._state is CircuitState.CLOSED:
                self._state = CircuitState.OPEN
                self._opened_at = now
                self.opens_total += 1
                journal.emit("breaker.open", scope=self.journal_scope,
                             reason="warm_start",
                             opens_total=self.opens_total)
                self._failures.clear()

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            self._tick(self._clock())
            return {"state": self._state.name,
                    "failures_in_window": len(self._failures),
                    "opens_total": self.opens_total}


class RetryPolicy:
    """Exponential backoff with full jitter (seedable, thread-safe enough:
    the RNG is only read under the caller's request thread; determinism is
    per-policy-instance for single-threaded drills)."""

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.02,
                 max_delay_s: float = 1.0, seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self._rng = random.Random(seed)
        self._sleep = sleep

    def delay_for(self, attempt: int) -> float:
        """Full jitter: U[0, min(max_delay, base * 2^attempt)] for the
        delay AFTER failed attempt number ``attempt`` (0-based)."""
        cap = min(self.max_delay_s, self.base_delay_s * (2 ** attempt))
        return self._rng.uniform(0.0, cap)

    def sleep_before_retry(self, attempt: int) -> float:
        d = self.delay_for(attempt)
        if d > 0:
            self._sleep(d)
        return d


NO_RETRY = RetryPolicy(max_attempts=1)
