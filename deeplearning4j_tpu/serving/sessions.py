"""Session tier: server-side recurrent state for streaming inference
(ISSUE 16 tentpole).

The reference's ``MultiLayerNetwork.rnnTimeStep`` keeps carry state on the
network between calls — DL4J's signature stateful-inference API. This
module puts that state behind the serving fleet: a :class:`SessionStore`
holds one carry tree per (model, session id), every step advances it
through the batcher's fixed-shape session program
(:meth:`~deeplearning4j_tpu.serving.batcher.ContinuousBatcher.submit_step`),
and the store generalizes the PR 11 pager's resident/cold discipline from
model weights to session state:

- **Write-through spill.** Every acked step persists the NEW carry to a
  CRC-framed spill file with the checkpoint atomics (tmp +
  ``os.replace``; no per-step fsync — a SIGKILL preserves OS-buffered
  writes of replaced files, and a torn replace loses at most the step
  whose response was never sent, which the step-replay dedup below makes
  exactly-once). Memory is therefore only a CACHE: idle-TTL eviction and
  the host-byte budget drop the memory copy, nothing else.
- **Rehydrate on touch.** A step that misses memory (evicted, or the
  session was created on another worker — failover / rolling deploy)
  reads the spill file back, CRC-checked: a corrupt or truncated frame is
  an explicit :class:`SessionLost`, never a silently-wrong carry.
  Rehydration is single-flight per session — the per-session lock that
  already serializes steps is the flight; waiters bound their wait by
  their own deadline.
- **Migration for free.** The spill directory is SHARED across workers
  (the fleet supervisor defaults it into the run dir), so "migrate a
  session" is simply "rehydrate its spill file on the new pinned worker"
  — the drain stage of a rolling deploy spills, the router repins, the
  next step rehydrates. A rehydrate of a frame written by a different
  worker incarnation emits ``session.migrate``.
- **Exactly-once steps.** A step request may carry the client's step
  index; a replay of the last applied step (router failover retry after
  the response was lost) returns the PERSISTED last output without
  re-advancing the carry — duplicate steps would corrupt it, which is
  also why the router never hedges session traffic.

Every lifecycle transition emits a typed journal event —
``session.create`` / ``session.step_miss`` / ``session.spill`` /
``session.rehydrate`` / ``session.migrate`` / ``session.evict`` /
``session.close`` — so a dropped stream is diagnosable from one
``GET /v1/debug/bundle``; counts, bytes and rehydrate latencies surface
on ``/v1/capacity`` and ``/metrics``.

Timing: the idle-TTL clock is injectable (``clock=``) so eviction tests
never sleep; deadline math stays on ``time.monotonic`` like the rest of
the serving stack.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import struct
import tempfile
import threading
import time
import uuid
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.runtime import chaos, journal
from deeplearning4j_tpu.serving.admission import (DeadlineExceeded,
                                                  ServingError)
from deeplearning4j_tpu.serving.metrics import LatencyHistogram

logger = logging.getLogger(__name__)

__all__ = ["Session", "SessionLost", "SessionStore", "SessionStepConflict"]

_MAGIC = b"DL4JSES1"
_SPILL_SUFFIX = ".sess"


class SessionLost(ServingError):
    """The session's spilled carry state is unusable — corrupt frame, bad
    CRC, truncation, or a structure that no longer matches the model. The
    stream cannot be resumed; the client must create a new session.
    Raised EXPLICITLY: a damaged spill is never rehydrated into a
    silently-wrong carry."""


class SessionStepConflict(ServingError):
    """The client's step index is neither the next step nor a replay of
    the last applied one — the stream and the server disagree about
    position, and applying the input anyway would corrupt the carry."""


def _tree_bytes(tree) -> int:
    return int(sum(getattr(l, "nbytes", 0)
                   for l in jax.tree_util.tree_leaves(tree)))


def _pack_frame(header: Dict[str, Any], leaves: List[np.ndarray]) -> bytes:
    """CRC-framed spill encoding: magic, header length, JSON header (leaf
    shapes/dtypes + payload CRC32), concatenated raw leaf bytes."""
    payload = b"".join(np.ascontiguousarray(l).tobytes() for l in leaves)
    header = dict(header)
    header["leaves"] = [{"shape": list(l.shape), "dtype": l.dtype.str}
                        for l in leaves]
    header["crc"] = zlib.crc32(payload) & 0xFFFFFFFF
    hj = json.dumps(header, sort_keys=True).encode("utf-8")
    return _MAGIC + struct.pack("<II", len(hj), len(payload)) + hj + payload


def _unpack_frame(raw: bytes) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """Decode + verify a spill frame; any damage is :class:`SessionLost`."""
    fixed = len(_MAGIC) + 8
    if len(raw) < fixed or raw[:len(_MAGIC)] != _MAGIC:
        raise SessionLost("spill frame: bad magic or truncated header")
    hlen, plen = struct.unpack("<II", raw[len(_MAGIC):fixed])
    if len(raw) != fixed + hlen + plen:
        raise SessionLost(f"spill frame: truncated "
                          f"({len(raw)} bytes, expected {fixed + hlen + plen})")
    try:
        header = json.loads(raw[fixed:fixed + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise SessionLost(f"spill frame: unreadable header ({e})") from e
    payload = raw[fixed + hlen:]
    if (zlib.crc32(payload) & 0xFFFFFFFF) != header.get("crc"):
        raise SessionLost("spill frame: payload CRC mismatch")
    leaves: List[np.ndarray] = []
    ofs = 0
    for meta in header.get("leaves", []):
        dt = np.dtype(str(meta["dtype"]))
        shape = tuple(int(s) for s in meta["shape"])
        n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        if ofs + n > len(payload):
            raise SessionLost("spill frame: leaf extends past payload")
        leaves.append(np.frombuffer(payload, dtype=dt, count=n // dt.itemsize,
                                    offset=ofs).reshape(shape).copy())
        ofs += n
    if ofs != len(payload):
        raise SessionLost("spill frame: trailing bytes after last leaf")
    return header, leaves


class Session:
    """One stream's server-side record. ``lock`` serializes everything
    that touches the carry — steps, rehydration, eviction — so a stream's
    steps are totally ordered and rehydration is single-flight."""

    __slots__ = ("session_id", "model_name", "state", "last_out", "step",
                 "touched", "state_bytes", "spilled_step", "lock")

    def __init__(self, model_name: str, session_id: str, touched: float):
        self.model_name = model_name
        self.session_id = session_id
        self.state = None          # carry tree (numpy leaves) or None=cold
        self.last_out: Optional[np.ndarray] = None
        self.step = 0              # steps applied to the carry
        self.touched = touched     # store clock; drives idle-TTL
        self.state_bytes = 0
        self.spilled_step = -1     # step count persisted on disk
        # guards: state, last_out, step, state_bytes, spilled_step
        self.lock = threading.Lock()


class SessionStore:
    """Per-worker store of streaming-session carry state (see module
    docstring). One instance per :class:`ModelServer`, shared spill
    directory per fleet."""

    def __init__(self, registry, spill_dir: str, worker_id: str = "",
                 idle_ttl_s: float = 300.0,
                 byte_budget_bytes: Optional[int] = None,
                 clock=time.monotonic, evict_interval_s: float = 1.0,
                 start_evictor: bool = True):
        self._registry = registry
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self.worker_id = worker_id
        self.idle_ttl_s = float(idle_ttl_s)
        self.byte_budget_bytes = byte_budget_bytes
        self._clock = clock
        self._lock = threading.Lock()  # guards: _sessions, _counters
        self._sessions: Dict[Tuple[str, str], Session] = {}
        self._counters = {
            "creates_total": 0, "steps_total": 0, "replays_total": 0,
            "step_misses_total": 0, "rehydrates_total": 0,
            "migrations_total": 0, "spills_total": 0, "evictions_total": 0,
            "closes_total": 0, "lost_total": 0,
        }
        self._rehydrate_hist = LatencyHistogram()
        self._stop = threading.Event()
        self._evictor: Optional[threading.Thread] = None
        if start_evictor:
            self._evictor = threading.Thread(
                target=self._run_evictor, daemon=True,
                name="session-evictor",
                args=(float(evict_interval_s),))
            self._evictor.start()

    # ------------------------------------------------------------ lifecycle
    def create(self, model_name: str, session_id: Optional[str] = None,
               timeout_ms: Optional[float] = None) -> Session:
        """Open a stream: zero carry, spill frame written immediately (a
        brand-new session already survives a worker SIGKILL)."""
        served = self._registry.acquire(model_name, timeout_ms)
        try:
            batcher = served.batcher
            if batcher.session_bucket is None:
                raise ValueError(f"model {model_name!r} is not serving "
                                 f"sessions (no session bucket warmed)")
            sid = str(session_id) if session_id else uuid.uuid4().hex[:16]
            if "/" in sid or os.sep in sid:
                raise ValueError(f"invalid session id {sid!r}")
            key = (model_name, sid)
            sess = Session(model_name, sid, self._clock())
            sess.state = batcher.session_state_template()
            sess.state_bytes = _tree_bytes(sess.state)
            with self._lock:
                if key in self._sessions:
                    raise ValueError(f"session {sid!r} already exists "
                                     f"for model {model_name!r}")
                self._sessions[key] = sess
                self._counters["creates_total"] += 1
            with sess.lock:
                self._write_spill(sess)
            journal.emit("session.create", model=model_name, session=sid,
                         worker=self.worker_id)
            return sess
        finally:
            served.unpin()

    def step(self, model_name: str, session_id: str, x,
             timeout_ms: Optional[float] = None,
             client_step: Optional[int] = None):
        """Advance the stream by one input chunk; returns
        ``(out_row, step, replayed)``. ``client_step`` (the 0-based index
        of the step the CLIENT believes it is sending) makes retries
        exactly-once: a replay of the last applied step returns the
        persisted output without touching the carry."""
        chaos.inject("serving.session.step")
        t0 = time.monotonic()
        served = self._registry.acquire(model_name, timeout_ms)
        try:
            sess = self._lookup_or_adopt(model_name, session_id)
            remaining = (None if timeout_ms is None
                         else max(0.0, timeout_ms / 1000.0
                                  - (time.monotonic() - t0)))
            # the per-session lock IS the step serializer and the
            # rehydration single-flight: the holder rehydrates, everyone
            # else waits bounded by their own deadline
            if not sess.lock.acquire(timeout=remaining if remaining
                                     is not None else -1):
                raise DeadlineExceeded(
                    f"session {session_id!r} busy past the deadline "
                    f"(a prior step of this stream is still executing)")
            try:
                if sess.state is None:
                    with self._lock:
                        self._counters["step_misses_total"] += 1
                    journal.emit("session.step_miss", model=model_name,
                                 session=session_id, worker=self.worker_id)
                    self._rehydrate(sess, served)
                if client_step is not None:
                    if client_step == sess.step - 1 \
                            and sess.last_out is not None:
                        with self._lock:
                            self._counters["replays_total"] += 1
                        return sess.last_out, sess.step, True
                    if client_step != sess.step:
                        raise SessionStepConflict(
                            f"session {session_id!r} is at step "
                            f"{sess.step}, client sent step {client_step}")
                step_timeout = (None if timeout_ms is None
                                else max(1.0, timeout_ms
                                         - (time.monotonic() - t0) * 1000.0))
                out, new_state = served.batcher.submit_step(
                    x, sess.state, timeout_ms=step_timeout)
                sess.state = new_state
                sess.last_out = out
                sess.step += 1
                sess.state_bytes = _tree_bytes(new_state)
                sess.touched = self._clock()
                self._write_spill(sess)  # write-through: ack implies durable
                with self._lock:
                    self._counters["steps_total"] += 1
                return out, sess.step, False
            finally:
                sess.lock.release()
        finally:
            served.unpin()

    def close(self, model_name: str, session_id: str) -> None:
        """End the stream: forget the memory copy AND the spill file."""
        key = (model_name, str(session_id))
        with self._lock:
            sess = self._sessions.pop(key, None)
        path = self._spill_path(model_name, session_id)
        if sess is not None:
            with sess.lock:  # let an in-flight step finish first
                self._remove_file(path)
        else:
            if not os.path.exists(path):
                raise KeyError(session_id)
            self._remove_file(path)
        with self._lock:
            self._counters["closes_total"] += 1
        journal.emit("session.close", model=model_name,
                     session=str(session_id), worker=self.worker_id)

    # ------------------------------------------------------------- residency
    def spill_all(self, reason: str = "drain") -> int:
        """Push every resident session cold (state already durable via
        write-through; this drops the memory copies and emits the
        spill/evict events). The migration fence a rolling deploy runs
        before restarting a worker — after it, any step landing anywhere
        rehydrates current state."""
        with self._lock:
            sessions = list(self._sessions.values())
        n = 0
        for sess in sessions:
            if self._evict_one(sess, reason, block_s=2.0):
                n += 1
        return n

    def _evict_one(self, sess: Session, reason: str,
                   block_s: float = 0.0) -> bool:
        if block_s > 0:
            acquired = sess.lock.acquire(timeout=block_s)
        else:
            acquired = sess.lock.acquire(blocking=False)
        if not acquired:
            return False  # busy stream: skip, next pass gets it
        try:
            if sess.state is None:
                return False
            if sess.spilled_step != sess.step:
                self._write_spill(sess)  # write-through should prevent this
            with self._lock:
                self._counters["spills_total"] += 1
                self._counters["evictions_total"] += 1
            journal.emit("session.spill", model=sess.model_name,
                         session=sess.session_id, step=sess.step,
                         bytes=sess.state_bytes, worker=self.worker_id)
            sess.state = None
            sess.last_out = None
            journal.emit("session.evict", model=sess.model_name,
                         session=sess.session_id, reason=reason,
                         worker=self.worker_id)
            return True
        finally:
            sess.lock.release()

    def _evict_pass(self) -> None:
        now = self._clock()
        with self._lock:
            resident = [s for s in self._sessions.values()
                        if s.state is not None]
        # idle-TTL first
        for sess in resident:
            if now - sess.touched >= self.idle_ttl_s:
                self._evict_one(sess, "idle_ttl")
        if self.byte_budget_bytes is None:
            return
        with self._lock:
            resident = [s for s in self._sessions.values()
                        if s.state is not None]
        total = sum(s.state_bytes for s in resident)
        if total <= self.byte_budget_bytes:
            return
        # LRU beyond the budget: coldest-touched first
        for sess in sorted(resident, key=lambda s: s.touched):
            if total <= self.byte_budget_bytes:
                break
            freed = sess.state_bytes
            if self._evict_one(sess, "byte_budget"):
                total -= freed

    def _run_evictor(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self._evict_pass()
            except Exception:
                logger.exception("session evictor pass failed")

    def shutdown(self, spill: bool = True) -> None:
        self._stop.set()
        if self._evictor is not None:
            self._evictor.join(timeout=5.0)
        if spill:
            try:
                self.spill_all(reason="shutdown")
            except Exception:
                logger.exception("session spill-all at shutdown failed")

    # --------------------------------------------------------------- spill io
    def _spill_path(self, model_name: str, session_id: str) -> str:
        return os.path.join(self.spill_dir,
                            f"{model_name}__{session_id}{_SPILL_SUFFIX}")

    @staticmethod
    def _remove_file(path: str) -> None:
        try:
            os.remove(path)
        except FileNotFoundError:
            pass

    def _write_spill(self, sess: Session) -> None:
        """Persist the carry with the checkpoint atomics: tmp file in the
        same directory, then ``os.replace`` — a reader sees the old frame
        or the new frame, never a torn one. Called under ``sess.lock``."""
        leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(sess.state)]
        header = {"v": 1, "model": sess.model_name,
                  "session": sess.session_id, "step": sess.step,
                  "worker": self.worker_id,
                  "incarnation": journal.incarnation(),
                  "out": None}
        if sess.last_out is not None:
            out = np.ascontiguousarray(sess.last_out)
            header["out"] = {"shape": list(out.shape),
                             "dtype": out.dtype.str}
            leaves = leaves + [out]
        raw = _pack_frame(header, leaves)
        path = self._spill_path(sess.model_name, sess.session_id)
        fd, tmp = tempfile.mkstemp(dir=self.spill_dir,
                                   prefix=f".{sess.session_id}-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(raw)
            os.replace(tmp, path)
        except BaseException:
            self._remove_file(tmp)
            raise
        sess.spilled_step = sess.step

    def _lookup_or_adopt(self, model_name: str, session_id: str) -> Session:
        """Find the session in memory, or ADOPT it cold from a spill file
        another worker (or a previous incarnation of this one) wrote —
        the failover/migration entry point. Unknown everywhere is
        ``KeyError`` (HTTP 404)."""
        key = (model_name, str(session_id))
        with self._lock:
            sess = self._sessions.get(key)
        if sess is not None:
            return sess
        if not os.path.exists(self._spill_path(model_name, session_id)):
            raise KeyError(session_id)
        sess = Session(model_name, str(session_id), self._clock())
        with self._lock:
            return self._sessions.setdefault(key, sess)

    def _rehydrate(self, sess: Session, served) -> None:
        """Read the spill frame back into memory (under ``sess.lock``).
        Any damage — chaos-injected or real — is :class:`SessionLost`."""
        t0 = time.monotonic()
        chaos.inject("serving.session.rehydrate")
        path = self._spill_path(sess.model_name, sess.session_id)
        try:
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except FileNotFoundError as e:
                raise SessionLost(
                    f"session {sess.session_id!r}: spill file vanished "
                    f"({path})") from e
            raw = chaos.transform_bytes("serving.session.rehydrate", raw)
            header, leaves = _unpack_frame(raw)
            out = None
            if header.get("out") is not None:
                if not leaves:
                    raise SessionLost("spill frame: output leaf missing")
                out, leaves = leaves[-1], leaves[:-1]
            template = served.batcher.session_state_template()
            tl = jax.tree_util.tree_leaves(template)
            if len(tl) != len(leaves):
                raise SessionLost(
                    f"spill frame: {len(leaves)} state leaves, model "
                    f"expects {len(tl)} — archive/state mismatch")
            for have, want in zip(leaves, tl):
                if tuple(have.shape) != tuple(np.shape(want)):
                    raise SessionLost(
                        f"spill frame: leaf shape {have.shape} != model "
                        f"carry shape {np.shape(want)}")
            sess.state = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(template), leaves)
            sess.last_out = out
            sess.step = int(header.get("step", 0))
            sess.spilled_step = sess.step
            sess.state_bytes = _tree_bytes(sess.state)
            sess.touched = self._clock()
        except SessionLost:
            # drop the record so every later step fails the same way
            # (410, not a silently-fresh stream); the file stays on disk
            # for forensics
            with self._lock:
                self._counters["lost_total"] += 1
                self._sessions.pop((sess.model_name, sess.session_id), None)
            raise
        seconds = time.monotonic() - t0
        self._rehydrate_hist.observe(seconds)
        with self._lock:
            self._counters["rehydrates_total"] += 1
        journal.emit("session.rehydrate", model=sess.model_name,
                     session=sess.session_id, step=sess.step,
                     seconds=round(seconds, 6), bytes=len(raw),
                     worker=self.worker_id)
        if header.get("worker") != self.worker_id or \
                header.get("incarnation") != journal.incarnation():
            # the frame was written by another worker (failover, rolling
            # deploy) or a previous life of this one — the stream MOVED
            with self._lock:
                self._counters["migrations_total"] += 1
            journal.emit("session.migrate", model=sess.model_name,
                         session=sess.session_id, step=sess.step,
                         from_worker=header.get("worker"),
                         to_worker=self.worker_id)

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Any]:
        """The ``/v1/capacity`` ``sessions`` section: counts, bytes,
        rehydrate latency percentiles, lifecycle counters."""
        with self._lock:
            sessions = list(self._sessions.values())
            counters = dict(self._counters)
        resident = [s for s in sessions if s.state is not None]
        try:
            spilled_files = len(glob.glob(os.path.join(
                self.spill_dir, f"*{_SPILL_SUFFIX}")))
        except OSError:
            spilled_files = 0
        h = self._rehydrate_hist
        return {
            "tracked": len(sessions),
            "resident": len(resident),
            "resident_bytes": sum(s.state_bytes for s in resident),
            "spilled_files": spilled_files,
            "idle_ttl_s": self.idle_ttl_s,
            "byte_budget_bytes": self.byte_budget_bytes,
            "counters": counters,
            "rehydrate": {
                "count": h.count,
                "p50_s": round(h.percentile(50), 6),
                "p99_s": round(h.percentile(99), 6),
                "max_s": round(h.max, 6),
            },
        }
