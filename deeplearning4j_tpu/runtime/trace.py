"""End-to-end distributed tracing: the flight recorder for the fleet and
the trainer (ISSUE 9 tentpole; ``docs/observability.md``).

The stack spans processes — ``FleetRouter`` -> supervised ``ModelServer``
workers -> batcher pipeline -> ``ReplicaPool``, and ``DistributedTrainer``
ranks — but until this module every observability surface was per-process
and per-subsystem (``/metrics`` histograms, profiler sections,
``ExchangeStats``). Nothing correlated ONE request (or one training step)
across those boundaries. This is the Dapper-shaped answer, and the analog
of the reference DL4J's ``StatsListener`` -> UI-server pipeline
(``docs/parity.md``): every unit of work is a :class:`Span` in a trace
tree, propagated

- **in-process** via a ``contextvars`` context (``span()`` parents to the
  caller's active span) and explicitly across the batcher's worker
  threads (a request's span rides its ``_Request``; batch stage spans
  parent to the first traced request of the batch), and
- **cross-process** via the ``X-Trace-Id`` / ``X-Parent-Span-Id`` HTTP
  headers, piggybacking the fleet tier's existing ``X-Request-Id`` /
  ``X-Deadline-Ms`` plumbing — the router's attempt span id becomes the
  worker's root span parent, so router-side aggregation
  (``FleetRouter /v1/traces``) can merge worker spans into one tree.

Design constraints (the serving hot path calls into this unconditionally):

- **Disabled = no-op fast path, zero allocations.** With tracing off (the
  default; ``enable()`` never called, or rate 0 via ``DL4J_TPU_TRACE``),
  ``span(name)`` is one module-global load, an ``is None`` test, and the
  return of a shared singleton no-op span — nothing allocates, nothing
  locks, and ``current_span()`` is ``None`` (``bench.py
  --trace-overhead`` asserts this path is allocation-free and
  bit-identical).
- **Tail-based sampling.** While enabled, every request is *recorded*;
  the keep/drop decision happens when the trace completes (root span and
  every late child — e.g. a hedge loser — finished): a trace that was
  flagged (``shed``, ``fault``, ``hedged``, ``deadline``, ``chaos``,
  ``slow``) is ALWAYS kept; a healthy trace is kept with probability
  ``rate`` (so ``enable(rate=0.0)`` keeps exactly the interesting
  traces). This is what makes a post-hoc fault-drill investigation
  possible without paying for healthy traffic.
- **Bounded memory.** Kept traces land in a fixed-capacity lock-free
  ring buffer (:class:`TraceCollector`) — one slot store per trace, old
  traces overwritten, no growth under sustained load.
- **Monotonic timing.** Span durations come from ``time.monotonic()``;
  a wall-clock anchor per span start orders spans across processes
  (same-host skew is microseconds — the fleet topology this serves).

Export: :func:`to_chrome_trace` renders trace records as Chrome
trace-event JSON (``chrome://tracing`` / Perfetto's legacy loader —
``ph: "X"`` complete events per span, ``ph: "i"`` instants per chaos
stamp); :func:`merge_traces` merges multi-process records by trace id
(span-id deduplicated); :func:`span_tree` rebuilds the parent/child tree.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import random
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "Span", "TraceCollector", "TraceConfig", "enable", "disable", "enabled",
    "span", "server_span", "current_span", "current_trace_id", "collector",
    "flag_current", "annotate_current", "stamp_chaos", "stage_event",
    "merge_traces", "span_tree", "to_chrome_trace", "set_process_tag",
    "process_tag",
    "access_log_enabled", "emit_access_log", "bound_traces",
    "TRACES_RESPONSE_BYTE_CAP", "NOOP",
]

_CURRENT: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("dl4j_tpu_trace_span", default=None)

_ids = itertools.count(1)
# per-process random base: ids are collision-free within a process by the
# counter and across processes by the base; formatting one small counter
# is several times cheaper than drawing fresh random bits per span (this
# runs on the serving hot path for every recorded span)
_ID_BASE = f"{random.getrandbits(48):012x}"


def _new_id() -> str:
    """Process-unique span/trace id (no uuid machinery on the recording
    path)."""
    return _ID_BASE + format(next(_ids), "08x")


# ---------------------------------------------------------------- collector
class TraceCollector:
    """Bounded lock-free ring buffer of kept trace records.

    ``record`` is a single slot store (the index comes from an
    ``itertools.count``, atomic under the GIL) — no lock on the keep path;
    a full ring overwrites the oldest trace. Readers snapshot the slots.
    """

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        # each slot holds (insertion seq, record) or None
        self._slots: List[Optional[tuple]] = [None] * self.capacity
        self._n = itertools.count()
        # kept/dropped are per-TRACE (not per-span) counters; a plain
        # `+= 1` from concurrent finalizing threads loses updates, so
        # they take a (rarely contended) lock — the slot store itself
        # stays lock-free via the atomic counter
        self._count_lock = threading.Lock()  # guards: kept, dropped
        self.kept = 0        # traces stored (monotonic; ring may overwrite)
        self.dropped = 0     # completed traces the sampler discarded

    def record(self, rec: Dict[str, Any]) -> None:
        n = next(self._n)
        self._slots[n % self.capacity] = (n, rec)
        with self._count_lock:
            self.kept += 1

    def record_dropped(self) -> None:
        with self._count_lock:
            self.dropped += 1

    def traces(self) -> List[Dict[str, Any]]:
        """Recent kept traces, oldest first (at most ``capacity``). Slots
        carry their insertion sequence so order survives ring wraparound
        (the read path is not hot; sorting <= capacity entries is fine)."""
        entries = [e for e in list(self._slots) if e is not None]
        entries.sort(key=lambda e: e[0])
        return [rec for _, rec in entries]

    def find(self, trace_id: str) -> Optional[Dict[str, Any]]:
        for rec in reversed(self.traces()):
            if rec.get("trace_id") == trace_id:
                return rec
        return None

    def clear(self) -> None:
        self._slots = [None] * self.capacity


# ------------------------------------------------------------------- config
class TraceConfig:
    """Sampling policy: ``rate`` is the probability of keeping a HEALTHY
    completed trace; flagged traces (shed/fault/hedged/deadline/chaos/slow)
    are always kept. ``latency_threshold_ms`` flags any trace whose root
    span exceeds it (``slow``). ``seed`` makes the probabilistic decision
    replayable in tests."""

    __slots__ = ("rate", "latency_threshold_ms", "_rng", "_rng_lock")

    def __init__(self, rate: float = 0.0,
                 latency_threshold_ms: Optional[float] = None,
                 seed: Optional[int] = None):
        if not 0.0 <= float(rate) <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        self.rate = float(rate)
        self.latency_threshold_ms = (None if latency_threshold_ms is None
                                     else float(latency_threshold_ms))
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()  # guards: _rng

    def keep(self, flagged: bool) -> bool:
        if flagged:
            return True
        if self.rate <= 0.0:
            return False
        if self.rate >= 1.0:
            return True
        with self._rng_lock:
            return self._rng.random() < self.rate


_CONFIG: Optional[TraceConfig] = None
_COLLECTOR = TraceCollector()
_PROCESS_TAG = f"pid-{os.getpid()}"


def set_process_tag(tag: str) -> None:
    """Name this process in exported traces (``ModelServer`` sets its
    ``worker_id``; defaults to ``pid-<n>``)."""
    global _PROCESS_TAG
    _PROCESS_TAG = str(tag)


def process_tag() -> str:
    """This process's tag — shared with the event journal
    (``runtime/journal.py``) so journal events and trace spans name the
    same process the same way."""
    return _PROCESS_TAG


def enable(rate: float = 0.0, latency_threshold_ms: Optional[float] = None,
           capacity: Optional[int] = None,
           seed: Optional[int] = None) -> TraceConfig:
    """Turn tracing on with the given tail-sampling policy. ``capacity``
    (when given) replaces the process collector with a fresh ring of that
    size. Returns the installed config."""
    global _CONFIG, _COLLECTOR
    if capacity is not None:
        _COLLECTOR = TraceCollector(capacity)
    _CONFIG = TraceConfig(rate, latency_threshold_ms, seed)
    return _CONFIG


def disable() -> None:
    """Back to the no-op fast path (in-flight traces finish un-kept)."""
    global _CONFIG
    _CONFIG = None


def enabled() -> bool:
    return _CONFIG is not None


def collector() -> TraceCollector:
    return _COLLECTOR


# -------------------------------------------------------------- trace state
class _TraceState:
    """Per-trace accumulation shared by every span of one trace in one
    process: the span buffer, the flag set, and the open-span count that
    defers the tail-sampling decision until the LAST span (e.g. a hedge
    loser completing after the root) has finished."""

    __slots__ = ("trace_id", "spans", "flags", "open", "root_done", "lock")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.spans: List[Dict[str, Any]] = []
        self.flags: set = set()
        self.open = 0
        self.root_done = False
        self.lock = threading.Lock()  # guards: open, root_done

    def span_started(self) -> None:
        with self.lock:
            self.open += 1

    def span_finished(self, span: "Span") -> None:
        """Buffer the finished Span OBJECT — serialization to dicts is
        deferred to :meth:`_finalize` and paid only for KEPT traces (at a
        sampling rate of r, 1-r of the traffic skips it entirely)."""
        with self.lock:
            self.spans.append(span)
            self.open -= 1
            if span._is_root:
                self.root_done = True
            done = self.root_done and self.open == 0
        if done:
            self._finalize()

    def _finalize(self) -> None:
        cfg = _CONFIG
        spans, self.spans = self.spans, []  # break the span<->state cycle
        if cfg is None:
            return  # tracing was disabled mid-trace: drop silently
        if cfg.keep(bool(self.flags)):
            _COLLECTOR.record({
                "trace_id": self.trace_id,
                "process": _PROCESS_TAG,
                "flags": sorted(self.flags),
                "spans": [s.to_dict() for s in spans],
            })
        else:
            _COLLECTOR.record_dropped()


# --------------------------------------------------------------------- span
class Span:
    """One timed unit of work. Use as a context manager; annotate with
    :meth:`set`, stamp point events with :meth:`event`, and mark the whole
    trace interesting with :meth:`flag`. ``child()`` creates an
    explicitly-parented span for work handed to another thread (the
    batcher's stage threads)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_ts",
                 "_t0", "duration_s", "annotations", "events", "thread",
                 "_state", "_token", "_is_root", "_done")

    recording = True

    def __init__(self, name: str, state: _TraceState,
                 parent_id: Optional[str], is_root: bool):
        self.name = name
        self.trace_id = state.trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.start_ts = time.time()
        self._t0 = time.monotonic()
        self.duration_s: Optional[float] = None
        self.annotations: Dict[str, Any] = {}
        self.events: Optional[List[Dict[str, Any]]] = None  # lazy: rare
        self.thread = threading.current_thread().name
        self._state = state
        self._token = None
        self._is_root = is_root
        self._done = False
        state.span_started()

    # ------------------------------------------------------------ recording
    def set(self, key: str, value: Any) -> "Span":
        self.annotations[key] = value
        return self

    def event(self, name: str, **attrs: Any) -> "Span":
        if self.events is None:
            self.events = []
        self.events.append({"name": name, "ts": time.time(),
                            "offset_ms": round(
                                (time.monotonic() - self._t0) * 1e3, 3),
                            **attrs})
        return self

    def flag(self, reason: str) -> "Span":
        """Mark the whole trace as always-keep (tail sampling)."""
        with self._state.lock:
            self._state.flags.add(str(reason))
        return self

    def child(self, name: str) -> "Span":
        """A child span of THIS span (explicit parentage — safe from any
        thread, independent of the calling thread's context)."""
        return Span(name, self._state, self.span_id, is_root=False)

    # ------------------------------------------------------------- lifecycle
    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        if exc is not None and not self._done:
            self.set("error", type(exc).__name__)
            self.flag("fault")
        self.finish()

    def finish(self) -> None:
        if self._done:
            return
        self._done = True
        self.duration_s = time.monotonic() - self._t0
        cfg = _CONFIG
        if (self._is_root and cfg is not None
                and cfg.latency_threshold_ms is not None
                and self.duration_s * 1e3 > cfg.latency_threshold_ms):
            self.flag("slow")
        self._state.span_finished(self)

    def to_dict(self) -> Dict[str, Any]:
        """Serialize (called once, at keep-time, for kept traces only —
        the span is finished and immutable, so no defensive copies)."""
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start_ts": self.start_ts,
                "duration_s": self.duration_s, "thread": self.thread,
                "process": _PROCESS_TAG,
                "annotations": self.annotations,
                "events": self.events or []}


class _NoopSpan:
    """The shared do-nothing span returned while tracing is disabled —
    every method is a constant-return no-op, ``with`` works, nothing
    allocates. There is exactly ONE instance (:data:`NOOP`)."""

    __slots__ = ()
    recording = False
    trace_id = None
    span_id = None
    annotations: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []

    def set(self, key, value):
        return self

    def event(self, name, **attrs):
        return self

    def flag(self, reason):
        return self

    def child(self, name):
        return self

    def finish(self):
        return None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return None


NOOP = _NoopSpan()


# ------------------------------------------------------------- entry points
def span(name: str) -> Any:
    """A span parented to the calling context's active span (or a new
    trace root when there is none). THE hot-path entry point: with tracing
    disabled this is one global load + ``is None`` + singleton return —
    zero allocations."""
    if _CONFIG is None:
        return NOOP
    cur = _CURRENT.get()
    if cur is not None and cur.recording:
        return Span(name, cur._state, cur.span_id, is_root=False)
    return Span(name, _TraceState(_new_id()), None, is_root=True)


def server_span(name: str, trace_id: Optional[str] = None,
                parent_id: Optional[str] = None) -> Any:
    """A request-root span continuing a REMOTE trace: ``trace_id`` /
    ``parent_id`` come off the ``X-Trace-Id`` / ``X-Parent-Span-Id``
    headers (absent -> a fresh trace). This span is the local root — its
    completion (plus any late children) triggers the tail-sampling
    decision for this process's part of the trace."""
    if _CONFIG is None:
        return NOOP
    state = _TraceState(str(trace_id) if trace_id else _new_id())
    return Span(name, state, str(parent_id) if parent_id else None,
                is_root=True)


def current_span() -> Optional[Span]:
    if _CONFIG is None:
        return None
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    sp = current_span()
    return sp.trace_id if sp is not None else None


def flag_current(reason: str) -> None:
    sp = current_span()
    if sp is not None:
        sp.flag(reason)


def annotate_current(key: str, value: Any) -> None:
    sp = current_span()
    if sp is not None:
        sp.set(key, value)


def stamp_chaos(point: str, action: str) -> None:
    """Stamp a chaos-injection decision onto the active span (called by
    :mod:`deeplearning4j_tpu.runtime.chaos` for every policy action) and
    flag the trace ``chaos`` — every fault drill is traceable after the
    fact, and tail sampling always keeps it."""
    sp = current_span()
    if sp is not None:
        sp.event("chaos", point=point, action=action)
        sp.flag("chaos")


def stage_event(stage: str, seconds: float) -> None:
    """Stamp a named stage duration (encode/exchange/decode/apply,
    data_wait/dispatch/step) onto the active span — the bridge from the
    existing ``ExchangeStats`` / ``TrainingProfiler`` hooks into the
    trace tree."""
    if _CONFIG is None:
        return
    sp = _CURRENT.get()
    if sp is not None:
        sp.event("stage", stage=stage, seconds=round(float(seconds), 6))


# ------------------------------------------------------- merge / tree / export
def merge_traces(records: Iterable[Dict[str, Any]]
                 ) -> List[Dict[str, Any]]:
    """Merge per-process trace records by trace id into one record per
    trace (spans concatenated, de-duplicated by span id; flags unioned;
    contributing processes listed). The router's ``/v1/traces``
    aggregation is this function over its own collector plus every
    worker's."""
    by_id: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        tid = rec.get("trace_id")
        if tid is None:
            continue
        m = by_id.get(tid)
        if m is None:
            m = by_id[tid] = {"trace_id": tid, "flags": set(),
                              "processes": [], "spans": [], "_seen": set()}
        m["flags"].update(rec.get("flags", ()))
        proc = rec.get("process")
        if proc and proc not in m["processes"]:
            m["processes"].append(proc)
        for s in rec.get("spans", ()):
            sid = s.get("span_id")
            if sid in m["_seen"]:
                continue
            m["_seen"].add(sid)
            m["spans"].append(s)
    out = []
    for m in by_id.values():
        m.pop("_seen")
        m["flags"] = sorted(m["flags"])
        m["spans"].sort(key=lambda s: s.get("start_ts") or 0.0)
        out.append(m)
    out.sort(key=lambda m: min((s.get("start_ts") or 0.0
                                for s in m["spans"]), default=0.0))
    return out


def span_tree(record: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Rebuild the span tree of one (merged) trace record: returns the
    root spans, each with a ``children`` list, children sorted by start
    time. A span whose parent is not in the record (a remote parent whose
    process was not scraped) becomes a root."""
    spans = [dict(s) for s in record.get("spans", ())]
    by_id = {s["span_id"]: s for s in spans}
    roots = []
    for s in spans:
        s.setdefault("children", [])
    for s in spans:
        parent = by_id.get(s.get("parent_id"))
        if parent is None:
            roots.append(s)
        else:
            parent["children"].append(s)
    for s in spans:
        s["children"].sort(key=lambda c: c.get("start_ts") or 0.0)
    roots.sort(key=lambda s: s.get("start_ts") or 0.0)
    return roots


def to_chrome_trace(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Render trace records as Chrome trace-event JSON (the format
    Perfetto's legacy importer and ``chrome://tracing`` load): one
    ``ph: "X"`` complete event per span (``ts``/``dur`` in microseconds,
    wall-clock anchored), one ``ph: "i"`` instant per span event (chaos
    stamps, stage marks), ``pid`` = originating process tag, ``tid`` =
    recording thread."""
    events: List[Dict[str, Any]] = []
    for rec in records:
        for s in rec.get("spans", ()):
            ts_us = (s.get("start_ts") or 0.0) * 1e6
            events.append({
                "name": s["name"], "ph": "X",
                "ts": ts_us, "dur": (s.get("duration_s") or 0.0) * 1e6,
                "pid": s.get("process", rec.get("process", "?")),
                "tid": s.get("thread", "?"),
                "args": {"trace_id": rec.get("trace_id"),
                         "span_id": s.get("span_id"),
                         "parent_id": s.get("parent_id"),
                         **(s.get("annotations") or {})},
            })
            for ev in s.get("events", ()):
                attrs = {k: v for k, v in ev.items()
                         if k not in ("name", "ts", "offset_ms")}
                events.append({
                    "name": f"{s['name']}:{ev['name']}", "ph": "i", "s": "t",
                    "ts": (ev.get("ts") or 0.0) * 1e6,
                    "pid": s.get("process", rec.get("process", "?")),
                    "tid": s.get("thread", "?"),
                    "args": attrs,
                })
    events.sort(key=lambda e: e["ts"])
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ------------------------------------------------------------ read bounding
#: hard cap on one ``/v1/traces`` response body (serialized record bytes):
#: a scrape of a full ring must never produce an unbounded HTTP body — a
#: 256-slot ring of deep fleet traces can reach tens of MB (ISSUE 10).
TRACES_RESPONSE_BYTE_CAP = 4 * 1024 * 1024


def _record_newest_ts(rec: Dict[str, Any]) -> float:
    return max((s.get("start_ts") or 0.0 for s in rec.get("spans", ())),
               default=0.0)


def bound_traces(records: Iterable[Dict[str, Any]],
                 limit: Optional[int] = None,
                 since: Optional[float] = None,
                 max_bytes: Optional[int] = None):
    """Bound a trace-record read (the ``/v1/traces`` handlers' shared
    selection): ``since`` keeps records whose newest span started at or
    after the given wall-clock time, ``limit`` keeps the newest N, and
    the serialized size of what remains is capped at ``max_bytes``
    (default :data:`TRACES_RESPONSE_BYTE_CAP`) by dropping oldest-first —
    the newest record is always returned even if it alone exceeds the
    cap, so a scrape can never come back empty-but-truncated. Returns
    ``(records_oldest_first, truncated)``. Records are (re)ordered by
    their newest span's start time first, so "newest N" means newest in
    TIME even when the input interleaves several processes' records
    (the router's merge orders by *earliest* span — an overlapping
    long-lived trace would otherwise outrank a genuinely newer one)."""
    recs = sorted(records, key=_record_newest_ts)
    if since is not None:
        recs = [r for r in recs if _record_newest_ts(r) >= float(since)]
    truncated = False
    if limit is not None and limit >= 0 and len(recs) > int(limit):
        truncated = True
        recs = recs[len(recs) - int(limit):]
    cap = TRACES_RESPONSE_BYTE_CAP if max_bytes is None else int(max_bytes)
    total, kept = 0, []
    for r in reversed(recs):               # newest first
        size = len(json.dumps(r, default=str).encode())
        if kept and total + size > cap:
            truncated = True
            break
        kept.append(r)
        total += size
        if total > cap:                    # single over-cap record: keep it
            truncated = truncated or len(kept) < len(recs)
            break
    kept.reverse()
    return kept, truncated


# --------------------------------------------------------------- access log
#: spellings that DISABLE the access log — aligned with the journal's
#: ``DL4J_TPU_JOURNAL`` parsing, so "off"/"no" can never be mistaken
#: for a file literally named ./off
_ACCESS_LOG_OFF = ("", "0", "false", "off", "no")
#: bare truthy spellings that mean "enabled, to stderr" (the original
#: behaviour); anything else is a file path
_ACCESS_LOG_STDERR = ("1", "true", "on", "yes")


def access_log_enabled() -> bool:
    """The ``DL4J_TPU_ACCESS_LOG`` env knob (off by default): one
    structured JSON line per terminal request outcome — to stderr for
    the bare truthy spellings, to a FILE when the value is a path."""
    return os.environ.get("DL4J_TPU_ACCESS_LOG",
                          "").strip().lower() not in _ACCESS_LOG_OFF


def _access_log_path() -> Optional[str]:
    """The access-log destination file, or ``None`` for stderr (the
    original behaviour for bare truthy spellings of the knob)."""
    v = os.environ.get("DL4J_TPU_ACCESS_LOG", "")
    if v.strip().lower() in _ACCESS_LOG_OFF + _ACCESS_LOG_STDERR:
        return None
    return v


def _access_log_max_bytes() -> int:
    """``DL4J_TPU_ACCESS_LOG_MAX_BYTES``: size-based rotation threshold
    for the file form (0 / unset / unparsable = no rotation)."""
    try:
        return max(0, int(os.environ.get(
            "DL4J_TPU_ACCESS_LOG_MAX_BYTES", "0")))
    except ValueError:
        return 0


# serializes the size check + rename + append so concurrent request
# threads cannot double-rotate or interleave partial lines
_ACCESS_LOG_LOCK = threading.Lock()  # guards: (access-log rotate+append)


def emit_access_log(record: Dict[str, Any]) -> None:
    """Write one JSON access-log line (no-op unless
    :func:`access_log_enabled`). When ``DL4J_TPU_ACCESS_LOG`` is a file
    path, lines append there with size-based rotation (ISSUE 15): once
    the file would exceed ``DL4J_TPU_ACCESS_LOG_MAX_BYTES`` it is
    atomically renamed to ``<path>.1`` (keep-1 rollover — a soak can
    never grow the log unbounded) and a fresh file starts. Never raises
    — logging must not be able to fail a request."""
    if not access_log_enabled():
        return
    try:
        line = json.dumps({"log": "dl4j_tpu_access", **record},
                          default=str) + "\n"
        path = _access_log_path()
        if path is None:
            sys.stderr.write(line)
            sys.stderr.flush()
            return
        max_bytes = _access_log_max_bytes()
        with _ACCESS_LOG_LOCK:
            if max_bytes:
                try:
                    size = os.path.getsize(path)
                except OSError:
                    size = 0
                if size and size + len(line.encode()) > max_bytes:
                    os.replace(path, path + ".1")  # atomic keep-1 rollover
            with open(path, "a") as f:
                f.write(line)
    except Exception:
        pass


# env bootstrap: DL4J_TPU_TRACE=<rate> enables tracing at import (fleet
# worker subprocesses inherit the parent's env, so one knob traces the
# whole fleet; 0/absent keeps the no-op fast path; bare truthy spellings
# mean rate 1.0, matching the DL4J_TPU_ACCESS_LOG knob's convention).
# DL4J_TPU_TRACE_SLOW_MS=<ms> sets the worker-side slow threshold — and
# by itself enables tracing at rate 0, the shape that closes PR 9's
# documented per-process tail-sampling gap: a slow-but-healthy hedge
# LOSER has nothing local to flag, so the straggling worker's half of
# the trace self-keeps by flagging itself `slow` even at rate 0.
def _env_config(environ) -> Optional[tuple]:
    """Parse the two env knobs into ``(rate, latency_threshold_ms)``, or
    ``None`` when tracing should stay on the no-op fast path. Pure so
    the precedence rules are unit-testable without re-importing."""
    rate_s = environ.get("DL4J_TPU_TRACE", "").strip().lower()
    slow_s = environ.get("DL4J_TPU_TRACE_SLOW_MS", "").strip()
    slow_ms: Optional[float] = None
    if slow_s:
        try:
            slow_ms = float(slow_s)
        except ValueError:
            slow_ms = None
        if slow_ms is not None and slow_ms <= 0:
            slow_ms = None
    rate: Optional[float] = None
    if rate_s not in ("", "0", "0.0", "false", "off", "no"):
        try:
            rate = 1.0 if rate_s in ("true", "on", "yes") else float(rate_s)
        except ValueError:
            rate = None
        if rate is not None and not 0.0 <= rate <= 1.0:
            rate = None
    if rate is None and slow_ms is None:
        return None
    return (rate if rate is not None else 0.0, slow_ms)


_env_cfg = _env_config(os.environ)
if _env_cfg is not None:
    enable(rate=_env_cfg[0], latency_threshold_ms=_env_cfg[1])
del _env_cfg
