"""Small-leaf train-state packing: flat-buffer storage for the step boundary.

TPU-native analog of the reference's flat-parameter design (upstream
``MultiLayerNetwork.init()`` flattens every layer's parameters into ONE
``INDArray`` and hands layers views — ``org.deeplearning4j.nn.multilayer.
MultiLayerNetwork``, ``ParamInitializer``; SURVEY.md §3.1). There the flat
buffer made updater application and parameter averaging cheap; here it cuts
the *dispatch* cost of a jitted train step.

Why it matters on this runtime: a ResNet-50 ``TrainState`` is 429 leaves, of
which 371 are tiny per-channel vectors (BN gamma/beta/mean/var + their
momenta — 13 MB total). Every step dispatch marshals one buffer handle per
leaf through the PJRT tunnel (~0.1-0.15 ms each ≈ 40 ms/step, partially
hidden behind the ~94 ms device step), and on-device XLA stages each tiny
buffer into scratch memory with its own async copy pair (~1500 copies/step,
~2.5 ms measured). Packing every sub-threshold leaf into one flat buffer per
dtype collapses both costs; values are bit-identical (pack/unpack is pure
reshape/slice plumbing inside the same jitted program).

Sharded training keeps per-leaf state (packing would force one common
sharding across leaves); this is the single-device/replicated fast path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import warnings
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@contextlib.contextmanager
def _quiet_donation():
    """The one-off pack/unpack donations intentionally donate many tiny
    leaves that XLA cannot alias into the concatenated buffer (it copies
    them instead — exactly the desired semantics); silence jax's
    per-compile warning about it."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield

# One packed segment: leaf index in tree_flatten order, original shape,
# dtype name, offset (elements) into that dtype's flat buffer, element count.
_Spec = Tuple[int, Tuple[int, ...], str, int, int]

#: Leaves at or below this byte size are packed (conv kernels / embedding
#: tables stay standalone so their tiled layouts are preserved).
DEFAULT_MAX_LEAF_BYTES = 1 << 20

#: Segment alignment in elements — keeps every slice on a lane-tile boundary
#: so unpacked views never need a layout conversion.
DEFAULT_ALIGN = 1024


class LeafPacker:
    """Packs all small leaves of a pytree into one flat buffer per dtype.

    ``pack``/``unpack`` are pure, jittable, and exact inverses; use them
    INSIDE a jitted step so the step's boundary carries the flat buffers::

        packer = LeafPacker(train_state)
        def packed_step(pts, *args):
            ts = packer.unpack(pts)
            new_ts, loss = step(ts, *args)
            return packer.pack(new_ts), loss

    The packed representation is ``(buffers, kept)`` where ``buffers`` maps
    dtype name -> 1-D array and ``kept`` is the list of above-threshold
    leaves in tree order — a plain pytree, so donation works unchanged.
    """

    def __init__(self, template: Any, max_leaf_bytes: int = DEFAULT_MAX_LEAF_BYTES,
                 align: int = DEFAULT_ALIGN):
        leaves, treedef = jax.tree_util.tree_flatten(template)
        self._treedef = treedef
        self._n_leaves = len(leaves)
        self._specs: List[_Spec] = []
        self._kept_idx: List[int] = []
        self._sizes: Dict[str, int] = {}
        for i, leaf in enumerate(leaves):
            if not hasattr(leaf, "dtype") or not hasattr(leaf, "size"):
                self._kept_idx.append(i)  # non-array leaf (plain Python value)
                continue
            nbytes = leaf.size * jnp.dtype(leaf.dtype).itemsize
            if nbytes <= max_leaf_bytes and leaf.ndim <= 2:
                dt = jnp.dtype(leaf.dtype).name
                off = self._sizes.get(dt, 0)
                n = int(leaf.size)
                self._specs.append((i, tuple(leaf.shape), dt, off, n))
                self._sizes[dt] = off + ((n + align - 1) // align) * align
            else:
                self._kept_idx.append(i)

    @property
    def n_packed(self) -> int:
        return len(self._specs)

    @property
    def n_kept(self) -> int:
        return len(self._kept_idx)

    def stats(self) -> Dict[str, Any]:
        return {
            "leaves": self._n_leaves,
            "packed": self.n_packed,
            "kept": self.n_kept,
            "buffer_bytes": {dt: n * jnp.dtype(dt).itemsize
                             for dt, n in self._sizes.items()},
        }

    # ------------------------------------------------------------------ pack
    def pack(self, tree: Any) -> Tuple[Dict[str, jax.Array], List[jax.Array]]:
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if treedef != self._treedef:
            raise ValueError(
                "LeafPacker.pack: tree structure differs from the template "
                f"this packer was built for ({treedef} vs {self._treedef})")
        segments: Dict[str, List[jax.Array]] = {dt: [] for dt in self._sizes}
        cursor: Dict[str, int] = {dt: 0 for dt in self._sizes}
        for i, shape, dt, off, n in self._specs:
            if jnp.dtype(leaves[i].dtype).name != dt:
                # a silent astype here would mask a stale packer (e.g. an
                # f32 checkpoint restored into a bf16-template packer) as
                # precision loss; raising routes callers to rebuild
                raise ValueError(
                    f"LeafPacker.pack: leaf {i} is {leaves[i].dtype}, the "
                    f"packer template recorded {dt} — rebuild the packer "
                    "for the current state")
            pad_to = off - cursor[dt]
            if pad_to:  # alignment gap from the PREVIOUS segment
                segments[dt].append(jnp.zeros((pad_to,), dtype=dt))
            segments[dt].append(leaves[i].reshape((n,)))
            cursor[dt] = off + n
        buffers = {}
        for dt, total in self._sizes.items():
            if total - cursor[dt]:
                segments[dt].append(jnp.zeros((total - cursor[dt],), dtype=dt))
            buffers[dt] = (jnp.concatenate(segments[dt]) if len(segments[dt]) > 1
                           else segments[dt][0])
        kept = [leaves[i] for i in self._kept_idx]
        return buffers, kept

    # ---------------------------------------------------------------- unpack
    def unpack(self, packed: Tuple[Dict[str, jax.Array], List[jax.Array]]) -> Any:
        buffers, kept = packed
        leaves: List[Any] = [None] * self._n_leaves
        for i, shape, dt, off, n in self._specs:
            leaves[i] = lax.slice(buffers[dt], (off,), (off + n,)).reshape(shape)
        for j, i in enumerate(self._kept_idx):
            leaves[i] = kept[j]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    @staticmethod
    def is_dead(packed) -> bool:
        """True if a donated step consumed these buffers (it dispatched,
        then raised): no post-step state exists anywhere."""
        buffers, kept = packed
        return (any(a.is_deleted() for a in buffers.values())
                or any(a.is_deleted() for a in kept
                       if hasattr(a, "is_deleted")))

    # ------------------------------------------------------------ round trip
    def pack_device(self, tree: Any):
        """Jitted pack (fit-loop entry). DONATES the input tree: kept big
        leaves alias through (no copy), and the caller's original per-leaf
        state is consumed — so only ONE full copy of the state exists while
        a packed loop runs. Wrapper cached so repeat packs don't retrace."""
        if not hasattr(self, "_pack_jit"):
            self._pack_jit = jax.jit(self.pack, donate_argnums=(0,))
        with _quiet_donation():
            return self._pack_jit(tree)

    def unpack_device(self, packed, donate: bool = False):
        """Jitted unpack (fit-loop exit / listener access); cached wrappers.
        ``donate=True`` consumes the packed buffers (kept leaves alias
        through) — use when the packed copy is being released."""
        if donate:
            if not hasattr(self, "_unpack_jit_donate"):
                self._unpack_jit_donate = jax.jit(self.unpack, donate_argnums=(0,))
            with _quiet_donation():
                return self._unpack_jit_donate(packed)
        if not hasattr(self, "_unpack_jit"):
            self._unpack_jit = jax.jit(self.unpack)
        return self._unpack_jit(packed)


def make_unrolled_packed_step(raw_step, packer, k: int):
    """One jitted program running ``k`` sequential train steps
    (env.dispatch_unroll). The per-step argument tuples arrive as a LIST
    pytree — never pre-stacked on device, which would cost one tiny
    dispatch per array per group (the very overhead grouping removes).
    Shared by MultiLayerNetwork and ComputationGraph (both raw steps take
    ``(train_state, *step_args)`` and return ``(new_state, loss)``)."""
    def unrolled(pts, args_list):
        ts = packer.unpack(pts)
        losses = []
        for i in range(k):
            ts, loss = raw_step(ts, *args_list[i])
            losses.append(loss)
        return packer.pack(ts), jnp.stack(losses)

    return jax.jit(unrolled, donate_argnums=(0,))


def make_unrolled_step(raw_step, k: int):
    """One jitted program running ``k`` sequential train steps over
    PER-LEAF state — the sharded-training counterpart of
    :func:`make_unrolled_packed_step` (sharded training cannot pack: one
    flat buffer would force a common sharding across leaves, see module
    docstring). Used by ``ParallelWrapper`` to honor
    ``env.dispatch_unroll`` on a mesh; state donated, losses stacked."""
    def unrolled(ts, args_list):
        losses = []
        for i in range(k):
            ts, loss = raw_step(ts, *args_list[i])
            losses.append(loss)
        return ts, jnp.stack(losses)

    return jax.jit(unrolled, donate_argnums=(0,))


class GroupedDispatch:
    """Buffer-and-flush protocol for grouped dispatch, shared by the fit
    loops (a raising listener or iterator must never leave an executed
    group buffered — the exceptional-exit flush would train it twice, a
    bug reproduced in review before this class existed).

    - ``run_single(args) -> loss`` and ``run_group([args, ...]) -> [loss]``
      perform the dispatches;
    - ``compatible(a, b)`` says whether two buffered tuples may share one
      unrolled program (same shapes / mask presence);
    - ``deliver(args, loss)`` does the caller's per-step bookkeeping
      (score, iteration counters, listeners) in submission order.
    """

    def __init__(self, unroll: int, compatible, run_single, run_group,
                 deliver):
        self._unroll = max(1, int(unroll))
        self._compatible = compatible
        self._run_single = run_single
        self._run_group = run_group
        self._deliver = deliver
        self._pending: list = []

    def submit(self, args) -> None:
        if self._unroll <= 1:
            self._deliver(args, self._run_single(args))
            return
        if self._pending and not self._compatible(self._pending[0], args):
            self.flush()
        self._pending.append(args)
        if len(self._pending) >= self._unroll:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        # snapshot-and-clear BEFORE dispatch/listeners (see class docstring)
        todo = list(self._pending)
        self._pending.clear()
        if len(todo) == self._unroll and self._unroll > 1:
            losses = self._run_group(todo)
        else:  # partial tail group: single steps avoid a fresh compile
            losses = [self._run_single(a) for a in todo]
        for args, loss in zip(todo, losses):
            self._deliver(args, loss)

    def drain_on_error(self) -> None:
        """Best-effort flush for exceptional exits: deliver batches that
        were buffered but never dispatched; if the state itself is dead (a
        raising donated step), drop them without masking the original
        exception."""
        try:
            self.flush()
        except Exception:
            self._pending.clear()


def step_args_signature(args) -> tuple:
    """Cheap structural signature of a step's per-batch argument tuple
    (shapes/dtypes of arrays, None-ness of masks, dict/list structure) —
    the :class:`~deeplearning4j_tpu.runtime.compile_cache.AotCache` key for
    the fit loops. Dtypes are canonicalized (an np.float64 batch lands on
    the float32 program when x64 is off, for jit and compiled executables
    alike). Collisions are safe (the executable's argument check falls
    back to jit); misses only cost one extra lower+compile."""
    def leaf(a):
        if a is None:
            return None
        if isinstance(a, dict):
            return tuple(sorted((k, leaf(v)) for k, v in a.items()))
        if isinstance(a, (list, tuple)):
            return tuple(leaf(v) for v in a)
        shape = getattr(a, "shape", None)
        if shape is None:
            return type(a).__name__
        try:
            dt = str(jax.dtypes.canonicalize_dtype(a.dtype))
        except (TypeError, ValueError):  # extended dtypes (typed PRNG keys)
            dt = str(a.dtype)
        return tuple(shape), dt

    return tuple(leaf(a) for a in args)


class PackedStepLoop:
    """Drives a network's jitted train step with packed state inside ``fit``.

    Lazily packs ``net.train_state`` on the first :meth:`step`; callers must
    :meth:`sync` before anything else reads or writes ``net.train_state``
    (listeners that need model state, solver/tBPTT branches, epoch ends).
    ``sync(release=True)`` additionally drops the packed copy so a
    subsequent step re-packs from the (possibly externally modified) state.

    Dispatch rides the AOT fast path (``env.aot_dispatch``): per step-args
    signature, the loop calls a cached ``lower().compile()`` executable
    with the donated packed buffers instead of re-entering jit dispatch —
    bit-identical trajectories (same trace → same executable). The
    :class:`~deeplearning4j_tpu.runtime.compile_cache.AotCache` lives in
    the NETWORK's jit cache, so repeated ``fit`` calls reuse executables
    and ``init()``/graph edits (which clear that cache) invalidate them.
    """

    def __init__(self, net, enabled: bool):
        self._net = net
        self._enabled = enabled
        self._packed = None
        self._step_fn = None
        self._packer = None
        from deeplearning4j_tpu.runtime.compile_cache import AotCache
        self._aot = net._jit_cache.setdefault("__aot__", AotCache("fit-step"))

    @classmethod
    def for_network(cls, net) -> "PackedStepLoop":
        from deeplearning4j_tpu.runtime.environment import get_environment
        from deeplearning4j_tpu.train.prefetch import stateless_listeners
        # same listener gate as async loss delivery — the two must never
        # desynchronize (a state-reading listener disables BOTH)
        enabled = (get_environment().packed_state
                   and stateless_listeners(net))
        return cls(net, enabled)

    @property
    def active(self) -> bool:
        return self._packed is not None

    @property
    def enabled(self) -> bool:
        """Whether packed stepping is in effect (env flag + listener gate).
        Grouped dispatch must also gate on this: with a state-reading
        listener attached, batches must dispatch (and notify) one at a
        time so the listener observes per-iteration state."""
        return self._enabled

    def step(self, *rest_args):
        """One train step (packed when enabled, plain otherwise). Returns the
        ``(loss, aux...)`` tail of the step (everything after the state)."""
        if not self._enabled:
            if self._step_fn is None:
                self._step_fn = self._net._jitted(
                    "train_step", self._net._make_train_step)
            out = self._aot.call(
                ("plain", step_args_signature(rest_args)),
                self._step_fn, self._net.train_state, *rest_args)
            self._net.train_state = out[0]
            return out[1:]
        if self._packed is None:
            self._step_fn, self._packer = self._net._jitted_packed()
            try:
                self._packed = self._packer.pack_device(self._net.train_state)
            # Structure changed since the packer was built. A changed
            # treedef/dtype raises ValueError; a changed leaf SHAPE with the
            # same treedef surfaces as TypeError from the reshape inside
            # pack — both mean "rebuild the packer".
            except (ValueError, TypeError):
                prefix = self._net._packed_cache_key()
                for k in [k for k in self._net._jit_cache
                          if isinstance(k, str) and k.startswith(prefix)]:
                    self._net._jit_cache.pop(k, None)  # incl. @unroll variants
                # AOT executables were lowered from the stale packed step
                self._aot.clear()
                self._step_fn, self._packer = self._net._jitted_packed()
                self._packed = self._packer.pack_device(self._net.train_state)
        out = self._aot.call(
            ("packed", self._net._packed_cache_key(),
             step_args_signature(rest_args)),
            self._step_fn, self._packed, *rest_args)
        self._packed = out[0]
        return out[1:]

    def step_group(self, group):
        """Run a list of per-step argument tuples as ONE unrolled device
        dispatch (env.dispatch_unroll). All tuples in the group must share
        shapes and mask-presence (the fit loop guarantees it). Returns the
        per-step losses (device scalars, lazy)."""
        if not self._enabled or len(group) == 1:
            return [self.step(*args)[0] for args in group]
        if self._packed is None:
            # first call packs lazily: run the first batch single-step,
            # then the rest as a (possibly shorter) group
            first_loss, = self.step(*group[0])
            rest = self.step_group(group[1:]) if len(group) > 1 else []
            return [first_loss] + rest
        fn = self._net._jitted_packed_unrolled(len(group))
        self._packed, losses = self._aot.call(
            ("packed-group", self._net._packed_cache_key(), len(group),
             step_args_signature(group[0])),
            fn, self._packed, [tuple(args) for args in group])
        return [losses[i] for i in range(len(group))]

    def sync(self, release: bool = False) -> None:
        """Refresh ``net.train_state`` from the packed buffers.

        If a donated step consumed the packed buffers and then raised (NaN
        panic, device error), no post-step state exists anywhere — sync
        drops the dead packed copy WITHOUT raising, so the original
        exception propagates; ``net.train_state`` is then whatever was last
        synced, and recovery is checkpoint restore (reference semantics for
        a crashed fit are the same).
        """
        if self._packed is None:
            return
        if LeafPacker.is_dead(self._packed):
            self._packed = None
            return
        self._net.train_state = self._packer.unpack_device(
            self._packed, donate=release)
        if release:
            self._packed = None
