"""Profiling and debugging hooks.

TPU-native equivalent of the reference's tracing stack (SURVEY.md §5.1):

- ``OpProfiler`` / ``ProfilerConfig`` (upstream
  ``org.nd4j.linalg.profiler.OpProfiler``): section timing + NaN panic modes.
  Per-op hooks make no sense under XLA (ops are fused into one program), so the
  unit of timing here is a *section* (a jitted step, an epoch, an ETL stage).
- SameDiff ``ProfilingListener`` Chrome-trace output → `jax.profiler` traces
  (viewable in TensorBoard/Perfetto), exposed via :func:`trace`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

import jax

# aliased: this module's own `trace` is the jax device-trace context
# manager; the distributed-tracing module must not shadow (or be
# shadowed by) it
from deeplearning4j_tpu.runtime import trace as _dtrace


@dataclasses.dataclass
class ProfilerConfig:
    """Modes mirror the reference's enum where meaningful on TPU."""

    enabled: bool = False
    check_for_nan: bool = False  # reference NAN_PANIC
    check_for_inf: bool = False  # reference INF_PANIC


class OpProfiler:
    """Section timer with aggregate stats.

    Usage::

        prof = OpProfiler()
        with prof.section("train_step"):
            state = step(state, batch)
        prof.summary()
    """

    def __init__(self, config: Optional[ProfilerConfig] = None):
        from deeplearning4j_tpu.serving.metrics import LatencyHistogram
        self.config = config or ProfilerConfig(enabled=True)
        self._totals: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        # serving's SLO histogram doubles as the section-latency histogram:
        # one percentile implementation across training and serving
        self._hists: Dict[str, "LatencyHistogram"] = defaultdict(LatencyHistogram)

    @contextlib.contextmanager
    def section(self, name: str) -> Iterator[None]:
        if not self.config.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._totals[name] += dt
            self._counts[name] += 1
            self._hists[name].observe(dt)

    def timings(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {
                "total_s": self._totals[name],
                "count": self._counts[name],
                "mean_s": self._totals[name] / max(1, self._counts[name]),
                "p50_s": self._hists[name].percentile(50),
                "p99_s": self._hists[name].percentile(99),
            }
            for name in self._totals
        }

    def summary(self) -> str:
        lines = ["OpProfiler summary:"]
        for name, t in sorted(self.timings().items(), key=lambda kv: -kv[1]["total_s"]):
            lines.append(
                f"  {name:30s} total={t['total_s'] * 1e3:9.2f}ms "
                f"n={t['count']:6d} mean={t['mean_s'] * 1e3:9.3f}ms"
            )
        cc = compile_cache_stats()
        if cc["compiles"] or cc["hits"] or cc["aot_compiles"]:
            lines.append(
                f"  compile cache: hits={cc['hits']} misses={cc['misses']} "
                f"corrupt={cc['corrupt_entries']} "
                f"compile={cc['compile_seconds']:.2f}s "
                f"aot={cc['aot_compiles']} "
                f"(+{cc['aot_compile_seconds']:.2f}s)")
        return "\n".join(lines)

    def reset(self) -> None:
        self._totals.clear()
        self._counts.clear()
        self._hists.clear()


class ExchangeStats:
    """Per-step stage split + compression counters for the distributed
    trainer's gradient exchange (ISSUE 6): ``encode`` (threshold codec),
    ``exchange`` (the collective), ``decode`` (peer-contribution
    accumulate), ``apply`` (updater step). Reuses the serving
    :class:`~deeplearning4j_tpu.serving.metrics.LatencyHistogram` — one
    percentile implementation across serving, training and distributed
    training. Attach to a
    :class:`~deeplearning4j_tpu.train.profiler.TrainingProfiler` via
    ``profiler.attach_exchange(stats)`` to surface the split and the
    compression ratio on the training headline.

    Thread-safety: recorded from the worker's step loop only, but guarded
    by a lock anyway so a supervisor thread may snapshot mid-run.
    """

    STAGES = ("encode", "exchange", "decode", "apply")

    def __init__(self):
        import threading

        from deeplearning4j_tpu.serving.metrics import LatencyHistogram
        # guards: _totals, _counts, _hists, _wire_bytes, _dense_bytes, _payload_bytes, _steps
        self._lock = threading.Lock()
        self._hists = {s: LatencyHistogram() for s in self.STAGES}
        self._totals = {s: 0.0 for s in self.STAGES}
        self._counts = {s: 0 for s in self.STAGES}
        self._dense_bytes = 0      # what a dense f32 exchange would move
        self._wire_bytes = 0       # what this worker actually put on the wire
        self._payload_bytes = 0    # unpadded encoded payload
        self._steps = 0

    def record(self, stage: str, seconds: float) -> None:
        _dtrace.stage_event(stage, seconds)  # onto the active train.step span
        with self._lock:
            self._totals[stage] += seconds
            self._counts[stage] += 1
            self._hists[stage].observe(seconds)

    def record_bytes(self, dense_bytes: int, wire_bytes: int,
                     payload_bytes: int) -> None:
        with self._lock:
            self._dense_bytes += int(dense_bytes)
            self._wire_bytes += int(wire_bytes)
            self._payload_bytes += int(payload_bytes)
            self._steps += 1

    @property
    def steps(self) -> int:
        with self._lock:
            return self._steps

    def report(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {"steps": self._steps}
            for s in self.STAGES:
                n = self._counts[s]
                out[f"{s}_total_s"] = round(self._totals[s], 4)
                out[f"{s}_mean_ms"] = round(
                    self._totals[s] / n * 1e3, 3) if n else 0.0
                out[f"{s}_p99_ms"] = round(
                    self._hists[s].percentile(99) * 1e3, 3)
            steps = max(1, self._steps)
            out["comms_bytes_per_step"] = round(self._wire_bytes / steps)
            out["dense_bytes_per_step"] = round(self._dense_bytes / steps)
            out["payload_bytes_per_step"] = round(self._payload_bytes / steps)
            out["compression_ratio"] = round(
                self._dense_bytes / self._wire_bytes, 2) \
                if self._wire_bytes else 1.0
        return out

    def headline(self) -> str:
        r = self.report()
        return (f"exchange {r['exchange_mean_ms']:.2f}ms/step "
                f"(encode {r['encode_mean_ms']:.2f} decode "
                f"{r['decode_mean_ms']:.2f} apply {r['apply_mean_ms']:.2f}), "
                f"{r['comms_bytes_per_step']} B/step on the wire "
                f"({r['compression_ratio']}x vs dense)")


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace (Chrome-trace analog of ``ProfilingListener``).

    View with TensorBoard's profile plugin or Perfetto.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def compile_cache_stats() -> Dict[str, object]:
    """Persistent-executable-cache and AOT-dispatch counters (hit/miss/
    corrupt, backend compile seconds, AOT executables minted) — the same
    numbers the serving ``/metrics`` endpoint renders; see
    :mod:`deeplearning4j_tpu.runtime.compile_cache`."""
    from deeplearning4j_tpu.runtime import compile_cache
    return compile_cache.stats()


_ROUTER_METRICS = None


def attach_router(metrics) -> None:
    """Register the process's live
    :class:`~deeplearning4j_tpu.serving.router.RouterMetrics` (ISSUE 7)
    so profiling tooling can read the fleet gauges without holding a
    router reference. Called by ``FleetRouter.start``; the newest router
    wins (one routing tier per process)."""
    global _ROUTER_METRICS
    _ROUTER_METRICS = metrics


def router_stats() -> Dict[str, object]:
    """Fleet-router gauges for the process's attached router: forwards,
    hedges launched/won/discarded-duplicates, failovers, shed skips,
    rolling deploys, and request-latency percentiles. Empty dict when no
    router is attached (the single-process serving topology)."""
    if _ROUTER_METRICS is None:
        return {}
    return _ROUTER_METRICS.snapshot()


_QUANT_METRICS: Dict[str, object] = {}


def attach_quant_metrics(name: str, metrics) -> None:
    """Register a model's :class:`~deeplearning4j_tpu.serving.metrics
    .ServingMetrics` under its served name when it carries a serving dtype
    policy (ISSUE 8) so profiling tooling can read the quantized-vs-f32
    latency split without holding a registry reference. Called by
    ``ModelRegistry.register`` for policy-carrying models; a hot-swap
    re-attaches the replacement's metrics (newest wins per name)."""
    _QUANT_METRICS[str(name)] = metrics


def quant_split_stats() -> Dict[str, Dict[str, object]]:
    """Per-model quantized-vs-f32 serving split for every attached
    policy-carrying model: the dtype-policy label, how much traffic rode
    the reduced-precision path, and the latency percentiles of each dtype
    class side by side — the profiler-side view of the
    ``serving_dtype_latency_seconds`` / ``serving_quantized_requests_total``
    series on ``/metrics``. Empty dict when nothing quantized is being
    served."""
    out: Dict[str, Dict[str, object]] = {}
    for name, m in list(_QUANT_METRICS.items()):
        s = m.snapshot()
        out[name] = {
            "dtype_policy": s.get("dtype_policy"),
            "requests_total": s.get("requests_total", 0),
            "quantized_requests_total": s.get("quantized_requests_total", 0),
            "quant_responses": s.get("quant_responses", 0),
            "float_responses": s.get("float_responses", 0),
            "latency_quant_p50_s": s.get("latency_quant_p50_s"),
            "latency_quant_p99_s": s.get("latency_quant_p99_s"),
            "latency_float_p50_s": s.get("latency_float_p50_s"),
            "latency_float_p99_s": s.get("latency_float_p99_s"),
        }
    return out


def detach_quant_metrics(name: str) -> None:
    """Drop a served name's attached quantized metrics (tests and graceful
    undeploy; absent names are a no-op)."""
    _QUANT_METRICS.pop(str(name), None)


_CAPACITY_PROVIDER = None


def attach_capacity(provider) -> None:
    """Register a capacity provider (a zero-arg callable returning the
    ``serving/capacity.py`` registry payload — ISSUE 10) so profiling
    tooling can read per-model resource accounting without holding a
    registry reference. Called by ``ModelServer.start``; the newest
    provider wins (mirrors :func:`attach_router`)."""
    global _CAPACITY_PROVIDER
    _CAPACITY_PROVIDER = provider


def detach_capacity(provider=None) -> None:
    """Drop the attached capacity provider. When ``provider`` is given,
    detach only if it is still the CURRENT one — a stopping server must
    not clobber a newer server's attachment (``ModelServer.stop`` passes
    its own provider)."""
    global _CAPACITY_PROVIDER
    if provider is None or _CAPACITY_PROVIDER is provider:
        _CAPACITY_PROVIDER = None


def capacity_stats() -> Dict[str, object]:
    """The attached registry's capacity ledger (per-model parameter /
    device bytes, replica utilization, queue headroom, compile footprint
    — the same payload ``/v1/capacity`` serves). Empty dict when no
    serving registry is attached."""
    if _CAPACITY_PROVIDER is None:
        return {}
    return _CAPACITY_PROVIDER()


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """Per-device memory stats — feeds the HBM crash report (§5.5 parity)."""
    out = {}
    for d in jax.devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats:
            out[str(d)] = {k: int(v) for k, v in stats.items()}
    return out
