"""Device and mesh discovery.

TPU-native replacement for the reference's device management (upstream
``CudaEnvironment`` device affinity and ``ParallelWrapper`` worker placement):
on TPU, placement is a `jax.sharding.Mesh` + named shardings, and XLA inserts
the collectives. This module is the single place the rest of the framework asks
"what devices exist and what mesh should I use".

Mesh axis conventions used throughout the framework:

- ``data``   — data parallelism (batch sharding; psum of grads over ICI)
- ``fsdp``   — parameter/optimizer sharding (ZeRO-3) in a composed plan;
  single-axis FSDP reuses ``data`` (batch AND params shard together there)
- ``model``  — tensor parallelism (weight sharding)
- ``pipe``   — pipeline stage axis
- ``seq``    — sequence/context parallelism (ring attention)
- ``expert`` — expert parallelism (MoE)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


def devices(backend: Optional[str] = None):
    """All addressable devices (this process)."""
    return jax.devices(backend) if backend else jax.devices()


def device_count(backend: Optional[str] = None) -> int:
    return len(devices(backend))


def global_device_count() -> int:
    return jax.device_count()


def process_count() -> int:
    return jax.process_count()


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: ordered mapping of axis name -> size.

    ``size == -1`` on at most one axis means "whatever is left over", like a
    reshape wildcard. ``MeshSpec({'data': -1})`` is pure DP over all devices.
    """

    axes: Tuple[Tuple[str, int], ...]

    def __init__(self, axes: Dict[str, int] | Sequence[Tuple[str, int]]):
        items = tuple(axes.items()) if isinstance(axes, dict) else tuple(axes)
        object.__setattr__(self, "axes", items)

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = dict(self.axes)
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError("At most one mesh axis may be -1")
        fixed = int(np.prod([v for v in sizes.values() if v != -1])) if sizes else 1
        if wild:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"Mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


@dataclasses.dataclass(frozen=True)
class HostSpec:
    """One machine in a multi-host bring-up (ISSUE 12): the declarative
    twin of :class:`MeshSpec` for the HOST axis. ``name`` is what a
    :class:`~deeplearning4j_tpu.serving.fleet.WorkerSpec.host` (or a
    training worker placement) references, ``address`` is where that
    host's processes are reachable, and ``spawn`` selects the process
    adapter (``"local"`` = this machine, ``"loopback"`` = a named
    same-machine stand-in for tests/drills, ``"ssh"``/other = a remote
    transport an adapter must implement). The serving fleet resolves
    these through ``serving.fleet.resolve_host_adapters``; the training
    side feeds the same roster into :func:`initialize_multihost`
    (coordinator + process ids per host)."""

    name: str
    address: str = "127.0.0.1"
    spawn: str = "local"
    #: how many worker processes this host is expected to carry (a
    #: placement hint; 0 = unconstrained)
    processes: int = 0


def loopback_hosts(n: int, prefix: str = "host") -> Tuple[HostSpec, ...]:
    """``n`` named loopback hosts — the serving twin of the ``local[N]``
    Spark-master trick: every "host" is this machine, but specs, spawn
    adapters, endpoints and placement all flow through the real
    multi-host paths, so tests and drills exercise a fleet that spans
    machines without owning any."""
    return tuple(HostSpec(name=f"{prefix}{i}", address="127.0.0.1",
                          spawn="loopback") for i in range(int(n)))


def create_mesh(
    spec: MeshSpec | Dict[str, int] | None = None,
    devices_: Optional[Sequence] = None,
) -> Mesh:
    """Build a `jax.sharding.Mesh` from a :class:`MeshSpec`.

    Defaults to pure data parallelism over every addressable device. Device
    order is preserved so that, on real hardware, neighbouring mesh positions
    are ICI neighbours (jax returns devices in torus order).
    """
    devs = list(devices_ if devices_ is not None else jax.devices())
    if spec is None:
        spec = MeshSpec({DATA_AXIS: -1})
    elif isinstance(spec, dict):
        spec = MeshSpec(spec)
    sizes = spec.resolve(len(devs))
    names = tuple(sizes.keys())
    shape = tuple(sizes[n] for n in names)
    mesh_devices = np.asarray(devs).reshape(shape)
    return Mesh(mesh_devices, names)


def local_mesh() -> Mesh:
    """1-axis DP mesh over local devices — the single-chip/dev default."""
    return create_mesh(MeshSpec({DATA_AXIS: -1}))


def _gloo_available() -> bool:
    """Whether this jaxlib ships the gloo TCP CPU-collectives backend —
    selecting an unavailable implementation would fail CPU client creation."""
    try:
        from jaxlib import xla_extension
        return hasattr(xla_extension, "make_gloo_tcp_collectives")
    except ImportError:  # pragma: no cover
        return False


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up: the replacement for the reference's Spark driver +
    Aeron mesh join (upstream ``SharedTrainingMaster`` / ``MeshOrganizer``).

    On TPU pods this is one call per host; XLA then routes collectives over
    ICI within a slice and DCN across slices. Safe to call with no arguments
    under TPU metadata-provided environments.

    On the CPU backend (the ``local[N]``-style multi-process smoke path) the
    default XLA client has no cross-process collectives at all — every
    allreduce dies with "Multiprocess computations aren't implemented on the
    CPU backend" — so a gloo TCP implementation must be selected BEFORE the
    backend initializes. Selected for EVERY multi-process bring-up, not just
    ``JAX_PLATFORMS=cpu``: the flag only affects the CPU client (which jax
    creates regardless of which accelerator is primary), so it is harmless
    on TPU hosts and covers CPU-by-default/auto-detect runs too.
    """
    if num_processes is not None and num_processes > 1 and _gloo_available():
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # pragma: no cover
            pass  # older jax: flag absent — keep the default behaviour
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
