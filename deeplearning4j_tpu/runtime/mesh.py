"""Device and mesh discovery.

TPU-native replacement for the reference's device management (upstream
``CudaEnvironment`` device affinity and ``ParallelWrapper`` worker placement):
on TPU, placement is a `jax.sharding.Mesh` + named shardings, and XLA inserts
the collectives. This module is the single place the rest of the framework asks
"what devices exist and what mesh should I use".

Mesh axis conventions used throughout the framework:

- ``data``   — data parallelism (batch sharding; psum of grads over ICI)
- ``model``  — tensor parallelism (weight sharding)
- ``pipe``   — pipeline stage axis
- ``seq``    — sequence/context parallelism (ring attention)
- ``expert`` — expert parallelism (MoE)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


def devices(backend: Optional[str] = None):
    """All addressable devices (this process)."""
    return jax.devices(backend) if backend else jax.devices()


def device_count(backend: Optional[str] = None) -> int:
    return len(devices(backend))


def global_device_count() -> int:
    return jax.device_count()


def process_count() -> int:
    return jax.process_count()


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: ordered mapping of axis name -> size.

    ``size == -1`` on at most one axis means "whatever is left over", like a
    reshape wildcard. ``MeshSpec({'data': -1})`` is pure DP over all devices.
    """

    axes: Tuple[Tuple[str, int], ...]

    def __init__(self, axes: Dict[str, int] | Sequence[Tuple[str, int]]):
        items = tuple(axes.items()) if isinstance(axes, dict) else tuple(axes)
        object.__setattr__(self, "axes", items)

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = dict(self.axes)
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError("At most one mesh axis may be -1")
        fixed = int(np.prod([v for v in sizes.values() if v != -1])) if sizes else 1
        if wild:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"Mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def create_mesh(
    spec: MeshSpec | Dict[str, int] | None = None,
    devices_: Optional[Sequence] = None,
) -> Mesh:
    """Build a `jax.sharding.Mesh` from a :class:`MeshSpec`.

    Defaults to pure data parallelism over every addressable device. Device
    order is preserved so that, on real hardware, neighbouring mesh positions
    are ICI neighbours (jax returns devices in torus order).
    """
    devs = list(devices_ if devices_ is not None else jax.devices())
    if spec is None:
        spec = MeshSpec({DATA_AXIS: -1})
    elif isinstance(spec, dict):
        spec = MeshSpec(spec)
    sizes = spec.resolve(len(devs))
    names = tuple(sizes.keys())
    shape = tuple(sizes[n] for n in names)
    mesh_devices = np.asarray(devs).reshape(shape)
    return Mesh(mesh_devices, names)


def local_mesh() -> Mesh:
    """1-axis DP mesh over local devices — the single-chip/dev default."""
    return create_mesh(MeshSpec({DATA_AXIS: -1}))


def _gloo_available() -> bool:
    """Whether this jaxlib ships the gloo TCP CPU-collectives backend —
    selecting an unavailable implementation would fail CPU client creation."""
    try:
        from jaxlib import xla_extension
        return hasattr(xla_extension, "make_gloo_tcp_collectives")
    except ImportError:  # pragma: no cover
        return False


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up: the replacement for the reference's Spark driver +
    Aeron mesh join (upstream ``SharedTrainingMaster`` / ``MeshOrganizer``).

    On TPU pods this is one call per host; XLA then routes collectives over
    ICI within a slice and DCN across slices. Safe to call with no arguments
    under TPU metadata-provided environments.

    On the CPU backend (the ``local[N]``-style multi-process smoke path) the
    default XLA client has no cross-process collectives at all — every
    allreduce dies with "Multiprocess computations aren't implemented on the
    CPU backend" — so a gloo TCP implementation must be selected BEFORE the
    backend initializes. Selected for EVERY multi-process bring-up, not just
    ``JAX_PLATFORMS=cpu``: the flag only affects the CPU client (which jax
    creates regardless of which accelerator is primary), so it is harmless
    on TPU hosts and covers CPU-by-default/auto-detect runs too.
    """
    if num_processes is not None and num_processes > 1 and _gloo_available():
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # pragma: no cover
            pass  # older jax: flag absent — keep the default behaviour
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
