"""Deterministic fault injection (the chaos-engineering layer).

The ROADMAP north star is production serving; that requires the system to
be provably well-behaved under *injected* failure, not just under load.
This module is the injection side: production code declares **named
injection points** (``chaos.inject("serving.batcher.forward")``,
``chaos.transform_bytes("train.checkpoint.bytes", data)``) and a test,
benchmark, or drill installs a :class:`ChaosController` that maps points to
**policies**:

- :class:`FailNth` — fail the N-th call (or every N-th) at a point.
- :class:`FailWithProbability` — fail each call with probability ``p``
  drawn from a per-policy seeded RNG, so a schedule replays exactly.
- :class:`AddLatency` — sleep a fixed delay plus seeded jitter.
- :class:`CorruptBytes` — corrupt data flowing through a byte point
  (bit-flips or truncation at seeded offsets): the torn-write /
  bit-rot simulator for checkpoint archives.
- :class:`HangUntilCancelled` — block until the controller is cancelled
  (scope exit), then raise :class:`ChaosCancelled`: the stuck-worker
  simulator a heartbeat watchdog must catch.

Design constraints:

- **No-op fast path.** With no controller installed, ``inject()`` is one
  module-global load and an ``is None`` test — nothing allocates, nothing
  locks. Serving/training hot paths may call it unconditionally.
- **Determinism.** Every policy owns a ``random.Random`` seeded from
  ``(controller seed, point pattern, policy index, class name)``; per-point
  call indices are sequential under a lock. The same seed and the same
  call sequence produce the same fault schedule, and the controller's
  ``events`` log records every decision for replay assertions.
- **Scoped.** ``with ChaosController(seed=7) as c: ...`` installs the
  controller globally for the block and restores the previous one (nesting
  allowed) on exit; exit also cancels any :class:`HangUntilCancelled`
  waiters so no thread outlives the blast radius.

Catalogue of injection points threaded through the stack (see
``docs/robustness.md``): ``serving.batcher.submit``,
``serving.batcher.forward`` (dispatch stage — fires as the batch is issued
to a replica), ``serving.batcher.complete`` (completion stage — fires
before the blocking readback, so ``AddLatency`` here simulates a slow
device and fills the pipeline's in-flight window),
``serving.batcher.warmup``, ``serving.registry.register``,
``serving.registry.page_in`` (fires as a cold model's single-flight
rehydration begins — ``AddLatency`` here simulates a slow page-in so
drills can exercise the queue-wait and honest-``Retry-After`` paths),
``train.checkpoint.write`` (call), ``train.checkpoint.bytes`` (byte
point), ``train.epoch``, ``train.iteration`` (via :class:`ChaosListener`),
``train.prefetch.fetch`` (fires once per fetched batch on the training
feed path, before coercion/transfer — in the
:class:`~deeplearning4j_tpu.train.prefetch.DevicePrefetcher` worker when
prefetching, inline otherwise, so one drill schedule covers both; a fault
must fail the fit cleanly with no thread left behind, see
``tests/test_train_pipeline.py``), ``runtime.compile_cache.load`` (fires
once per persistent-compilation-cache lookup, before the entry is read —
a fault here simulates a corrupt/truncated cached executable and must
degrade to a fresh compile, never a crash or a wrong answer, see
``tests/test_compile_cache.py``), ``train.distributed.exchange`` (fires
once per distributed training step at the top of the gradient exchange —
a fault kills the worker's step, which must surface as a supervised
whole-group restart with exact checkpoint resume, never a silent
divergence) and ``train.distributed.exchange.bytes`` (byte point over a
worker's encoded-update payload AFTER its CRC header is computed, so
injected wire corruption is exactly what every receiver's CRC check
catches — see ``tests/test_distributed.py``), ``serving.worker.predict``
(fires at the top of every ``ModelServer`` predict — per-PROCESS, so a
fleet drill can slow or fail one worker without touching its peers;
``AddLatency(p=...)`` here is the straggler injector ``bench.py
--fleet`` hedges against), ``serving.router.forward`` (fires in the
fleet router before each forward attempt — primary, hedge, or failover —
a fault here is a failed attempt the router must absorb by failing over
within the deadline), ``serving.router.hedge`` (fires as a hedge is
launched against a second worker, so a drill can fault or delay exactly
the hedge path — see ``tests/test_router.py``) and ``serving.wire.frame``
(fires per binary wire-frame encode, plus a ``transform_bytes`` byte
point over the finished CRC-framed frame — injected corruption,
truncation or bit flips must surface as a counted wire protocol error
and a JSON fallback/retry, never a silently wrong tensor, see
``tests/test_wire.py``).
"""

from __future__ import annotations

import fnmatch
import logging
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.runtime import journal, trace

logger = logging.getLogger(__name__)


# Central chaos-point registry (ISSUE 14): every injection point fired
# anywhere in the package, name -> one-line description. The analysis
# lint diffs this registry against (a) the `chaos.inject`/`transform_bytes`
# call sites in code, (b) the `docs/robustness.md` catalogue rows, and
# (c) the test/bench corpus — a point missing from any leg is a finding,
# so code, registry, docs and drills can never drift apart.
REGISTERED_POINTS: Dict[str, str] = {
    "serving.batcher.submit": "every request admission into the batcher",
    "serving.batcher.forward": "dispatch stage, as a batch is issued to a replica",
    "serving.batcher.complete": "completion stage, before the blocking readback",
    "serving.batcher.warmup": "AOT bucket warmup during build/hot-swap",
    "serving.registry.register": "start of every model registration",
    "serving.registry.deploy_quantized": "top of the accuracy-gated quantized deploy",
    "serving.registry.page_in": "start of a cold model's single-flight rehydration",
    "serving.worker.predict": "top of every ModelServer predict (per process)",
    "serving.router.forward": "router, before each forward attempt",
    "serving.router.hedge": "router, as a hedge launches against a second worker",
    "serving.router.config_load": "FleetConfig reload (call + byte point)",
    "serving.autoscale.lease": "LeaseElection, before every leader heartbeat",
    "serving.quantize.calibrate": "per calibration batch (call + CRC byte point)",
    "serving.quantize.gate": "top of the deploy_quantized accuracy-gate eval",
    "serving.delivery.gate": "golden-set gate eval; also a byte point over the CRC-framed golden-set sidecar",
    "serving.delivery.shadow": "shadow mirror launch; also a byte point over the mirrored response body",
    "train.checkpoint.write": "before each checkpoint archive write",
    "train.checkpoint.bytes": "byte point over the checkpoint archive bytes",
    "train.epoch": "supervised epoch worker, before net.fit",
    "train.iteration": "every iteration via chaos.ChaosListener",
    "train.prefetch.fetch": "per fetched batch on the training feed path",
    "train.distributed.exchange": "top of each distributed gradient exchange",
    "train.distributed.exchange.bytes": "byte point over a worker's encoded update",
    "runtime.compile_cache.load": "per persistent-executable-cache lookup",
    "serving.session.step": "top of every streaming-session step",
    "serving.session.rehydrate": "session spill read-back; also a byte point over the CRC-framed spill frame",
    "serving.wire.frame": "binary wire-frame encode; also a byte point over the CRC-framed frame",
    "serving.scheduler.claim": "background scheduler, before each exactly-once job claim on the ledger",
}


class ChaosError(RuntimeError):
    """An injected failure (never raised by real production faults)."""


class ChaosCancelled(ChaosError):
    """A :class:`HangUntilCancelled` hang released by controller exit."""


class Policy:
    """Base injection policy. Subclasses override :meth:`apply` (call
    points: raise / sleep / hang) and/or :meth:`transform` (byte points)."""

    def apply(self, point: str, index: int, rng: random.Random,
              controller: "ChaosController") -> Optional[str]:
        """Act on the ``index``-th call (1-based) of ``point``. Return a
        short action tag for the event log, or None for no action."""
        return None

    def transform(self, point: str, index: int, rng: random.Random,
                  data: bytes) -> Tuple[bytes, Optional[str]]:
        """Transform bytes flowing through ``point``. Returns (data, tag);
        return the SAME object untouched for no action."""
        return data, None


class FailNth(Policy):
    """Fail the ``n``-th call at a point (1-based); with ``every=True``,
    fail every ``n``-th call."""

    def __init__(self, n: int, every: bool = False,
                 exc: Optional[BaseException] = None):
        self.n = int(n)
        self.every = every
        self.exc = exc

    def apply(self, point, index, rng, controller):
        hit = (index % self.n == 0) if self.every else (index == self.n)
        if hit:
            raise self.exc or ChaosError(
                f"injected failure at {point} (call #{index})")
        return None


class FailWithProbability(Policy):
    """Fail each call with probability ``p`` from the policy's seeded RNG
    — the same seed replays the same fault schedule call-for-call."""

    def __init__(self, p: float, exc: Optional[BaseException] = None):
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"p must be in [0, 1], got {p}")
        self.p = float(p)
        self.exc = exc

    def apply(self, point, index, rng, controller):
        if rng.random() < self.p:
            raise self.exc or ChaosError(
                f"injected probabilistic failure at {point} (call #{index})")
        return None


class AddLatency(Policy):
    """Sleep ``seconds`` plus uniform seeded jitter in [0, ``jitter``].

    ``p < 1.0`` makes it a *straggler* profile: each call sleeps with
    probability ``p`` from the policy's seeded RNG (the tail-latency
    simulator the fleet router's hedging exists for) — a given seed
    replays the same slow-call schedule exactly. ``p=1.0`` (default)
    draws nothing and slows every call, so existing schedules replay
    unchanged."""

    def __init__(self, seconds: float, jitter: float = 0.0, p: float = 1.0):
        if not 0.0 < p <= 1.0:
            raise ValueError(f"p must be in (0, 1], got {p}")
        self.seconds = float(seconds)
        self.jitter = float(jitter)
        self.p = float(p)

    def apply(self, point, index, rng, controller):
        if self.p < 1.0 and rng.random() >= self.p:
            return None
        delay = self.seconds + (rng.uniform(0.0, self.jitter)
                                if self.jitter else 0.0)
        time.sleep(delay)
        return f"latency:{delay:.4f}"


class CorruptBytes(Policy):
    """Corrupt bytes at a byte point: ``mode="flip"`` XORs ``n_bytes``
    bytes at seeded offsets (bit rot), ``mode="truncate"`` cuts the tail at
    a seeded offset (torn write). ``nth`` restricts corruption to one call
    index (e.g. only the 3rd checkpoint); None corrupts every call."""

    def __init__(self, n_bytes: int = 8, mode: str = "flip",
                 nth: Optional[int] = None):
        if mode not in ("flip", "truncate"):
            raise ValueError(f"mode must be 'flip' or 'truncate', got {mode!r}")
        self.n_bytes = int(n_bytes)
        self.mode = mode
        self.nth = nth

    def transform(self, point, index, rng, data):
        if self.nth is not None and index != self.nth:
            return data, None
        if not data:
            return data, None
        if self.mode == "truncate":
            cut = rng.randrange(0, max(1, len(data) - 1))
            return data[:cut], f"corrupt:truncate@{cut}"
        buf = bytearray(data)
        for _ in range(min(self.n_bytes, len(buf))):
            i = rng.randrange(len(buf))
            buf[i] ^= 0xFF
        return bytes(buf), f"corrupt:flip:{min(self.n_bytes, len(buf))}"


class HangUntilCancelled(Policy):
    """Block the calling thread until the controller is cancelled (scope
    exit or explicit :meth:`ChaosController.cancel`), then raise
    :class:`ChaosCancelled`. ``timeout_s`` bounds the wait as a safety net
    against a forgotten cancel (raises anyway when it expires)."""

    def __init__(self, timeout_s: float = 60.0):
        self.timeout_s = float(timeout_s)

    def apply(self, point, index, rng, controller):
        controller._cancel_event.wait(self.timeout_s)
        raise ChaosCancelled(
            f"injected hang at {point} (call #{index}) released")


class ChaosController:
    """Scoped, seeded registry of (point pattern -> policies).

    Usage::

        with ChaosController(seed=7) as c:
            c.on("serving.batcher.forward", FailWithProbability(0.2))
            c.on("train.checkpoint.write", CorruptBytes(mode="truncate"))
            ... run traffic / training ...
        # scope exit: hangs cancelled, previous controller restored

    ``events`` is the append-only decision log — one
    ``(point, call_index, policy_name, action)`` tuple per policy action —
    used to assert deterministic replay of a fault schedule.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.events: List[Tuple[str, int, str, str]] = []
        self._rules: List[Tuple[str, Policy, random.Random]] = []
        self._counts: Dict[str, int] = {}
        # _rules is append-under-lock / read-lock-free by design (list
        # iteration over a snapshot reference is safe in CPython)
        self._lock = threading.Lock()  # guards: _counts, events
        self._cancel_event = threading.Event()
        self._previous: Optional["ChaosController"] = None

    # -------------------------------------------------------------- config
    def on(self, pattern: str, *policies: Policy) -> "ChaosController":
        """Attach policies to an injection-point name or fnmatch pattern
        (``"serving.*"`` matches every serving point). Chainable."""
        if not policies:
            raise ValueError("on() needs at least one policy")
        with self._lock:
            for p in policies:
                # seed from the per-PATTERN policy position (not the global
                # rule index): a schedule replays identically even when
                # unrelated rules are registered around it
                nth = sum(1 for pat, _, _ in self._rules if pat == pattern)
                rng = random.Random(
                    f"{self.seed}:{pattern}:{nth}:{type(p).__name__}")
                self._rules.append((pattern, p, rng))
        return self

    def cancel(self) -> None:
        """Release every :class:`HangUntilCancelled` waiter."""
        self._cancel_event.set()

    # --------------------------------------------------------------- scope
    def __enter__(self) -> "ChaosController":
        global _ACTIVE
        with _INSTALL_LOCK:
            self._previous = _ACTIVE
            _ACTIVE = self
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        global _ACTIVE
        self.cancel()
        with _INSTALL_LOCK:
            _ACTIVE = self._previous
        self._previous = None

    # ------------------------------------------------------------- plumbing
    def _matching(self, name: str):
        return [(pat, pol, rng) for pat, pol, rng in self._rules
                if pat == name or fnmatch.fnmatchcase(name, pat)]

    def _next_index(self, name: str) -> int:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + 1
            return self._counts[name]

    def count(self, name: str) -> int:
        """How many times ``name`` has fired under this controller."""
        with self._lock:
            return self._counts.get(name, 0)

    def _record(self, name, index, policy, action) -> None:
        with self._lock:
            self.events.append((name, index, type(policy).__name__, action))
        # the black box sees every injected fault (ISSUE 15): the event
        # rides next to the breaker/failover/restart events the fault
        # causes, trace-linked via the active span like the chaos stamp
        journal.emit("chaos.action", point=name, index=index,
                     policy=type(policy).__name__, action=action)

    def fire(self, name: str) -> None:
        rules = self._matching(name)
        if not rules:
            return
        index = self._next_index(name)
        for _pat, policy, rng in rules:
            try:
                action = policy.apply(name, index, rng, self)
            except BaseException as e:
                self._record(name, index, policy, f"raise:{type(e).__name__}")
                # stamp the injected fault onto the active trace span
                # (ISSUE 9): every fault drill is traceable after the
                # fact, and tail sampling always keeps the trace
                trace.stamp_chaos(name, f"raise:{type(e).__name__}")
                logger.info("chaos: %s #%d -> %s", name, index, e)
                raise
            if action is not None:
                self._record(name, index, policy, action)
                trace.stamp_chaos(name, action)

    def transform(self, name: str, data: bytes) -> bytes:
        rules = self._matching(name)
        if not rules:
            return data
        index = self._next_index(name)
        for _pat, policy, rng in rules:
            out, action = policy.transform(name, index, rng, data)
            if action is not None:
                self._record(name, index, policy, action)
                trace.stamp_chaos(name, action)
                logger.info("chaos: %s #%d -> %s", name, index, action)
                data = out
        return data


_INSTALL_LOCK = threading.Lock()  # guards: (_ACTIVE install/restore)
_ACTIVE: Optional[ChaosController] = None


def active() -> bool:
    """True when a controller is installed (hot paths may use this to skip
    chaos-only work like re-reading a file for byte corruption)."""
    return _ACTIVE is not None


def inject(name: str) -> None:
    """Fire the injection point ``name``. No-op fast path when no
    controller is installed; otherwise applies every matching policy
    (which may raise, sleep, or hang)."""
    c = _ACTIVE
    if c is None:
        return
    c.fire(name)


def transform_bytes(name: str, data: bytes) -> bytes:
    """Pass ``data`` through the byte point ``name``. Returns ``data``
    itself (same object) when no controller or no matching corruption
    policy is installed."""
    c = _ACTIVE
    if c is None:
        return data
    return c.transform(name, data)


class ChaosListener:
    """TrainingListener shim firing ``train.iteration`` every iteration —
    attach it to a net to schedule deterministic mid-epoch faults (the
    in-process analog of losing a chip at step N)."""

    def __init__(self, point: str = "train.iteration"):
        self.point = point

    def iteration_done(self, model, iteration, epoch, score):
        inject(self.point)

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass
