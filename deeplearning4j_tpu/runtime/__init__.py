"""Runtime substrate: environment/config facade, mesh discovery, RNG, profiling.

TPU-native replacement for the reference's runtime plumbing:
``org.nd4j.config.ND4JSystemProperties`` / ``ND4JEnvironmentVars`` (flag
facade), ``CudaEnvironment`` (device runtime tuning), ``Nd4j.getRandom()``
(global RNG), and ``OpProfiler`` (profiling hooks).
"""

from deeplearning4j_tpu.runtime import chaos
from deeplearning4j_tpu.runtime.chaos import (
    AddLatency,
    ChaosCancelled,
    ChaosController,
    ChaosError,
    ChaosListener,
    CorruptBytes,
    FailNth,
    FailWithProbability,
    HangUntilCancelled,
)
from deeplearning4j_tpu.runtime.environment import Environment, get_environment
from deeplearning4j_tpu.runtime.mesh import (
    MeshSpec,
    create_mesh,
    device_count,
    devices,
    local_mesh,
)
from deeplearning4j_tpu.runtime.rng import RngManager, get_default_rng, set_default_seed
from deeplearning4j_tpu.runtime.profiler import OpProfiler, ProfilerConfig
# the jax device-trace context manager keeps its old spelling as
# runtime.profiler.trace; the package-level name `trace` now names the
# distributed-tracing module (ISSUE 9), re-exported here as device_trace
from deeplearning4j_tpu.runtime.profiler import trace as device_trace
from deeplearning4j_tpu.runtime import trace
# the fleet event journal (ISSUE 15): the black box every control seam
# writes to — see docs/observability.md "Black box"
from deeplearning4j_tpu.runtime import journal

__all__ = [
    "trace",
    "journal",
    "device_trace",
    "chaos",
    "ChaosController",
    "ChaosError",
    "ChaosCancelled",
    "ChaosListener",
    "FailNth",
    "FailWithProbability",
    "AddLatency",
    "CorruptBytes",
    "HangUntilCancelled",
    "Environment",
    "get_environment",
    "MeshSpec",
    "create_mesh",
    "device_count",
    "devices",
    "local_mesh",
    "RngManager",
    "get_default_rng",
    "set_default_seed",
    "OpProfiler",
    "ProfilerConfig",
]
