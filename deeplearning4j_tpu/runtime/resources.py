"""Resource-directory management and archive utilities.

Rebuild of the reference's common utilities: ``DL4JResources`` (upstream
``org.deeplearning4j.common.resources.DL4JResources`` — the configurable
root under which datasets/models/caches live, default ``~/.deeplearning4j``)
and ``ArchiveUtils`` (upstream ``org.nd4j.common.util.ArchiveUtils`` —
zip/tar/tgz extraction with path-traversal protection).

This environment is offline, so the download-mirror side of DL4JResources
(``DL4JResources.getURLString``) has no analog; the directory layout and the
programmatic/env-var override (``DL4J_TPU_RESOURCES``) are kept so dataset
fetchers and the model zoo resolve caches the same way the reference does.
"""

from __future__ import annotations

import os
import shutil
import tarfile
import zipfile
from pathlib import Path
from typing import List, Optional


class ResourceType:
    DATASET = "datasets"
    ZOO_MODEL = "models"
    RESOURCE = "resources"


class DL4JResources:
    """Process-wide base directory for datasets/models (reference
    ``DL4JResources.getBaseDirectory`` / ``setBaseDirectory``)."""

    _base: Optional[str] = None

    @classmethod
    def get_base_directory(cls) -> str:
        if cls._base is None:
            cls._base = os.environ.get(
                "DL4J_TPU_RESOURCES",
                os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu"))
        return cls._base

    @classmethod
    def set_base_directory(cls, path: str) -> None:
        cls._base = str(path)

    @classmethod
    def get_directory(cls, resource_type: str, *subdirs: str) -> str:
        p = Path(cls.get_base_directory(), resource_type, *subdirs)
        p.mkdir(parents=True, exist_ok=True)
        return str(p)


class ArchiveUtils:
    """Archive extraction (reference ``ArchiveUtils.unzipFileTo`` etc.) with
    zip-slip/path-traversal protection."""

    @staticmethod
    def _check_dest(dest_dir: str, member_path: str) -> str:
        dest = os.path.realpath(dest_dir)
        target = os.path.realpath(os.path.join(dest, member_path))
        if not target.startswith(dest + os.sep) and target != dest:
            raise ValueError(
                f"archive member escapes destination: {member_path!r}")
        return target

    @staticmethod
    def unzip_file_to(archive: str, dest_dir: str) -> List[str]:
        out = []
        os.makedirs(dest_dir, exist_ok=True)
        with zipfile.ZipFile(archive) as z:
            for name in z.namelist():
                target = ArchiveUtils._check_dest(dest_dir, name)
                if name.endswith("/"):
                    os.makedirs(target, exist_ok=True)
                    continue
                os.makedirs(os.path.dirname(target), exist_ok=True)
                with z.open(name) as src, open(target, "wb") as dst:
                    shutil.copyfileobj(src, dst)
                out.append(target)
        return out

    @staticmethod
    def untar_file_to(archive: str, dest_dir: str) -> List[str]:
        """Handles .tar, .tar.gz/.tgz, .tar.bz2 (reference ``tarGzExtract``)."""
        out = []
        os.makedirs(dest_dir, exist_ok=True)
        with tarfile.open(archive) as t:
            members = [m for m in t.getmembers() if m.isfile() or m.isdir()]
            for member in members:
                ArchiveUtils._check_dest(dest_dir, member.name)
            t.extractall(dest_dir, members=members, filter="data")
            out = [os.path.join(dest_dir, m.name) for m in members
                   if m.isfile()]
        return out

    @staticmethod
    def extract(archive: str, dest_dir: str) -> List[str]:
        """Dispatch on extension (reference ``ArchiveUtils.unzipFileTo``'s
        format sniffing)."""
        a = archive.lower()
        if a.endswith(".zip") or a.endswith(".jar"):
            return ArchiveUtils.unzip_file_to(archive, dest_dir)
        if a.endswith((".tar", ".tar.gz", ".tgz", ".tar.bz2")):
            return ArchiveUtils.untar_file_to(archive, dest_dir)
        raise ValueError(f"unsupported archive format: {archive}")

    @staticmethod
    def list_files(archive: str) -> List[str]:
        a = archive.lower()
        if a.endswith(".zip") or a.endswith(".jar"):
            with zipfile.ZipFile(archive) as z:
                return [n for n in z.namelist() if not n.endswith("/")]
        with tarfile.open(archive) as t:
            return [m.name for m in t.getmembers() if m.isfile()]
