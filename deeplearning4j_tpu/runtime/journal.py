"""Unified fleet event journal: the black box (ISSUE 15 tentpole;
``docs/observability.md`` "Black box").

The reference DL4J pairs its ``StatsListener`` -> UI-server telemetry
with ``CrashReportingUtil`` — when something dies, a single artifact
tells the whole story. Our stack had the *telemetry* (traces, SLO burn
rates, capacity) but the *operational record* was scattered: autoscaler
decisions in one deque, lease elections in another, breaker state only
as a gauge, fleet restarts only in the supervisor's logger, chaos stamps
only on spans, crash reports as loose files. Reconstructing "what
happened during that SIGKILL drill" meant correlating five endpoints by
hand.

This module is the single ordered record those sources now write to: a
bounded, lock-free, causally-ordered **event journal** — the same ring
discipline as :class:`~deeplearning4j_tpu.runtime.trace.TraceCollector`
— of typed events, one per control-plane state change:

- every event carries a **monotonic per-process ``seq``** (dense — a gap
  in a scraped window means the ring overwrote history, never that an
  event was silently lost in flight), a **wall-clock anchor** ``ts``
  that orders events across processes (same-host skew is microseconds),
  a per-process-incarnation id (a restarted worker's seq reset cannot
  alias its predecessor's events), and the **active trace id** when one
  exists — the journal and the flight recorder cross-link, so a
  breaker-open event names the exact request tree that opened it;
- event *types* are a closed registry (:data:`EVENT_TYPES`), enforced by
  ``analysis/lint.py`` with the same four-way diff as chaos points: an
  emit site whose type is unregistered, a registered type never emitted,
  undocumented in ``docs/observability.md``, or exercised by no
  test/bench drill is each a lint finding;
- **emit is lock-free and cheap**: one ``itertools.count`` draw (atomic
  under the GIL), one dict build, one slot store. Nothing on the serving
  request hot path emits per-request — journal events fire on control
  seams (breaker transitions, page-ins, restarts, deploys, decisions),
  so ``bench.py --blackbox`` bounds the journal-on serving cost < 1%;
- reads are bounded: :func:`bound_events` (shared by the worker and
  router ``/v1/journal`` handlers) applies ``since``/``limit``/``types``
  filters plus a hard serialized-size cap, exactly like
  ``trace.bound_traces``.

The router merges its own ring with every ready worker's
(``GET /v1/journal`` fleet view) via :func:`merge_events`: wall-anchor
first, seq as the within-process tiebreak — so a worker restart (seq
resets to 0, new incarnation) cannot reorder the merged view, and one
scrape yields the fleet's full ordered timeline. ``serving/blackbox.py``
builds the anomaly watchdog and the one-``curl`` incident bundle on top.

The journal is ON by default (a black box that must be switched on
before the crash records nothing); ``DL4J_TPU_JOURNAL=0`` or
:func:`disable` restores a no-op fast path (one global load + ``is
None`` test), which is the off arm of the bench A/B.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import time
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from deeplearning4j_tpu.runtime import trace

__all__ = [
    "EVENT_TYPES", "EventJournal", "emit", "events", "counters",
    "enable", "disable", "enabled", "journal", "incarnation",
    "merge_events", "bound_events", "render_prometheus",
    "JOURNAL_RESPONSE_BYTE_CAP",
]

# Central journal-event-type registry (ISSUE 15): every event type
# emitted anywhere in the package, name -> one-line description. The
# analysis lint diffs this registry against (a) the ``journal.emit``
# call sites in code, (b) the ``docs/observability.md`` event-schema
# rows, and (c) the test/bench corpus — the same four-way parity as
# ``chaos.REGISTERED_POINTS``, so code, registry, docs and drills can
# never drift apart.
EVENT_TYPES: Dict[str, str] = {
    "breaker.open": "circuit breaker tripped OPEN (scope: model:* or worker:*)",
    "breaker.half_open": "breaker reset timeout elapsed; probing",
    "breaker.close": "half-open probe succeeded; breaker CLOSED",
    "router.hedge": "router launched a hedge against a second worker",
    "router.failover": "every launched attempt failed; retrying elsewhere",
    "router.shed_window": "router honoring a worker's Retry-After shed hint",
    "router.worker_ready": "router probe readmitted a worker (not-ready -> ready)",
    "router.worker_unready": "router probe lost a worker (ready -> not-ready)",
    "router.wire_downgrade": "worker answered 415 to a binary frame; router pinned JSON for it",
    "autoscale.decision": "one SLOAutoscaler decision (acted/refused/deferred)",
    "autoscale.election": "lease transition (acquired/takeover/lost/released)",
    "control.config_apply": "a FleetConfig mutation committed (new version)",
    "control.deploy_stage": "rolling-deploy stage (claim/drain/restart/readmit/done)",
    "fleet.worker_spawn": "supervisor spawned a worker process",
    "fleet.worker_restart": "supervisor relaunched a worker (crash or intentional)",
    "fleet.worker_retire": "supervisor retired a worker from the fleet",
    "fleet.worker_kill": "SIGKILL issued to a worker (the chaos drill's hammer)",
    "registry.hot_swap": "a model hot-swapped to a new version",
    "registry.page_in": "a cold model rehydrated under the HBM budget",
    "registry.evict": "a resident model paged out to COLD",
    "registry.residency_lever": "explicit residency lever (POST .../residency)",
    "train.checkpoint": "a checkpoint archive written (atomic + manifested)",
    "train.resume": "a restarted trainer restored from a checkpoint",
    "train.restart": "supervised trainer counted a restart against its budget",
    "delivery.gate": "a candidate's golden-set gate verdict (pass/fail/refused)",
    "delivery.stage": "gated-delivery stage transition (shadow/canary/ramp/verdict)",
    "delivery.shadow_stats": "shadow stage closed: mirror comparison stats + verdict",
    "delivery.rollback": "gated delivery auto-rolled back to the incumbent (cause)",
    "delivery.promote": "gated delivery promoted the candidate fleet-wide",
    "chaos.action": "a chaos policy acted (fault/latency/corruption injected)",
    "crash.report": "CrashReportingUtil wrote (or failed to write) a dump",
    "incident.open": "anomaly watchdog opened an incident (rule + evidence)",
    "incident.close": "anomaly watchdog closed an incident (quiet again)",
    "session.create": "streaming session opened (zero carry, spill written)",
    "session.step_miss": "session step found no resident carry; rehydrating",
    "session.spill": "session carry pushed cold to its CRC-framed spill file",
    "session.rehydrate": "session carry read back from spill (CRC-verified)",
    "session.migrate": "session moved workers (rehydrated a foreign spill)",
    "session.evict": "session memory copy dropped (idle TTL or byte budget)",
    "session.close": "streaming session closed; spill file deleted",
    "scheduler.submit": "background job submitted to the shared job store",
    "scheduler.claim": "scheduler claim attempt on a job (won or lost the ledger race)",
    "scheduler.start": "claimed job started running on a worker's spare capacity",
    "scheduler.preempt": "traffic preempted a running job (checkpointed mid-run)",
    "scheduler.resume": "preempted job resumed from its checkpoint (exact batch-skip)",
    "scheduler.complete": "background job ran to completion (result recorded)",
    "scheduler.fail": "background job raised; failure recorded in the job store",
    "scheduler.cancel": "background job cancelled before completion",
}

#: per-process-incarnation id: a restarted worker starts a fresh seq
#: stream under a fresh incarnation, so merged views can never alias two
#: lifetimes of the same worker id into one stream
_INCARNATION = f"{random.getrandbits(48):012x}"


def incarnation() -> str:
    return _INCARNATION


class EventJournal:
    """Bounded lock-free ring of journal events.

    ``record`` assigns the event its dense per-process ``seq`` from an
    ``itertools.count`` (atomic under the GIL) and stores it in
    ``seq % capacity`` — a single slot store, no lock, old events
    overwritten. Readers snapshot the slots and sort by seq (the read
    path is not hot).
    """

    def __init__(self, capacity: int = 1024):
        self.capacity = max(1, int(capacity))
        self._slots: List[Optional[Dict[str, Any]]] = [None] * self.capacity
        self._n = itertools.count()

    def record(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        seq = next(self._n)
        rec["seq"] = seq
        self._slots[seq % self.capacity] = rec
        return rec

    def events(self, since: Optional[float] = None,
               limit: Optional[int] = None,
               types: Optional[Iterable[str]] = None
               ) -> List[Dict[str, Any]]:
        """Live events oldest-first, optionally filtered: ``since`` is a
        wall-clock lower bound, ``types`` an allow-set, ``limit`` keeps
        the newest N of what remains."""
        recs = [r for r in list(self._slots) if r is not None]
        recs.sort(key=lambda r: r["seq"])
        if types is not None:
            tset = set(types)
            recs = [r for r in recs if r["type"] in tset]
        if since is not None:
            recs = [r for r in recs if r["ts"] >= float(since)]
        if limit is not None and int(limit) >= 0:
            recs = recs[max(0, len(recs) - int(limit)):]
        return recs

    def counters(self) -> Dict[str, int]:
        """``events_total`` is derived from the newest live seq (seqs are
        dense, so newest+1 == emitted) — no separate counter to race."""
        live = [r["seq"] for r in list(self._slots) if r is not None]
        total = (max(live) + 1) if live else 0
        return {"events_total": total,
                "capacity": self.capacity,
                "live": len(live),
                "overwritten_total": max(0, total - self.capacity)}

    def clear(self) -> None:
        self._slots = [None] * self.capacity


def _env_enabled(environ) -> bool:
    return environ.get("DL4J_TPU_JOURNAL", "").strip().lower() not in (
        "0", "false", "off", "no")


_JOURNAL: Optional[EventJournal] = (
    EventJournal() if _env_enabled(os.environ) else None)


def enable(capacity: Optional[int] = None) -> EventJournal:
    """(Re)install the process journal; ``capacity`` replaces the ring
    with a fresh one of that size."""
    global _JOURNAL
    if capacity is not None or _JOURNAL is None:
        _JOURNAL = EventJournal(capacity or 1024)
    return _JOURNAL


def disable() -> None:
    """No-op fast path: subsequent ``emit`` calls do nothing (the off
    arm of ``bench.py --blackbox``'s A/B)."""
    global _JOURNAL
    _JOURNAL = None


def enabled() -> bool:
    return _JOURNAL is not None


def journal() -> Optional[EventJournal]:
    return _JOURNAL


def emit(etype: str, _trace_id: Optional[str] = None,
         **attrs: Any) -> Optional[Dict[str, Any]]:
    """Record one typed event. THE emit entry point: with the journal
    disabled this is one global load and an ``is None`` test. The active
    trace id (when any) is captured automatically so the journal and the
    flight recorder cross-link; ``_trace_id`` overrides it. Returns the
    stored record (or ``None`` when disabled). Never raises — the black
    box must not be able to fail the system it records."""
    j = _JOURNAL
    if j is None:
        return None
    try:
        tid = _trace_id if _trace_id is not None else trace.current_trace_id()
        return j.record({"ts": time.time(), "type": str(etype),
                         "process": trace.process_tag(),
                         "incarnation": _INCARNATION,
                         "trace_id": tid, "attrs": attrs})
    except Exception:
        return None


def events(since: Optional[float] = None, limit: Optional[int] = None,
           types: Optional[Iterable[str]] = None) -> List[Dict[str, Any]]:
    """This process's live events (empty when disabled)."""
    j = _JOURNAL
    return [] if j is None else j.events(since=since, limit=limit,
                                         types=types)


def counters() -> Dict[str, int]:
    j = _JOURNAL
    if j is None:
        return {"events_total": 0, "capacity": 0, "live": 0,
                "overwritten_total": 0}
    return j.counters()


# ------------------------------------------------------------ merge + bound
def merge_events(streams: Iterable[Iterable[Dict[str, Any]]]
                 ) -> List[Dict[str, Any]]:
    """Merge per-process event streams into one fleet timeline,
    de-duplicated by ``(incarnation, seq)`` and ordered by **wall anchor
    first, seq second** — the wall clock orders across processes; the
    dense seq breaks same-tick ties within a process. Seq is NOT the
    primary key on purpose: a restarted worker's seq resets to 0 under a
    fresh incarnation, and seq-first ordering would teleport its new
    events before its old ones (the satellite regression test)."""
    seen: Set[Tuple[str, int]] = set()
    out: List[Dict[str, Any]] = []
    for stream in streams:
        for rec in stream or ():
            key = (rec.get("incarnation", "?"), int(rec.get("seq", -1)))
            if key in seen:
                continue
            seen.add(key)
            out.append(rec)
    out.sort(key=lambda r: (r.get("ts") or 0.0, r.get("seq") or 0,
                            r.get("incarnation") or ""))
    return out


#: hard cap on one ``/v1/journal`` response body — a scrape of a full
#: ring must never produce an unbounded HTTP body (the trace.bound_traces
#: contract, applied to events)
JOURNAL_RESPONSE_BYTE_CAP = 2 * 1024 * 1024


def bound_events(records: Iterable[Dict[str, Any]],
                 since: Optional[float] = None,
                 limit: Optional[int] = None,
                 types: Optional[Iterable[str]] = None,
                 max_bytes: Optional[int] = None):
    """The ``/v1/journal`` handlers' shared read bound: ``since`` /
    ``types`` filter, ``limit`` keeps the newest N, and the serialized
    size of what remains is capped (default
    :data:`JOURNAL_RESPONSE_BYTE_CAP`) by dropping oldest-first — the
    newest event always survives. Returns
    ``(events_oldest_first, truncated)``."""
    recs = sorted(records, key=lambda r: (r.get("ts") or 0.0,
                                          r.get("seq") or 0))
    if types is not None:
        tset = set(types)
        recs = [r for r in recs if r.get("type") in tset]
    if since is not None:
        recs = [r for r in recs if (r.get("ts") or 0.0) >= float(since)]
    truncated = False
    if limit is not None and int(limit) >= 0 and len(recs) > int(limit):
        truncated = True
        recs = recs[len(recs) - int(limit):]
    cap = JOURNAL_RESPONSE_BYTE_CAP if max_bytes is None else int(max_bytes)
    total, kept = 0, []
    for r in reversed(recs):               # newest first
        size = len(json.dumps(r, default=str).encode())
        if kept and total + size > cap:
            truncated = True
            break
        kept.append(r)
        total += size
    kept.reverse()
    return kept, truncated


def render_prometheus() -> str:
    """The ``journal_*`` gauges for ``/metrics`` (both tiers)."""
    c = counters()
    return "\n".join([
        f"journal_enabled {int(enabled())}",
        f"journal_events_total {c['events_total']}",
        f"journal_ring_capacity {c['capacity']}",
        f"journal_overwritten_total {c['overwritten_total']}",
    ]) + "\n"
