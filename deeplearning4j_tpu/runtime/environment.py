"""Runtime configuration facade.

TPU-native equivalent of the reference's flag system (upstream
``org.nd4j.config.ND4JSystemProperties`` / ``ND4JEnvironmentVars`` and the
libnd4j ``Environment`` singleton; see SURVEY.md §5.6): a single process-wide
configuration object, settable programmatically or through ``DL4J_TPU_*``
environment variables, controlling dtype policy, debug modes, and defaults.

Unlike the reference there is no backend switch to manage — JAX/PJRT selects
the platform — but the same knobs (default float dtype, NaN panic, verbose op
logging, workspace-debug analog) are exposed so user code ports cleanly.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

_ENV_PREFIX = "DL4J_TPU_"

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "float64": jnp.float64,
}


@dataclasses.dataclass
class Environment:
    """Process-wide runtime configuration.

    Attributes mirror the reference's runtime flags where a TPU analog exists:

    - ``default_dtype``: dtype of freshly initialised parameters (reference:
      ``Nd4j.setDefaultDataTypes``). ``float32`` by default.
    - ``compute_dtype``: dtype activations/matmuls are cast to inside the
      jitted step. ``bfloat16`` keeps the MXU fed; params stay
      ``default_dtype`` (mixed precision policy).
    - ``nan_panic``: throw on first NaN/Inf produced by a jitted step
      (reference: OpProfiler ``ANY_PANIC``); implemented via
      ``jax.config.debug_nans`` plus explicit checks in the fit loop.
    - ``verbose`` / ``debug``: op-level logging analogs of libnd4j
      ``Environment::setVerbose/setDebug``.
    - ``cache_compiled``: persistent XLA compilation cache directory.
    """

    default_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    nan_panic: bool = False
    verbose: bool = False
    debug: bool = False
    cache_compiled: Optional[str] = None
    # Analog of org.nd4j.memory.limit: fraction of HBM jax may pre-allocate.
    memory_fraction: Optional[float] = None
    # Rematerialization (jax.checkpoint) of single-entry DAG segments during
    # training: trades recompute FLOPs for HBM traffic — the winning trade
    # when a model is bandwidth-bound (ResNet-50 measured 87 GB/step vs the
    # v5e's 819 GB/s). The workspace-memory knob of this framework.
    remat_segments: bool = False
    # Flat-buffer packing of small train-state leaves at the jitted-step
    # boundary (runtime/state_packing.py): bit-identical math, ~4x fewer
    # buffer handles per dispatch. The TPU analog of the reference's
    # flat-params design (MultiLayerNetwork.init() flattening). On by
    # default for the single-process fit path; sharded training keeps
    # per-leaf state.
    packed_state: bool = True
    # Batches grouped per device dispatch in all three fit loops
    # (MultiLayerNetwork.fit, ComputationGraph.fit, SameDiff.fit; >1 =
    # opt-in): K same-shape batches run as ONE unrolled jitted program.
    # For dispatch-bound small steps (char-RNN 2x512: 3.46 ms device step
    # vs ~5 ms host cost per dispatch through a remote tunnel) this is the
    # difference between 1.8M and 3.9M tokens/s. Costs K-fold compile
    # time; losses/listeners still observe every step.
    dispatch_unroll: int = 1
    # AOT dispatch fast path (runtime/compile_cache.AotCache): the fit
    # loops and serving replicas call cached lower().compile() executables
    # per (graph, shape, mesh) signature instead of re-entering jit
    # dispatch every step. Bit-identical results (same trace, same
    # executable); any signature drift falls back to the jit path. On by
    # default; DL4J_TPU_AOT_DISPATCH=0 disables.
    aot_dispatch: bool = True

    def set_remat(self, enabled: bool = True) -> "Environment":
        self.remat_segments = bool(enabled)
        return self

    def set_default_dtype(self, dtype) -> "Environment":
        self.default_dtype = _coerce_dtype(dtype)
        return self

    def set_compute_dtype(self, dtype) -> "Environment":
        self.compute_dtype = _coerce_dtype(dtype)
        return self

    def allow_bfloat16(self) -> "Environment":
        """Enable the standard TPU mixed-precision policy (bf16 compute)."""
        self.compute_dtype = jnp.bfloat16
        return self

    def enable_bf16_state(self) -> "Environment":
        """FULL-bf16 training state: parameters AND optimizer moments live
        in bfloat16 (compute already bf16). An HBM-traffic knob for
        bandwidth-bound steps — BERT-base measured 35.8 vs 40.5 GB/step and
        1724 vs 1637 samples/s on v5e. CAVEAT: bf16 has ~3 significant
        digits, so parameter updates smaller than ~param*0.004 round away —
        fine for pre-training-scale learning rates, risky for tiny
        fine-tune LRs (2e-5 on mature weights). Opt-in, never default."""
        self.default_dtype = jnp.bfloat16
        self.compute_dtype = jnp.bfloat16
        return self

    def set_packed_state(self, enabled: bool = True) -> "Environment":
        self.packed_state = bool(enabled)
        return self

    def set_dispatch_unroll(self, k: int) -> "Environment":
        if int(k) < 1:
            raise ValueError("dispatch_unroll must be >= 1")
        self.dispatch_unroll = int(k)
        return self

    def set_aot_dispatch(self, enabled: bool = True) -> "Environment":
        self.aot_dispatch = bool(enabled)
        return self

    def set_compile_cache(self, directory: str) -> "Environment":
        """Enable the persistent executable cache rooted at ``directory``
        (builder-knob form of ``DL4J_TPU_COMPILE_CACHE``); see
        :mod:`deeplearning4j_tpu.runtime.compile_cache`."""
        from deeplearning4j_tpu.runtime import compile_cache
        self.cache_compiled = compile_cache.enable(directory)
        return self

    def set_nan_panic(self, enabled: bool) -> "Environment":
        self.nan_panic = enabled
        jax.config.update("jax_debug_nans", bool(enabled))
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {
            "default_dtype": jnp.dtype(self.default_dtype).name,
            "compute_dtype": jnp.dtype(self.compute_dtype).name,
            "nan_panic": self.nan_panic,
            "verbose": self.verbose,
            "debug": self.debug,
            "cache_compiled": self.cache_compiled,
            "memory_fraction": self.memory_fraction,
            "remat_segments": self.remat_segments,
            "packed_state": self.packed_state,
            "dispatch_unroll": self.dispatch_unroll,
            "aot_dispatch": self.aot_dispatch,
        }


def _coerce_dtype(dtype):
    if isinstance(dtype, str):
        if dtype not in _DTYPES:
            raise ValueError(f"Unknown dtype {dtype!r}; expected one of {sorted(_DTYPES)}")
        return _DTYPES[dtype]
    return jnp.dtype(dtype).type


_lock = threading.Lock()  # guards: (_instance singleton construction)
_instance: Optional[Environment] = None


def get_environment() -> Environment:
    """Return the process-wide :class:`Environment` singleton.

    First call reads ``DL4J_TPU_*`` environment variables:
    ``DL4J_TPU_DTYPE``, ``DL4J_TPU_COMPUTE_DTYPE``, ``DL4J_TPU_NAN_PANIC``,
    ``DL4J_TPU_VERBOSE``, ``DL4J_TPU_DEBUG``, ``DL4J_TPU_COMPILE_CACHE``,
    ``DL4J_TPU_AOT_DISPATCH``.
    """
    global _instance
    with _lock:
        if _instance is None:
            env = Environment()
            if os.environ.get(_ENV_PREFIX + "DTYPE"):
                env.set_default_dtype(os.environ[_ENV_PREFIX + "DTYPE"])
            if os.environ.get(_ENV_PREFIX + "COMPUTE_DTYPE"):
                env.set_compute_dtype(os.environ[_ENV_PREFIX + "COMPUTE_DTYPE"])
            if os.environ.get(_ENV_PREFIX + "NAN_PANIC", "").lower() in ("1", "true"):
                env.set_nan_panic(True)
            env.verbose = os.environ.get(_ENV_PREFIX + "VERBOSE", "").lower() in ("1", "true")
            env.debug = os.environ.get(_ENV_PREFIX + "DEBUG", "").lower() in ("1", "true")
            env.remat_segments = os.environ.get(
                _ENV_PREFIX + "REMAT", "").lower() in ("1", "true")
            if os.environ.get(_ENV_PREFIX + "PACKED_STATE", "").lower() in ("0", "false"):
                env.packed_state = False
            if os.environ.get(_ENV_PREFIX + "DISPATCH_UNROLL", "").isdigit():
                # "0" from the environment means "disable" — clamp to the
                # no-grouping value instead of tripping the >=1 validation.
                env.set_dispatch_unroll(
                    max(1, int(os.environ[_ENV_PREFIX + "DISPATCH_UNROLL"])))
            if os.environ.get(_ENV_PREFIX + "AOT_DISPATCH", "").lower() in (
                    "0", "false"):
                env.aot_dispatch = False
            cache = os.environ.get(_ENV_PREFIX + "COMPILE_CACHE")
            if cache:
                # full wiring (framework-keyed dir, counters, corrupt
                # tolerance) — not just the raw jax flag
                try:
                    from deeplearning4j_tpu.runtime import compile_cache
                    env.cache_compiled = compile_cache.enable(cache)
                except Exception:
                    # unwritable dir etc.: degrade to the plain jax knob
                    env.cache_compiled = cache
                    jax.config.update("jax_compilation_cache_dir", cache)
            _instance = env
        return _instance
