"""Memory crash reports.

Rebuild of upstream ``org.deeplearning4j.util.CrashReportingUtil``: on
training OOM the reference writes a full memory dump (system info, workspace
sizes, per-layer memory breakdown). TPU analog: HBM stats from the PJRT
device, per-layer parameter memory breakdown, compiled-program stats, and
the XLA error text — written to a timestamped file + returned as a string.

Wire-up: ``CrashReportingUtil.wrap(fn, model)`` runs ``fn`` and produces the
report on ``XlaRuntimeError``/``RESOURCE_EXHAUSTED``.

Black-box wiring (ISSUE 15): every written (or failed) dump emits a
``crash.report`` event into the fleet event journal carrying the report
path and the active trace id, so the one artifact the debug bundle pulls
(``serving/blackbox.py`` includes the newest N dump files) is also an
entry in the ordered incident timeline. ``CrashReportingUtil.clock`` is
injectable (default ``datetime.datetime.now``) so tests drive the
timestamped filename deterministically.
"""

from __future__ import annotations

import datetime
import os
import platform
import sys
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu.runtime import journal


class CrashReportingUtil:
    crash_dump_dir: Optional[str] = None
    enabled: bool = True
    #: injectable wall clock (ISSUE 15 satellite): returns a
    #: ``datetime.datetime`` — drives both the report header and the
    #: dump filename, so tests assert exact paths without freezing time
    clock: Callable[[], datetime.datetime] = datetime.datetime.now

    @staticmethod
    def memory_report(model=None, error: Optional[BaseException] = None) -> str:
        import jax

        from deeplearning4j_tpu.runtime import trace
        lines = ["===== deeplearning4j_tpu memory / crash report =====",
                 f"time: {CrashReportingUtil.clock().isoformat()}",
                 f"python: {sys.version.split()[0]}  platform: {platform.platform()}",
                 f"jax: {jax.__version__}  backend: {jax.devices()[0].platform}",
                 f"devices: {[str(d) for d in jax.devices()]}",
                 # the active trace id (ISSUE 9): a crash report joins the
                 # flight recorder's trace of the request/step that died
                 f"trace: {trace.current_trace_id() or '-'}"]
        if error is not None:
            lines += ["", "---- error ----", repr(error)]
        lines += ["", "---- device memory ----"]
        for d in jax.devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if stats:
                for k, v in sorted(stats.items()):
                    if "bytes" in k:
                        lines.append(f"  {d}: {k:32s} {v / (1 << 20):12.1f} MiB")
            else:
                lines.append(f"  {d}: memory stats unavailable")
        if model is not None and getattr(model, "train_state", None) is not None:
            lines += ["", "---- parameter memory breakdown ----"]
            total = 0
            for layer, sub in model.train_state.params.items():
                import jax as _jax
                n = sum(int(np.prod(p.shape)) for p in _jax.tree.leaves(sub))
                b = sum(int(np.prod(p.shape)) * p.dtype.itemsize
                        for p in _jax.tree.leaves(sub))
                total += b
                lines.append(f"  {layer:28s} {n:12,d} params {b / (1 << 20):10.2f} MiB")
            lines.append(f"  {'TOTAL':28s} {'':12s}        {total / (1 << 20):10.2f} MiB")
            lines.append("  (optimizer state typically 1-2x this again; activations "
                         "depend on batch and rematerialisation policy)")
        return "\n".join(lines)

    @staticmethod
    def write_memory_crash_dump(model=None, error: Optional[BaseException] = None) -> str:
        report = CrashReportingUtil.memory_report(model, error)
        d = CrashReportingUtil.crash_dump_dir or os.getcwd()
        path = os.path.join(
            d, f"dl4j-tpu-memory-crash-dump-"
               f"{CrashReportingUtil.clock():%Y%m%d-%H%M%S}.txt")
        written = True
        try:
            with open(path, "w") as f:
                f.write(report)
        except OSError:
            written = False
        # the crash joins the black box: the event carries the report
        # path and (via journal.emit) the active trace id, so the bundle
        # and the timeline reference the same artifact (ISSUE 15)
        journal.emit("crash.report", path=path if written else None,
                     written=written,
                     error=type(error).__name__ if error else None)
        return report

    @staticmethod
    def wrap(fn, model=None):
        """Run ``fn()``; on an XLA OOM/runtime error, write the crash dump
        then re-raise (the reference hooks this into fit())."""
        try:
            return fn()
        except Exception as e:
            msg = str(e).upper()
            if CrashReportingUtil.enabled and (
                    "RESOURCE_EXHAUSTED" in msg or "OUT OF MEMORY" in msg
                    or "OOM" in msg):
                CrashReportingUtil.write_memory_crash_dump(model, e)
            raise
