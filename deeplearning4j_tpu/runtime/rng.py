"""Random number management.

The reference exposes a global, stateful ``Nd4j.getRandom()`` seeded from
``NeuralNetConfiguration.seed`` (upstream ``org.nd4j.linalg.factory.Nd4j`` +
``DefaultRandom``). Stateful global RNG is hostile to XLA (trace-once
semantics), so the TPU design threads `jax.random` keys explicitly through
init/forward; this module provides the seeded key *manager* that owns the root
key and hands out fresh subkeys — the ergonomic equivalent of the global RNG
with functional semantics underneath.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class RngManager:
    """Owns a root PRNG key; ``next_key()`` splits deterministically.

    One manager per network instance (seeded from the config seed, like the
    reference seeds its global RNG per-conf), so runs are reproducible and
    independent networks don't perturb each other's streams.
    """

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        # Lazy: creating a PRNG key runs a computation, which would
        # initialise the XLA backend at import time — and that must not
        # happen before jax.distributed.initialize() on multihost.
        self._key = None
        self._lock = threading.Lock()  # guards: _key

    @property
    def seed(self) -> int:
        return self._seed

    def next_key(self, n: Optional[int] = None):
        """Return one fresh subkey (or a batch of ``n``)."""
        with self._lock:
            if self._key is None:
                self._key = jax.random.PRNGKey(self._seed)
            if n is None:
                self._key, sub = jax.random.split(self._key)
                return sub
            self._key, *subs = jax.random.split(self._key, n + 1)
            return subs

    def reset(self, seed: Optional[int] = None) -> None:
        with self._lock:
            if seed is not None:
                self._seed = int(seed)
            self._key = None  # re-created lazily from the (new) seed

    def get_state(self) -> dict:
        """JSON-serializable stream position (seed + current key, or None
        when the stream is still at its lazily-initialised origin). The
        serializers persist this so restored training continues the SAME
        key stream instead of replaying from the seed."""
        with self._lock:
            return {"seed": self._seed,
                    "key": (None if self._key is None
                            else np.asarray(self._key).tolist())}

    def set_state(self, state: dict) -> None:
        with self._lock:
            self._seed = int(state["seed"])
            k = state.get("key")
            self._key = (None if k is None
                         else jnp.asarray(np.asarray(k, np.uint32)))


_default = RngManager(0)


def get_default_rng() -> RngManager:
    """Process default manager — analog of ``Nd4j.getRandom()``."""
    return _default


def set_default_seed(seed: int) -> None:
    _default.reset(seed)
