"""Cold-start layer: persistent executable cache + AOT dispatch fast path.

The reference stack splits one-time native-graph construction from cheap
per-call execution (SURVEY §2.1 native graph executor, §2.2 OpExecutioner
SPI). On this runtime the expensive one-time cost is XLA compilation — a
process restart or a registry hot-swap recompiles every bucket×replica
executable from scratch, and compile time gates time-to-ready. This module
closes both ends:

**Persistent executable cache** (:func:`enable`): wires JAX's persistent
compilation cache under a *framework-keyed* directory (one subdirectory per
jax version, so an upgrade never deserializes stale executables), forces
every executable to be cached (the default 1 s minimum-compile-time gate
would skip exactly the sub-second serving-bucket programs cold start is
made of), and instruments the load path:

- **hit / miss / corrupt counters + compile seconds**, exposed through
  :func:`stats`, ``runtime.profiler.compile_cache_stats`` and the serving
  ``/metrics`` endpoint (``compile_cache_hits_total`` …).
- **corrupt-entry tolerance**: a truncated or bit-rotten cache entry (or a
  fault injected at the ``runtime.compile_cache.load`` chaos point) is
  counted, logged, and answered with "not cached" — a cold compile is
  always a correct fallback; a bad cache file can never take the process
  down. The entry is rewritten by the post-compile cache write.

Knobs: ``DL4J_TPU_COMPILE_CACHE=<dir>`` environment variable (read by
``Environment``'s first-touch init) or
``get_environment().set_compile_cache(dir)``.

**AOT dispatch fast path** (:class:`AotCache`): the fit loops and the
serving replica pool re-dispatch ONE jitted program millions of times at a
fixed shape. ``jax.jit``'s dispatch still pays a python cache probe and
signature re-derivation per call; :class:`AotCache` instead keeps the
``lower().compile()`` executable per (graph, shape, mesh) signature and
calls it directly with the already-device-resident donated buffers. The
executable is compiled from the *same* jitted trace, so results are
bit-identical to the jit path — and any signature drift the caller's cheap
key missed raises ``TypeError`` at argument check (before execution or
donation), which falls back to the jit path, never to a wrong answer.
Disable with ``DL4J_TPU_AOT_DISPATCH=0`` or
``get_environment().set_aot_dispatch(False)``.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Hashable, Optional

import jax

from deeplearning4j_tpu.runtime import chaos, trace

logger = logging.getLogger(__name__)

#: Framework key for the cache directory: executables are only reusable
#: within one jax/jaxlib build, so the version is part of the path.
FRAMEWORK_KEY = "dl4j-tpu-v1"


class CompileCacheStats:
    """Thread-safe counters for the persistent cache + AOT layer."""

    def __init__(self):
        # guards: hits, misses, corrupt_entries, compiles, compile_seconds, retrieval_seconds, aot_compiles, aot_compile_seconds, aot_fallbacks
        self._lock = threading.Lock()
        self._zero()

    def _zero(self):  # holds: _lock (or pre-sharing, from __init__)
        self.hits = 0               # executables deserialized from the cache
        self.misses = 0             # consulted, absent -> backend compile
        self.corrupt_entries = 0    # unreadable entry -> fallback compile
        self.compiles = 0           # backend compiles observed
        self.compile_seconds = 0.0  # total backend compile wall time
        self.retrieval_seconds = 0.0  # total cache deserialize wall time
        self.aot_compiles = 0       # lower().compile() executables minted
        self.aot_compile_seconds = 0.0
        self.aot_fallbacks = 0      # signature drift -> jit path fallback

    def reset(self):
        with self._lock:
            self._zero()

    def record(self, field: str, dt: float = 0.0):
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)
            if field == "compiles":
                self.compile_seconds += dt
            elif field == "hits":
                self.retrieval_seconds += dt
            elif field == "aot_compiles":
                self.aot_compile_seconds += dt

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": is_enabled(),
                "cache_dir": _cache_dir,
                "hits": self.hits,
                "misses": self.misses,
                "corrupt_entries": self.corrupt_entries,
                "compiles": self.compiles,
                "compile_seconds": round(self.compile_seconds, 4),
                "retrieval_seconds": round(self.retrieval_seconds, 4),
                "aot_compiles": self.aot_compiles,
                "aot_compile_seconds": round(self.aot_compile_seconds, 4),
                "aot_fallbacks": self.aot_fallbacks,
            }


STATS = CompileCacheStats()

_cache_dir: Optional[str] = None
_hooks_installed = False
_orig_get = None


def stats() -> Dict[str, Any]:
    """Process-wide cache/AOT counters (see also
    ``runtime.profiler.compile_cache_stats`` and serving ``/metrics``).
    Note: hit/miss counts include jax's own small internal jits
    (convert_element_type etc.), not only model programs — they are true
    per-executable counts."""
    return STATS.snapshot()


def reset_stats() -> None:
    STATS.reset()


def is_enabled() -> bool:
    return _cache_dir is not None


def cache_dir() -> Optional[str]:
    return _cache_dir


def _install_hooks() -> None:
    """Patch the cache load path (counters + chaos + corrupt tolerance) and
    subscribe to jax's compile-duration monitoring stream. Idempotent."""
    global _hooks_installed, _orig_get
    if _hooks_installed:
        return
    from jax._src import compilation_cache as _cc

    _orig_get = _cc.get_executable_and_time

    def _guarded_get(cache_key, compile_options, backend):
        t0 = time.perf_counter()
        try:
            chaos.inject("runtime.compile_cache.load")
            executable, compile_time = _orig_get(
                cache_key, compile_options, backend)
        except (KeyboardInterrupt, SystemExit):
            raise  # an abort is not a corrupt entry; let it abort
        except BaseException as e:
            # Corrupt/truncated entry, deserialize failure, or an injected
            # fault: count it, answer "not cached", and let the caller
            # compile — the post-compile write refreshes the bad entry.
            STATS.record("corrupt_entries")
            logger.warning(
                "compile cache: entry %s unreadable (%s: %s); falling back "
                "to a fresh compile", str(cache_key)[:16],
                type(e).__name__, e)
            return None, None
        if executable is None:
            STATS.record("misses")
        else:
            STATS.record("hits", time.perf_counter() - t0)
        return executable, compile_time

    _cc.get_executable_and_time = _guarded_get

    try:  # compile seconds ride jax's monitoring stream (best effort)
        from jax._src import monitoring

        def _on_duration(name: str, dur: float, **kw) -> None:
            if name == "/jax/core/compile/backend_compile_duration":
                STATS.record("compiles", dur)

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # pragma: no cover - monitoring API moved
        logger.debug("compile cache: no monitoring stream; compile-seconds "
                     "counter disabled", exc_info=True)
    _hooks_installed = True


def enable(directory: Optional[str] = None) -> str:
    """Turn on the persistent executable cache rooted at ``directory``
    (default: the ``DL4J_TPU_COMPILE_CACHE`` environment variable).
    Returns the resolved framework-keyed cache directory. Safe to call
    repeatedly / with a new directory."""
    global _cache_dir
    base = directory or os.environ.get("DL4J_TPU_COMPILE_CACHE")
    if not base:
        raise ValueError("compile_cache.enable() needs a directory (or set "
                         "DL4J_TPU_COMPILE_CACHE)")
    resolved = os.path.join(os.path.abspath(os.path.expanduser(base)),
                            f"{FRAMEWORK_KEY}-jax{jax.__version__}")
    os.makedirs(resolved, exist_ok=True)
    _install_hooks()
    jax.config.update("jax_compilation_cache_dir", resolved)
    # Cache EVERYTHING: serving cold start is dominated by many sub-second
    # bucket×replica compiles that the default 1s/size floors would skip.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    try:  # drop a previously-initialized handle so the new dir takes effect
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # pragma: no cover
        logger.debug("compile cache: reset_cache unavailable", exc_info=True)
    _cache_dir = resolved
    logger.info("compile cache enabled at %s", resolved)
    return resolved


def disable() -> None:
    """Detach the persistent cache (counters and hooks stay; they are
    inert without a configured directory)."""
    global _cache_dir
    if _cache_dir is None:
        return
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # pragma: no cover
        pass
    _cache_dir = None


# --------------------------------------------------------------------- AOT
def aot_enabled() -> bool:
    from deeplearning4j_tpu.runtime.environment import get_environment
    return bool(get_environment().aot_dispatch)


class AotCache:
    """Cache of AOT ``lower().compile()`` executables for ONE call site.

    ``call(key, jitted, *args)`` runs ``jitted``'s program for ``args``
    through a cached compiled executable — minting it with
    ``jitted.lower(*args).compile()`` on first sight of ``key``. The caller
    owns the key (cheap structural signatures like ``(x.shape, x.dtype)``
    beat re-flattening the whole arg tree every step); a key collision is
    harmless: the executable's own argument check raises ``TypeError``
    BEFORE anything executes or donates, and the call falls back to the
    jit path (same math, one wasted probe).

    Not locked: every current call site dispatches from a single thread
    (fit loop / batcher coalescer); a racing duplicate mint would only
    waste one compile.
    """

    __slots__ = ("name", "_entries")

    def __init__(self, name: str = ""):
        self.name = name
        self._entries: Dict[Hashable, Any] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def clear(self) -> None:
        self._entries.clear()

    def evict(self, pred) -> int:
        """Drop every entry whose key satisfies ``pred`` (ISSUE 10: a
        retired serving replica's executables leave the ledger so
        ``compile_count`` keeps describing the LIVE pool). Returns the
        number evicted. An execution already dispatched through an
        evicted entry is unaffected — eviction only forgets the handle."""
        dead = [k for k in list(self._entries) if pred(k)]
        for k in dead:
            self._entries.pop(k, None)
        return len(dead)

    def call(self, key: Hashable, jitted, *args):
        if not aot_enabled():
            return jitted(*args)
        entry = self._entries.get(key)
        # the dispatching span (batcher dispatch stage, fit-loop step)
        # gets the executable-cache outcome stamped on it (ISSUE 9)
        trace.annotate_current("aot", "hit" if entry is not None else "miss")
        if entry is None:
            t0 = time.perf_counter()
            entry = jitted.lower(*args).compile()
            STATS.record("aot_compiles", time.perf_counter() - t0)
            self._entries[key] = entry
        try:
            return entry(*args)
        except (TypeError, ValueError):
            # The caller's key was too coarse for these arguments — a shape
            # the structural key missed or a weak-type flip (TypeError), or
            # a sharding/layout change (ValueError: e.g. FSDP state whose
            # bias shardings XLA re-assigns after the first step). Both are
            # raised by the executable's argument check BEFORE anything
            # executes or donates: drop the entry and take the
            # always-correct jit path; the next call re-lowers from the
            # now-stable arguments.
            self._entries.pop(key, None)
            STATS.record("aot_fallbacks")
            logger.debug("AotCache(%s): signature drift at key %r; falling "
                         "back to jit dispatch", self.name, key)
            return jitted(*args)
