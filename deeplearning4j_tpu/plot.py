"""t-SNE embedding (reference ``org.deeplearning4j.plot.BarnesHutTsne``).

The reference approximates the N-body repulsion with a Barnes-Hut quadtree
(O(N log N)) because its per-op CPU/CUDA dispatch can't afford the dense
pairwise kernel. On TPU the dense formulation IS the fast path — an (N, N)
student-t kernel is a handful of fused MXU matmuls, so this implementation
runs *exact* t-SNE, fully jitted (per-point bandwidth calibration by
vectorized bisection + the full gradient-descent loop in one
``lax.fori_loop``). Same API surface/semantics as the reference (perplexity,
learning rate, momentum schedule, early exaggeration); ``theta`` is accepted
for signature parity and ignored (exact mode ≡ theta=0).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("perplexity",))
def _conditional_probs(x, perplexity: float):
    """Per-point Gaussian bandwidths by bisection so each row of P has the
    target perplexity; returns symmetrized joint probabilities."""
    n = x.shape[0]
    sq = jnp.sum(x * x, axis=1)
    d2 = sq[:, None] - 2.0 * (x @ x.T) + sq[None, :]
    d2 = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, d2)
    log_perp = jnp.log(perplexity)

    def row_probs(beta):
        p = jnp.exp(-d2 * beta[:, None])
        psum = jnp.maximum(p.sum(axis=1), 1e-12)
        # diagonal: d2=inf, p=0 — guard the whole product (inf*0 is nan)
        h = jnp.log(psum) + beta * jnp.sum(
            jnp.where(jnp.isinf(d2), 0.0, d2 * p), axis=1) / psum
        return p / psum[:, None], h

    def bisect_step(_, state):
        beta, lo, hi = state
        _, h = row_probs(beta)
        too_high = h > log_perp  # entropy too high -> increase beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2.0, (lo + hi) / 2.0)
        return beta, lo, hi

    beta0 = jnp.ones((n,))
    lo0 = jnp.zeros((n,))
    hi0 = jnp.full((n,), jnp.inf)
    beta, _, _ = jax.lax.fori_loop(0, 50, bisect_step, (beta0, lo0, hi0))
    p, _ = row_probs(beta)
    p = (p + p.T) / (2.0 * n)
    return jnp.maximum(p, 1e-12)


@functools.partial(jax.jit, static_argnames=("n_iter", "exaggeration_iters"))
def _tsne_optimize(p, y0, n_iter: int, learning_rate, exaggeration_iters: int):
    n = p.shape[0]

    def grad_kl(y, pp):
        sq = jnp.sum(y * y, axis=1)
        num = 1.0 / (1.0 + sq[:, None] - 2.0 * (y @ y.T) + sq[None, :])
        num = jnp.where(jnp.eye(n, dtype=bool), 0.0, num)
        q = jnp.maximum(num / jnp.maximum(num.sum(), 1e-12), 1e-12)
        w = (pp - q) * num
        return 4.0 * ((jnp.diag(w.sum(axis=1)) - w) @ y)

    def step(i, state):
        y, vel, gains = state
        pp = jnp.where(i < exaggeration_iters, p * 12.0, p)
        g = grad_kl(y, pp)
        momentum = jnp.where(i < 250, 0.5, 0.8)
        same_sign = jnp.sign(g) == jnp.sign(vel)
        gains = jnp.clip(jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
        vel = momentum * vel - learning_rate * gains * g
        y = y + vel
        return y - y.mean(axis=0), vel, gains

    y, _, _ = jax.lax.fori_loop(
        0, n_iter, step, (y0, jnp.zeros_like(y0), jnp.ones_like(y0)))
    return y


class BarnesHutTsne:
    """Builder mirrors the reference::

        tsne = (BarnesHutTsne.builder().set_max_iter(500).perplexity(30.0)
                .theta(0.5).learning_rate(200.0).num_dimension(2).build())
        tsne.fit(x)            # (N, D) -> (N, 2)
        y = tsne.get_data()
    """

    def __init__(self, max_iter: int = 1000, perplexity: float = 30.0,
                 theta: float = 0.5, learning_rate: float = 200.0,
                 num_dimensions: int = 2, seed: int = 0,
                 stop_lying_iteration: int = 250):
        self.max_iter = max_iter
        self.perplexity = perplexity
        self.theta = theta  # accepted for parity; exact mode ignores it
        self.learning_rate = learning_rate
        self.num_dimensions = num_dimensions
        self.seed = seed
        self.stop_lying_iteration = stop_lying_iteration
        self._y: Optional[np.ndarray] = None

    class Builder:
        def __init__(self):
            self._kw = {}

        def set_max_iter(self, v):
            self._kw["max_iter"] = int(v)
            return self

        def perplexity(self, v):
            self._kw["perplexity"] = float(v)
            return self

        def theta(self, v):
            self._kw["theta"] = float(v)
            return self

        def learning_rate(self, v):
            self._kw["learning_rate"] = float(v)
            return self

        def num_dimension(self, v):
            self._kw["num_dimensions"] = int(v)
            return self

        def stop_lying_iteration(self, v):
            self._kw["stop_lying_iteration"] = int(v)
            return self

        def seed(self, v):
            self._kw["seed"] = int(v)
            return self

        def build(self) -> "BarnesHutTsne":
            return BarnesHutTsne(**self._kw)

    @staticmethod
    def builder() -> "BarnesHutTsne.Builder":
        return BarnesHutTsne.Builder()

    def fit(self, x) -> np.ndarray:
        x = jnp.asarray(np.asarray(x, np.float32))
        n = x.shape[0]
        perp = min(self.perplexity, (n - 1) / 3.0)
        p = _conditional_probs(x, float(perp))
        y0 = jax.random.normal(jax.random.PRNGKey(self.seed),
                               (n, self.num_dimensions)) * 1e-2
        y = _tsne_optimize(p, y0, int(self.max_iter),
                           jnp.float32(self.learning_rate),
                           int(min(self.stop_lying_iteration, self.max_iter)))
        self._y = np.asarray(y)
        return self._y

    def get_data(self) -> np.ndarray:
        if self._y is None:
            raise RuntimeError("call fit() first")
        return self._y

    def save_as_file(self, labels, path: str) -> None:
        """Reference ``saveAsFile``: one 'coord,...,label' line per point."""
        y = self.get_data()
        with open(path, "w") as f:
            for row, lab in zip(y, labels):
                f.write(",".join(f"{v:.6f}" for v in row) + f",{lab}\n")
