"""Plan executors: run a model through a multi-axis :class:`ParallelPlan`.

This is the glue that makes ``pipe`` a *consumable* plan axis (ISSUE 20):
:class:`PipePlanExecutor` packs a `MultiLayerNetwork`'s uniform trunk into a
stage-stacked param tree (leading dim = pipe stages, sharded ``P('pipe')`` so
each pipe device holds 1/S of the trunk), and builds train/forward functions
that route the trunk through :func:`~deeplearning4j_tpu.parallel.pipeline.gpipe`
while the head/tail layers run exactly the model's own ``_forward`` math.
``ParallelWrapper.fit`` and the serving ``ReplicaPool`` both consume it, so an
oversized model trains and serves through the same pipelined executor with no
caller changes.

Shape of the thing::

    layers:   [head ...][ trunk: S stages x k layers each ][... tail, output]
    params:   {head keys..., "__pipe_trunk__": {"t0": stacked, ...}, tail keys}
    stacked:  every trunk leaf gains a leading stage dim, NamedSharding P(pipe)

Numerics: head/tail layers replay ``MultiLayerNetwork._forward`` line for
line (same rng fold-in per global layer index, same weight-noise keys, same
output-layer input-dropout placement), and the trunk's per-row math is
unchanged by pipelining — gpipe's shift register reorders nothing within a
microbatch. With ``pipe_microbatches=1`` the whole trained trajectory is the
oracle's; at M>1 microbatch gradient accumulation reassociates the batch
contraction (allclose, not bitwise — the same tradeoff every GPipe system
makes).

Eligibility is checked loudly: the trunk must be a run of shape-preserving,
stateless, structurally identical layers with no per-layer features that
couple stages (weight noise, constraints, l1/l2, weight decay, frozen flags,
global gradient clipping). Everything outside the trunk keeps the model's
full feature set.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.pipeline import gpipe
from deeplearning4j_tpu.parallel.sharding import ParallelPlan
from deeplearning4j_tpu.runtime.mesh import PIPE_AXIS

#: params key holding the stage-stacked trunk subtree
TRUNK_KEY = "__pipe_trunk__"


def _layer_key(i, layer):
    from deeplearning4j_tpu.models.multi_layer_network import _layer_key as lk
    return lk(i, layer)


class PipePlanExecutor:
    """Pipe-axis executor for one (MultiLayerNetwork, plan) pair.

    The plan's mesh must carry a ``pipe`` axis; its other axes keep their
    usual roles (``data`` shards the batch through ``gpipe(batch_axes=...)``).
    One executor is bound to one mesh — serving builds one per replica device
    group (shard_map bakes the mesh into the lowered program).
    """

    def __init__(self, model, plan: ParallelPlan):
        if plan.pipe_size < 2:
            raise ValueError("PipePlanExecutor needs a pipe axis of size >= 2; "
                             f"plan {plan.kind} has {plan.pipe_size}")
        if not hasattr(model, "layers") or not hasattr(model, "_forward"):
            raise NotImplementedError(
                "pipe-axis plans drive MultiLayerNetwork-style layer stacks; "
                f"{type(model).__name__} has no uniform layer list to stage")
        self.model = model
        self.plan = plan
        self.S = plan.pipe_size
        if model.train_state is None:
            model.init()
        self._find_trunk()

    # ------------------------------------------------------------ eligibility
    def _find_trunk(self):
        model, S = self.model, self.S
        layers = model.layers
        n = len(layers)
        params = model.train_state.params
        state = model.train_state.model_state
        g = model.conf.global_conf

        def eligible(i):
            layer = layers[i]
            k = _layer_key(i, layer)
            if i == n - 1 and hasattr(layer, "compute_loss"):
                return False  # the loss head always stays in the tail
            if i in model.conf.preprocessors:
                return False
            if k in state and state[k]:
                return False  # stateful layers can't stream through the ring
            if k not in params:
                return False
            if getattr(layer, "weight_noise", None) is not None:
                return False
            if getattr(layer, "constraints", None) or \
                    getattr(layer, "bias_constraints", None):
                return False
            if layer.frozen:
                return False
            l1 = layer.l1 if layer.l1 is not None else g.l1
            l2 = layer.l2 if layer.l2 is not None else g.l2
            wd = layer.weight_decay if layer.weight_decay is not None \
                else g.weight_decay
            if l1 or l2 or wd:
                return False  # reg walks per-layer keys; stacked keys would
                # silently drop the trunk's penalty
            return True

        def uniform(i, j):
            a, b = layers[i], layers[j]
            if type(a) is not type(b):
                return False
            if getattr(a, "activation", None) != getattr(b, "activation", None):
                return False
            if getattr(a, "updater", None) != getattr(b, "updater", None):
                return False
            pa = params[_layer_key(i, a)]
            pb = params[_layer_key(j, b)]
            sa = jax.tree.map(lambda x: (x.shape, x.dtype), pa)
            sb = jax.tree.map(lambda x: (x.shape, x.dtype), pb)
            return jax.tree.structure(pa) == jax.tree.structure(pb) \
                and jax.tree.leaves(sa) == jax.tree.leaves(sb)

        best: Tuple[int, int] = (0, 0)  # (start, length)
        i = 0
        while i < n:
            if not eligible(i):
                i += 1
                continue
            j = i + 1
            while j < n and eligible(j) and uniform(i, j):
                j += 1
            if j - i > best[1]:
                best = (i, j - i)
            i = j
        start, length = best
        length -= length % S  # spare layers stay in the tail
        if length < S:
            raise ValueError(
                f"no uniform trunk of >= {S} shape-preserving stateless "
                f"layers found for a pipe axis of {S} (longest run: "
                f"{best[1]}); pipe plans need a transformer-style stack — "
                "use fsdp/tensor axes for this model instead")
        if g.gradient_normalization:
            raise NotImplementedError(
                "global gradient normalization couples pipe stages through "
                "the stacked trunk — train this model unpipelined, or drop "
                "gradient_normalization")
        self.t0 = start
        self.n_trunk = length
        self.k = length // S
        self.head: List[int] = list(range(start))
        self.tail: List[int] = list(range(start + length, n))
        self.trunk_keys = {_layer_key(i, layers[i])
                           for i in range(start, start + length)}

    # ---------------------------------------------------------- param packing
    def pack_params(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Per-layer tree -> packed tree: trunk keys collapse into
        ``TRUNK_KEY`` holding, per in-stage position j, the stage-stacked
        leaves (leading dim S)."""
        layers = self.model.layers
        packed = {k: v for k, v in params.items() if k not in self.trunk_keys}
        sub = {}
        for j in range(self.k):
            stage_trees = [params[_layer_key(self.t0 + s * self.k + j,
                                             layers[self.t0 + s * self.k + j])]
                           for s in range(self.S)]
            sub[f"t{j}"] = jax.tree.map(lambda *xs: jnp.stack(xs), *stage_trees)
        packed[TRUNK_KEY] = sub
        return packed

    def unpack_params(self, packed: Dict[str, Any]) -> Dict[str, Any]:
        layers = self.model.layers
        params = {k: v for k, v in packed.items() if k != TRUNK_KEY}
        for s in range(self.S):
            for j in range(self.k):
                i = self.t0 + s * self.k + j
                params[_layer_key(i, layers[i])] = jax.tree.map(
                    lambda a, s=s: a[s], packed[TRUNK_KEY][f"t{j}"])
        return params

    def pack_sharding(self, packed: Dict[str, Any]) -> Dict[str, Any]:
        """NamedShardings for a packed tree on this executor's mesh: trunk
        leaves shard their leading stage dim over ``pipe``; head/tail leaves
        follow the plan's param rule (fsdp/tensor)."""
        rest = {k: v for k, v in packed.items() if k != TRUNK_KEY}
        sh = self.plan.param_sharding(rest) if rest else {}
        sh[TRUNK_KEY] = jax.tree.map(
            lambda _: NamedSharding(self.plan.mesh, P(PIPE_AXIS)),
            packed[TRUNK_KEY])
        return sh

    def place_packed(self, packed: Dict[str, Any]) -> Dict[str, Any]:
        return jax.tree.map(jax.device_put, packed, self.pack_sharding(packed))

    # -------------------------------------------------------------- forward
    def _apply_outer_layer(self, params, model_state, new_state, x, i,
                           training, rng):
        """One head/tail layer, replaying MultiLayerNetwork._forward's
        non-recurrent branch (same fold-in indices, same noise keys, same
        output-layer input-dropout placement). Returns (x, last_input)."""
        from deeplearning4j_tpu.nn.constraints import apply_weight_noise
        model = self.model
        layer = model.layers[i]
        n = len(model.layers)
        k = _layer_key(i, layer)
        if i in model.conf.preprocessors:
            x = model.conf.preprocessors[i].pre_process(x, None)
        p = params.get(k, {})
        s = model_state.get(k, {})
        lrng = jax.random.fold_in(rng, i) if rng is not None else None
        if training and getattr(layer, "weight_noise", None) is not None:
            p = apply_weight_noise(
                layer, p,
                None if lrng is None else jax.random.fold_in(lrng, 7919))
        last_input = None
        if i == n - 1 and hasattr(layer, "compute_loss"):
            x = layer._apply_input_dropout(x, layer._g, training, lrng)
            last_input = x
            x = layer.activate(p, x)
        else:
            x, s_new = layer.forward(p, s, x, training=training, rng=lrng,
                                     mask=None)
            if s:
                new_state[k] = s_new
        return x, last_input

    def _stage_fn(self, training: bool, with_rng: bool):
        t0, k, rep = self.t0, self.k, self.model.layers

        def stage_fn(stage_tree, mb):
            s_idx = jax.lax.axis_index(PIPE_AXIS)
            x = mb
            for j in range(k):
                lrng = None
                if with_rng:
                    # same per-layer fold-in as _forward: global layer index
                    lrng = jax.random.fold_in(stage_tree["rng"],
                                              t0 + s_idx * k + j)
                x, _ = rep[t0 + j].forward(stage_tree["p"][f"t{j}"], {}, x,
                                           training=training, rng=lrng,
                                           mask=None)
            return x

        return stage_fn

    def packed_forward(self, params, model_state, x, *, training: bool, rng):
        """(out, pre_output_input, new_state) — the packed twin of
        ``MultiLayerNetwork._forward``."""
        from deeplearning4j_tpu.nn.base import cast_floating
        from deeplearning4j_tpu.runtime.environment import get_environment
        cdt = get_environment().compute_dtype
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != cdt:
            x = x.astype(cdt)
        params = cast_floating(params, cdt)
        new_state = dict(model_state)
        last_input = x
        for i in self.head:
            x, _ = self._apply_outer_layer(params, model_state, new_state, x,
                                           i, training, rng)
        trunk: Dict[str, Any] = {"p": params[TRUNK_KEY]}
        if rng is not None:
            trunk["rng"] = jnp.stack([rng] * self.S)
        # the microbatch count must divide this call's batch (a warmup
        # bucket of 1, say) — clamp to the largest divisor <= the plan's
        # schedule. Static per traced shape; per-row results don't depend
        # on the microbatch split, so bucket programs stay bit-identical.
        m = math.gcd(int(x.shape[0]), self.plan.pipe_microbatches)
        x = gpipe(self._stage_fn(training, rng is not None), trunk, x,
                  mesh=self.plan.mesh,
                  n_microbatches=m,
                  batch_axes=self.plan.batch_axes())
        for i in self.tail:
            x, li = self._apply_outer_layer(params, model_state, new_state, x,
                                            i, training, rng)
            if li is not None:
                last_input = li
        return x, last_input, new_state

    # ----------------------------------------------------------------- train
    def packed_tx(self) -> optax.GradientTransformation:
        """multi_transform over the packed tree: head/tail layers keep their
        per-layer transform; the trunk trains under the (uniform, checked
        elementwise-safe) trunk layer's transform applied to stacked leaves
        — elementwise updaters make stacked and per-layer updates the same
        bits."""
        model = self.model
        transforms, labels = {}, {}
        params = model.train_state.params
        for i in self.head + self.tail:
            layer = model.layers[i]
            k = _layer_key(i, layer)
            if k not in params:
                continue
            transforms[k] = model._layer_transform(layer)
            labels[k] = jax.tree.map(lambda _: k, params[k])
        trunk_layer = model.layers[self.t0]
        transforms[TRUNK_KEY] = model._layer_transform(trunk_layer)
        packed = self.pack_params(params)
        labels[TRUNK_KEY] = jax.tree.map(lambda _: TRUNK_KEY,
                                         packed[TRUNK_KEY])
        return optax.multi_transform(transforms, labels)

    def _packed_loss(self, params, model_state, x, y, rng, lmask,
                     training=True):
        from deeplearning4j_tpu.nn.base import cast_floating
        from deeplearning4j_tpu.nn.constraints import apply_weight_noise
        from deeplearning4j_tpu.runtime.environment import get_environment
        model = self.model
        out, last_in, new_state = self.packed_forward(
            params, model_state, x, training=training, rng=rng)
        final = model.layers[-1]
        if not hasattr(final, "compute_loss"):
            raise ValueError("Last layer must be an output/loss layer")
        k = _layer_key(len(model.layers) - 1, final)
        final_p = cast_floating(params.get(k, {}),
                                get_environment().compute_dtype)
        if training and getattr(final, "weight_noise", None) is not None \
                and rng is not None:
            lrng = jax.random.fold_in(rng, len(model.layers) - 1)
            final_p = apply_weight_noise(final, final_p,
                                         jax.random.fold_in(lrng, 7919))
        loss = final.compute_loss(final_p, last_in, y, mask=lmask,
                                  state=model_state.get(k, {}))
        # trunk keys are absent from the packed tree, so _reg_score walks
        # head/tail only (trunk reg is an eligibility error, never silent)
        loss = loss + model._reg_score(params)
        if training:
            for s2 in new_state.values():
                if isinstance(s2, dict) and "_aux_loss" in s2:
                    loss = loss + s2["_aux_loss"]
        if training and hasattr(final, "update_state_with_labels"):
            new_state = dict(new_state)
            new_state[k] = final.update_state_with_labels(
                model_state.get(k, {}), jax.lax.stop_gradient(last_in), y)
        return loss, new_state

    def make_train_step(self, tx: optax.GradientTransformation):
        """(packed_ts, x, y, rng, fmask, lmask) -> (packed_ts, loss); fmask
        must be structurally None (feature masks don't stream through the
        ring — the wrapper refuses them loudly)."""
        from deeplearning4j_tpu.models.multi_layer_network import TrainState
        model = self.model

        def step(ts, x, y, rng, fmask, lmask):
            if fmask is not None:
                raise NotImplementedError(
                    "feature masks are not supported under pipe-axis plans")
            (loss, new_state), grads = jax.value_and_grad(
                self._packed_loss, has_aux=True)(
                    ts.params, ts.model_state, x, y, rng, lmask)
            updates, new_opt = tx.update(grads, ts.opt_state, ts.params)
            new_params = model._apply_constraints(
                optax.apply_updates(ts.params, updates))
            return TrainState(params=new_params, model_state=new_state,
                              opt_state=new_opt, step=ts.step + 1), loss

        return step

    def packed_state(self):
        """(packed TrainState placed on the plan's mesh, packed tx). Updater
        slots are freshly initialised for the packed tree — same values as a
        fresh unpacked init (counts 0, zero moments), so a fit that starts
        here matches the oracle's fit from the same params."""
        from deeplearning4j_tpu.models.multi_layer_network import TrainState
        ts = self.model.train_state
        packed = self.place_packed(self.pack_params(ts.params))
        tx = self.packed_tx()
        opt = tx.init(packed)
        rep = self.plan.replicated()
        return TrainState(
            params=packed,
            model_state=jax.device_put(ts.model_state, rep),
            opt_state=opt,
            step=jax.device_put(ts.step, rep)), tx

    def sync_back(self, packed_ts) -> None:
        """Write a trained packed state back to the model's unpacked
        ``train_state`` (params/model_state/step). Updater slot state is
        re-initialised — stateful updaters (Adam moments) lose accumulation
        across the pack boundary; SGD-family trajectories are unaffected."""
        from deeplearning4j_tpu.models.multi_layer_network import TrainState
        params = jax.tree.map(jnp.asarray,
                              self.unpack_params(jax.device_get(
                                  packed_ts.params)))
        model = self.model
        model.train_state = TrainState(
            params=params,
            model_state=jax.device_get(packed_ts.model_state),
            opt_state=model._tx.init(model._trainable(params)),
            step=jnp.asarray(jax.device_get(packed_ts.step)))

    # ----------------------------------------------------------------- serve
    def make_forward(self):
        """jit'd (packed_params, model_state, x, mask) -> output — the packed
        twin of ``MultiLayerNetwork.output``'s inner fwd. The lowered program
        bakes this executor's mesh (serving builds one executor per replica
        device group)."""
        def fwd(params, model_state, x_, m_):
            if m_ is not None:
                raise NotImplementedError(
                    "feature masks are not supported under pipe-axis plans")
            out, _, _ = self.packed_forward(params, model_state, x_,
                                            training=False, rng=None)
            return out

        return jax.jit(fwd)
