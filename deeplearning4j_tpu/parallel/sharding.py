"""Sharding strategies: how params/optimizer state/batches map onto a Mesh.

This module is where the reference's parallelism *configuration* surface
(``ParallelWrapper.Builder``, ``SharedTrainingMaster.Builder``) becomes
TPU-native: a :class:`ShardingStrategy` names the mesh axes and produces
`jax.sharding.NamedSharding`s for every leaf of the train state and batch.

Strategies (reference → here):

- ``data_parallel``   — replicate params, shard batch on ``data``: the analog
  of every DP mode the reference has (param averaging, shared gradients,
  Spark masters). XLA emits the gradient psum over ICI.
- ``fsdp``            — additionally shard params/updater state on ``data``
  (ZeRO-3-style; the reference has nothing comparable — parity-plus).
- ``tensor_parallel`` — shard weight matrices on ``model`` (Megatron-style
  alternating column/row split for attention+FFN; parity-plus).

All strategies produce plain NamedShardings consumed by ``jax.jit`` /
``jax.device_put``; the same code path runs on a simulated CPU mesh and a
real TPU pod slice (SURVEY.md §7.5 item 5).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.runtime.mesh import DATA_AXIS, EXPERT_AXIS, MODEL_AXIS


@dataclasses.dataclass
class ShardingStrategy:
    """Produces shardings for state/batch pytrees over a mesh.

    ``param_rule(path, shape) -> PartitionSpec`` decides weight placement;
    the default replicates everything (pure DP).
    """

    mesh: Mesh
    param_rule: Optional[Callable[[Tuple[str, ...], Tuple[int, ...]], P]] = None
    batch_axis: str = DATA_AXIS

    # ---- factories ----
    @staticmethod
    def data_parallel(mesh: Mesh) -> "ShardingStrategy":
        return ShardingStrategy(mesh=mesh, param_rule=None)

    @staticmethod
    def fsdp(mesh: Mesh, min_size: int = 1024) -> "ShardingStrategy":
        """Shard every large param's first divisible axis over the data axis
        (ZeRO-3 style). Small params stay replicated."""
        axis_size = mesh.shape[DATA_AXIS]

        def rule(path, shape):
            if int(np.prod(shape)) < min_size:
                return P()
            for dim, s in enumerate(shape):
                if s % axis_size == 0 and s >= axis_size:
                    spec = [None] * len(shape)
                    spec[dim] = DATA_AXIS
                    return P(*spec)
            return P()

        return ShardingStrategy(mesh=mesh, param_rule=rule)

    @staticmethod
    def tensor_parallel(mesh: Mesh) -> "ShardingStrategy":
        """Megatron-style TP over the ``model`` axis: column-split the
        first/expanding matmul of a block (W_q/W_k/W_v, FFN in), row-split the
        contracting one (W_o, FFN out); embedding tables split on vocab."""
        tp = mesh.shape[MODEL_AXIS]

        COL = ("W_q", "W_k", "W_v", "b_q", "b_k", "b_v", "W_ff1", "b_ff1")
        ROW = ("W_o", "W_ff2")

        def rule(path, shape):
            keys = [getattr(p, "key", None) for p in path]
            leaf = keys[-1] if keys else None
            if leaf in COL:
                if shape[-1] % tp == 0:
                    return P(*([None] * (len(shape) - 1) + [MODEL_AXIS]))
            if leaf in ROW and len(shape) >= 2:
                if shape[-2] % tp == 0:
                    return P(*([None] * (len(shape) - 2) + [MODEL_AXIS, None]))
            return P()

        return ShardingStrategy(mesh=mesh, param_rule=rule)

    @staticmethod
    def expert_parallel(mesh: Mesh) -> "ShardingStrategy":
        """Shard MoE expert tables (leading expert dim: ``W_e1``, ``W_e2``,
        ``b_e1``, ``b_e2``) over the ``expert`` axis; GSPMD partitions the
        per-expert einsums across devices (no hand-written all-to-all)."""
        ep = mesh.shape[EXPERT_AXIS]
        EXPERT_KEYS = ("W_e1", "W_e2", "b_e1", "b_e2")

        def rule(path, shape):
            keys = [getattr(p, "key", None) for p in path]
            leaf = keys[-1] if keys else None
            if leaf in EXPERT_KEYS:
                if not shape or shape[0] % ep:
                    raise ValueError(
                        f"expert table {leaf} has {shape[0] if shape else 0} "
                        f"experts, not divisible by expert-axis size {ep} — "
                        f"replicating would silently disable expert parallelism")
                return P(*([EXPERT_AXIS] + [None] * (len(shape) - 1)))
            return P()

        return ShardingStrategy(mesh=mesh, param_rule=rule)

    # ---- application ----
    def param_sharding(self, tree) -> Any:
        """NamedSharding pytree for params/updater state."""
        def leaf_sharding(path, leaf):
            shape = getattr(leaf, "shape", ())
            spec = self.param_rule(path, tuple(shape)) if self.param_rule else P()
            # never shard scalars / axes that don't exist
            if len(spec) > len(shape):
                spec = P()
            return NamedSharding(self.mesh, spec)

        return jax.tree_util.tree_map_with_path(leaf_sharding, tree)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, ndim: int) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.batch_axis, *([None] * (ndim - 1))))


def shard_train_state(state, strategy: ShardingStrategy):
    """Place a TrainState onto the mesh. Params/opt state follow the param
    rule; scalars (step counters) replicate."""
    import dataclasses as dc
    from deeplearning4j_tpu.models.multi_layer_network import TrainState

    params_sh = strategy.param_sharding(state.params)
    params = jax.tree.map(jax.device_put, state.params, params_sh)
    opt_sh = strategy.param_sharding(state.opt_state)
    opt_state = jax.tree.map(jax.device_put, state.opt_state, opt_sh)
    model_state = jax.device_put(state.model_state, strategy.replicated())
    step = jax.device_put(state.step, strategy.replicated())
    return TrainState(params=params, model_state=model_state,
                      opt_state=opt_state, step=step)


def shard_batch(strategy: ShardingStrategy, *arrays):
    """Shard batch arrays along the data axis (pad-free: batch must divide
    by the data-axis size, as in the reference's even data distribution)."""
    out = []
    n = strategy.mesh.shape[strategy.batch_axis]
    for a in arrays:
        if a is None:
            out.append(None)
            continue
        if a.shape[0] % n:
            raise ValueError(
                f"Batch size {a.shape[0]} not divisible by data-parallel size {n}")
        out.append(jax.device_put(a, strategy.batch_sharding(a.ndim)))
    return out if len(out) > 1 else out[0]


def shard_batch_tree(strategy: ShardingStrategy, tree):
    """:func:`shard_batch` over an arbitrary pytree of batch arrays — the
    dict inputs / list labels / optional-mask dicts of a ComputationGraph
    batch. ``None`` leaves (absent masks) pass through unsharded."""
    return jax.tree_util.tree_map(
        lambda a: None if a is None else shard_batch(strategy, a),
        tree, is_leaf=lambda x: x is None)
