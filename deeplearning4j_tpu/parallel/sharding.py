"""Parallel plans: how params/optimizer state/batches map onto a Mesh.

This module is where the reference's parallelism *configuration* surface
(``ParallelWrapper.Builder``, ``SharedTrainingMaster.Builder``) becomes
TPU-native: a :class:`ParallelPlan` names the mesh axes and produces
`jax.sharding.NamedSharding`s for every leaf of the train state and batch.

A plan is a named-axis mesh (any subset of ``data`` / ``fsdp`` / ``model``
/ ``pipe`` / ``seq``, each sized 1..N) plus per-leaf placement rules.  The
classic single-axis strategies are degenerate plans:

- ``data_parallel``   — replicate params, shard batch on ``data``: the analog
  of every DP mode the reference has (param averaging, shared gradients,
  Spark masters). XLA emits the gradient psum over ICI.
- ``fsdp``            — additionally shard params/updater state on ``data``
  (ZeRO-3-style; the reference has nothing comparable — parity-plus).
- ``tensor_parallel`` — shard weight matrices on ``model`` (Megatron-style
  alternating column/row split for attention+FFN; parity-plus).
- ``expert_parallel`` — shard MoE expert tables on ``expert``.

and :meth:`ParallelPlan.compose` builds the Megatron-LM-style multi-axis
composition (data x fsdp x tensor x pipe [x seq]) on ONE mesh: the batch
dim shards over the tuple of data-carrying axes (``data`` and ``fsdp`` —
HSDP style, total DP degree = data*fsdp), weights shard over ``model``
(tensor rule) then ``fsdp`` (first divisible dim), and a ``pipe`` axis
selects the GPipe shift-register executor (``parallel/plan_exec.py``) for
the model's uniform trunk. ``seq`` selects ring attention for the
sequence dimension (``parallel/ring_attention.py``).

All plans produce plain NamedShardings consumed by ``jax.jit`` /
``jax.device_put``; the same code path runs on a simulated CPU mesh and a
real TPU pod slice (SURVEY.md §7.5 item 5). ``plan.signature()`` is the
hashable identity executors mix into AOT-cache keys so a plan change can
never serve a stale executable (it misses the cache and recompiles — or
falls back to jit — instead).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.runtime.mesh import (DATA_AXIS, EXPERT_AXIS, FSDP_AXIS,
                                             MODEL_AXIS, PIPE_AXIS, SEQ_AXIS,
                                             MeshSpec, create_mesh)


def _fsdp_rule(axis: str, axis_size: int, min_size: int):
    """Shard every large param's first divisible dim over ``axis`` (ZeRO-3
    style). Small params stay replicated."""
    def rule(path, shape):
        if int(np.prod(shape)) < min_size:
            return P()
        for dim, s in enumerate(shape):
            if s % axis_size == 0 and s >= axis_size:
                spec = [None] * len(shape)
                spec[dim] = axis
                return P(*spec)
        return P()
    return rule


def _tensor_rule(tp: int):
    """Megatron-style TP over the ``model`` axis: column-split the
    first/expanding matmul of a block (W_q/W_k/W_v, FFN in), row-split the
    contracting one (W_o, FFN out)."""
    COL = ("W_q", "W_k", "W_v", "b_q", "b_k", "b_v", "W_ff1", "b_ff1")
    ROW = ("W_o", "W_ff2")

    def rule(path, shape):
        keys = [getattr(p, "key", None) for p in path]
        leaf = keys[-1] if keys else None
        if leaf in COL:
            if shape[-1] % tp == 0:
                return P(*([None] * (len(shape) - 1) + [MODEL_AXIS]))
        if leaf in ROW and len(shape) >= 2:
            if shape[-2] % tp == 0:
                return P(*([None] * (len(shape) - 2) + [MODEL_AXIS, None]))
        return P()
    return rule


@dataclasses.dataclass
class ParallelPlan:
    """Produces shardings for state/batch pytrees over a named-axis mesh.

    ``param_rule(path, shape) -> PartitionSpec`` decides weight placement;
    the default replicates everything (pure DP). ``batch_axis`` is the mesh
    axis (or tuple of axes) the batch dim shards over. ``kind`` names the
    plan for signatures/manifests; ``pipe_microbatches`` is the GPipe
    schedule depth used by the pipe-axis executors (1 = staged-sequential:
    still distributed/memory-sharded, and the setting at which trained
    trajectories are bit-identical to the unpipelined oracle — microbatch
    splits only reorder gradient accumulation, like any DP resharding).
    """

    mesh: Mesh
    param_rule: Optional[Callable[[Tuple[Any, ...], Tuple[int, ...]], P]] = None
    batch_axis: Union[str, Tuple[str, ...]] = DATA_AXIS
    kind: str = "data_parallel"
    pipe_microbatches: int = 1

    # ---- degenerate single-axis plans (the PR-3 strategy surface) ----
    @staticmethod
    def data_parallel(mesh: Mesh) -> "ParallelPlan":
        return ParallelPlan(mesh=mesh, param_rule=None, kind="data_parallel")

    @staticmethod
    def fsdp(mesh: Mesh, min_size: int = 1024) -> "ParallelPlan":
        """Single-axis FSDP: batch AND params shard over ``data``."""
        return ParallelPlan(
            mesh=mesh,
            param_rule=_fsdp_rule(DATA_AXIS, mesh.shape[DATA_AXIS], min_size),
            kind="fsdp")

    @staticmethod
    def tensor_parallel(mesh: Mesh) -> "ParallelPlan":
        return ParallelPlan(mesh=mesh,
                            param_rule=_tensor_rule(mesh.shape[MODEL_AXIS]),
                            kind="tensor_parallel")

    @staticmethod
    def expert_parallel(mesh: Mesh) -> "ParallelPlan":
        """Shard MoE expert tables (leading expert dim: ``W_e1``, ``W_e2``,
        ``b_e1``, ``b_e2``) over the ``expert`` axis; GSPMD partitions the
        per-expert einsums across devices (no hand-written all-to-all)."""
        ep = mesh.shape[EXPERT_AXIS]
        EXPERT_KEYS = ("W_e1", "W_e2", "b_e1", "b_e2")

        def rule(path, shape):
            keys = [getattr(p, "key", None) for p in path]
            leaf = keys[-1] if keys else None
            if leaf in EXPERT_KEYS:
                if not shape or shape[0] % ep:
                    raise ValueError(
                        f"expert table {leaf} has {shape[0] if shape else 0} "
                        f"experts, not divisible by expert-axis size {ep} — "
                        f"replicating would silently disable expert parallelism")
                return P(*([EXPERT_AXIS] + [None] * (len(shape) - 1)))
            return P()

        return ParallelPlan(mesh=mesh, param_rule=rule, kind="expert_parallel")

    # -------------------------------------------------------------- compose
    @staticmethod
    def compose(data: int = 1, fsdp: int = 1, tensor: int = 1,
                pipe: int = 1, seq: int = 1, *,
                devices_: Optional[Sequence] = None,
                min_size: int = 1024,
                microbatches: int = 1) -> "ParallelPlan":
        """One mesh carrying every requested axis (sizes 1..N; exactly one
        may be -1 to mean "whatever is left over"), with the composed
        placement rules:

        - batch dim over ``(data, fsdp)`` — both are data-parallel axes
          (HSDP: total DP degree = data*fsdp); ``fsdp`` additionally
          shards params/updater state (first divisible dim, ZeRO-3),
        - tensor keys over ``model`` (checked first — a W_ff1 leaf must
          land on the tensor split, not the fsdp split),
        - ``pipe`` > 1 selects the GPipe executors for the model's uniform
          trunk (``parallel/plan_exec.py``); the pipe axis never appears
          in the per-leaf rule — trunk params are stage-stacked by the
          executor and sharded ``P(pipe)`` on their leading stage dim,
        - ``seq`` > 1 selects ring attention over the sequence axis.

        Axis order is ``pipe, data, fsdp, model, seq`` so pipe stages are
        the outermost (slowest-varying, ICI-farthest) placement, matching
        the usual Megatron/GPipe topology.
        """
        sizes = {PIPE_AXIS: pipe, DATA_AXIS: data, FSDP_AXIS: fsdp,
                 MODEL_AXIS: tensor, SEQ_AXIS: seq}
        if sum(1 for v in sizes.values() if v == -1) > 1:
            raise ValueError("at most one composed axis may be -1")
        spec = {k: int(v) for k, v in sizes.items() if v == -1 or int(v) > 1}
        if not spec:
            spec = {DATA_AXIS: 1}
        mesh = create_mesh(MeshSpec(spec), devices_=devices_)
        shp = mesh.shape
        rules = []
        if shp.get(MODEL_AXIS, 1) > 1:
            rules.append(_tensor_rule(shp[MODEL_AXIS]))
        if shp.get(FSDP_AXIS, 1) > 1:
            rules.append(_fsdp_rule(FSDP_AXIS, shp[FSDP_AXIS], min_size))

        def rule(path, shape):
            for r in rules:
                spec_ = r(path, shape)
                if tuple(spec_) != ():
                    return spec_
            return P()

        batch_axes = tuple(a for a in (DATA_AXIS, FSDP_AXIS) if a in shp)
        kind = "compose(" + ",".join(
            f"{a}={shp[a]}" for a in mesh.axis_names) + ")"
        return ParallelPlan(mesh=mesh,
                            param_rule=rule if rules else None,
                            batch_axis=batch_axes or DATA_AXIS,
                            kind=kind,
                            pipe_microbatches=max(1, int(microbatches)))

    # ---------------------------------------------------------- introspection
    def batch_axes(self) -> Tuple[str, ...]:
        """The batch-sharding axes as a tuple (single-axis plans included),
        filtered to axes the mesh actually carries."""
        axes = (self.batch_axis if isinstance(self.batch_axis, tuple)
                else (self.batch_axis,))
        return tuple(a for a in axes if a in self.mesh.shape)

    def batch_divisor(self) -> int:
        """Total data-parallel degree: the batch size must divide by this."""
        n = 1
        for a in self.batch_axes():
            n *= self.mesh.shape[a]
        return max(1, n)

    def axis_size(self, axis: str) -> int:
        return int(self.mesh.shape.get(axis, 1))

    @property
    def pipe_size(self) -> int:
        return self.axis_size(PIPE_AXIS)

    @property
    def seq_size(self) -> int:
        return self.axis_size(SEQ_AXIS)

    def devices_per_replica(self) -> int:
        """Serving view: devices consumed by ONE plan-slice replica — every
        axis except ``data`` (the data axis of a serving plan IS the
        replica fan-out)."""
        n = 1
        for a, s in self.mesh.shape.items():
            if a != DATA_AXIS:
                n *= int(s)
        return max(1, n)

    def signature(self) -> Tuple:
        """Hashable plan identity for AOT-cache keys and warmup manifests:
        kind + ordered (axis, size) pairs + batch axes + the pipe schedule.
        Any drift (axis added/resized, executor knob changed) produces a
        different key, so a changed plan can never hit a stale executable
        — it misses and recompiles, or the AOT layer falls back to jit."""
        return ("plan", self.kind,
                tuple((a, int(self.mesh.shape[a]))
                      for a in self.mesh.axis_names),
                self.batch_axes(), int(self.pipe_microbatches))

    def describe(self) -> Dict[str, Any]:
        """JSON-friendly twin of :meth:`signature` for manifests/capacity."""
        return {"kind": self.kind,
                "axes": {a: int(self.mesh.shape[a])
                         for a in self.mesh.axis_names},
                "batch_axes": list(self.batch_axes()),
                "pipe_microbatches": int(self.pipe_microbatches)}

    def replica_slice(self, devices) -> "ParallelPlan":
        """The per-replica sub-plan over one replica's device group: the
        same axes minus ``data`` (sized to ``devices``). Used by the
        serving tier, where a "replica" generalizes from one device to one
        plan-slice."""
        axes = {a: int(s) for a, s in self.mesh.shape.items()
                if a != DATA_AXIS and int(s) > 1}
        if not axes:
            axes = {DATA_AXIS: 1}
        mesh = create_mesh(MeshSpec(axes), devices_=list(devices))
        batch = tuple(a for a in self.batch_axes() if a in mesh.shape)
        return ParallelPlan(mesh=mesh, param_rule=self.param_rule,
                            batch_axis=batch or DATA_AXIS,
                            kind=self.kind + "/slice",
                            pipe_microbatches=self.pipe_microbatches)

    # ---- application ----
    def param_sharding(self, tree) -> Any:
        """NamedSharding pytree for params/updater state."""
        def leaf_sharding(path, leaf):
            shape = getattr(leaf, "shape", ())
            spec = self.param_rule(path, tuple(shape)) if self.param_rule else P()
            # never shard scalars / axes that don't exist
            if len(spec) > len(shape):
                spec = P()
            return NamedSharding(self.mesh, spec)

        return jax.tree_util.tree_map_with_path(leaf_sharding, tree)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, ndim: int) -> NamedSharding:
        axes = self.batch_axes()
        lead = axes if len(axes) > 1 else (axes[0] if axes else None)
        return NamedSharding(self.mesh, P(lead, *([None] * (ndim - 1))))


#: Backward-compatible name: a "strategy" has always been a degenerate plan.
ShardingStrategy = ParallelPlan


def shard_train_state(state, strategy: ParallelPlan):
    """Place a TrainState onto the mesh. Params/opt state follow the param
    rule; scalars (step counters) replicate."""
    from deeplearning4j_tpu.models.multi_layer_network import TrainState

    params_sh = strategy.param_sharding(state.params)
    params = jax.tree.map(jax.device_put, state.params, params_sh)
    opt_sh = strategy.param_sharding(state.opt_state)
    opt_state = jax.tree.map(jax.device_put, state.opt_state, opt_sh)
    model_state = jax.device_put(state.model_state, strategy.replicated())
    step = jax.device_put(state.step, strategy.replicated())
    return TrainState(params=params, model_state=model_state,
                      opt_state=opt_state, step=step)


def shard_batch(strategy: ParallelPlan, *arrays):
    """Shard batch arrays along the plan's data axes (pad-free: batch must
    divide by the total DP degree, as in the reference's even data
    distribution)."""
    out = []
    n = strategy.batch_divisor()
    for a in arrays:
        if a is None:
            out.append(None)
            continue
        if a.shape[0] % n:
            raise ValueError(
                f"Batch size {a.shape[0]} not divisible by data-parallel size {n}")
        out.append(jax.device_put(a, strategy.batch_sharding(a.ndim)))
    return out if len(out) > 1 else out[0]


def shard_batch_tree(strategy: ParallelPlan, tree):
    """:func:`shard_batch` over an arbitrary pytree of batch arrays — the
    dict inputs / list labels / optional-mask dicts of a ComputationGraph
    batch. ``None`` leaves (absent masks) pass through unsharded."""
    return jax.tree_util.tree_map(
        lambda a: None if a is None else shard_batch(strategy, a),
        tree, is_leaf=lambda x: x is None)
