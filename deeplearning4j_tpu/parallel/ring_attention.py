"""Ring attention: sequence/context parallelism over the ICI ring.

The reference has NO long-context mechanism beyond truncated BPTT (SURVEY.md
§5.7) — this is the TPU-first capability that replaces it. Sequences are
sharded over the ``seq`` mesh axis; each device holds its query block and the
key/value blocks rotate around the ring via ``jax.lax.ppermute`` while a
flash-attention-style running softmax (running max + denominator) accumulates
the output. Communication overlaps compute and total memory per device is
O(T/n), so context length scales linearly with the ring size.

Public API:
- :func:`ring_attention` — inside-shard_map building block (needs axis_name)
- :func:`sequence_parallel_attention` — convenience wrapper that shard_maps
  over a mesh's ``seq`` axis.
"""

from __future__ import annotations

import functools
import inspect
from typing import Optional

import jax
import jax.numpy as jnp
try:
    from jax import shard_map  # stable location (jax >= 0.7)
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map (>=0.7) spells the replication check check_vma; the
# experimental one spelled it check_rep. Resolved once here — a per-call
# try/except TypeError would also swallow genuine construction errors.
_SHARD_MAP_CHECK_KW = ("check_vma" if "check_vma"
                       in inspect.signature(shard_map).parameters
                       else "check_rep")

from deeplearning4j_tpu.runtime.mesh import SEQ_AXIS


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   causal: bool = False) -> jax.Array:
    """Blockwise ring attention for one sequence shard.

    Args:
      q, k, v: (batch, heads, t_local, d) — the local sequence block; the
        full sequence is ``t_local * axis_size`` long.
      axis_name: mesh axis the sequence is sharded over.
      causal: apply a causal mask using global positions.

    Returns: (batch, heads, t_local, d) attention output for local queries.
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[2]
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    q32 = q.astype(jnp.float32)
    o = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m = jnp.full(q.shape[:3], -jnp.inf, jnp.float32)  # running max
    l = jnp.zeros(q.shape[:3], jnp.float32)  # running denominator

    q_pos = my_idx * t_local + jnp.arange(t_local)

    def body(step, carry):
        o, m, l, k_blk, v_blk = carry
        # which device's block are we holding? blocks travel "up" the ring
        src = jnp.mod(my_idx - step, n)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            cmask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(cmask[None, None], scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # guard fully-masked rows (new_m == -inf)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        # rotate k/v one step around the ring (overlapped with next compute)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o_new, new_m, l_new, k_nxt, v_nxt)

    o, m, l, _, _ = jax.lax.fori_loop(0, n, body, (o, m, l, k, v))
    out = o / jnp.maximum(l[..., None], 1e-20)
    return out.astype(q.dtype)


def sequence_parallel_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                                mesh: Mesh, causal: bool = False,
                                seq_axis: str = SEQ_AXIS) -> jax.Array:
    """shard_map wrapper: q/k/v are GLOBAL (batch, heads, T, d) arrays; the
    time axis is sharded over ``seq_axis`` and ring attention runs per shard."""
    spec = P(None, None, seq_axis, None)

    body = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, **{_SHARD_MAP_CHECK_KW: False})
    return fn(q, k, v)
