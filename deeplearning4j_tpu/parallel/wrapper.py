"""ParallelWrapper: multi-device training driver.

Rebuild of upstream ``org.deeplearning4j.parallelism.ParallelWrapper`` — but
where the reference spawns one trainer thread per GPU and averages params (or
exchanges threshold-encoded gradients through host-side accumulators), here
the wrapped network's OWN jitted train step runs SPMD over the mesh: the
batch is sharded on the ``data`` axis, params follow the
:class:`ShardingStrategy` (replicated for DP, sharded for FSDP/TP), and XLA
emits the gradient allreduce over ICI. There are no trainer threads, no
averaging frequency, no encoded updates — one compiled program IS the
distributed trainer, and it is mathematically equivalent to synchronous
all-reduce SGD (averaging every iteration).

Multi-node: run the same script per host after
``runtime.mesh.initialize_multihost()`` — the mesh then spans hosts and the
same step runs globally (the reference needed Spark + Aeron for this).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.parallel.sharding import ShardingStrategy, shard_batch, shard_train_state
from deeplearning4j_tpu.runtime.mesh import DATA_AXIS, MODEL_AXIS, create_mesh
from deeplearning4j_tpu.train.listeners import PerformanceListener


class ParallelWrapper:
    """Usage (mirrors the reference's builder)::

        pw = (ParallelWrapper.builder(net)
              .workers(8)                      # optional; defaults to all devices
              .strategy("data_parallel")       # or "fsdp" / "tensor_parallel"
              .build())
        pw.fit(iterator, epochs=2)
    """

    def __init__(self, model, strategy: Optional[ShardingStrategy] = None):
        self.model = model
        if strategy is None:
            strategy = ShardingStrategy.data_parallel(create_mesh())
        self.strategy = strategy
        self._sharded = False

    # -- builder API (reference parity) --
    class Builder:
        def __init__(self, model):
            self._model = model
            self._workers = None
            self._strategy_name = "data_parallel"

        def workers(self, n: int) -> "ParallelWrapper.Builder":
            self._workers = int(n)
            return self

        def strategy(self, name: str) -> "ParallelWrapper.Builder":
            self._strategy_name = name
            return self

        # reference knobs that are no-ops under sync-SPMD (documented parity):
        def averaging_frequency(self, n: int) -> "ParallelWrapper.Builder":
            return self  # sync allreduce == averaging every iteration

        def prefetch_buffer(self, n: int) -> "ParallelWrapper.Builder":
            return self

        def build(self) -> "ParallelWrapper":
            devs = jax.devices()
            if self._workers:
                devs = devs[: self._workers]
            if self._strategy_name == "tensor_parallel":
                # TP needs a `model` mesh axis; default to all devices on
                # it (Megatron single-node style). Build an explicit
                # data x model ShardingStrategy for hybrid DPxTP.
                mesh = create_mesh({DATA_AXIS: 1, MODEL_AXIS: -1},
                                   devices_=devs)
            else:
                mesh = create_mesh(devices_=devs)
            factory = {
                "data_parallel": ShardingStrategy.data_parallel,
                "fsdp": ShardingStrategy.fsdp,
                "tensor_parallel": ShardingStrategy.tensor_parallel,
            }[self._strategy_name]
            return ParallelWrapper(self._model, factory(mesh))

    @staticmethod
    def builder(model) -> "ParallelWrapper.Builder":
        return ParallelWrapper.Builder(model)

    # -- training --
    def _check_supported(self):
        """ParallelWrapper drives the model's PLAIN jitted SGD step; modes
        the model's own fit() special-cases (tBPTT chunking, legacy
        solvers) would silently train with different gradients here — so
        refuse loudly instead. tBPTT is checked per-batch (the models'
        own fit engages it only for sequence batches)."""
        conf = getattr(self.model, "conf", None)
        gc = getattr(conf, "global_conf", None)
        algo = getattr(gc, "optimization_algo",
                       "STOCHASTIC_GRADIENT_DESCENT") or \
            "STOCHASTIC_GRADIENT_DESCENT"
        if algo != "STOCHASTIC_GRADIENT_DESCENT":
            raise NotImplementedError(
                f"ParallelWrapper supports optimization_algo=SGD only "
                f"(got {algo!r}); legacy solvers run single-context via "
                "the model's own fit()")

    def _check_not_tbptt(self, x):
        from deeplearning4j_tpu.models._tbptt import is_sequence_array
        if getattr(getattr(self.model, "conf", None),
                   "tbptt_fwd_length", None) and is_sequence_array(x):
            raise NotImplementedError(
                "tBPTT training under ParallelWrapper is not supported — "
                "the wrapper would run full-sequence BPTT instead of the "
                "model's tBPTT chunking; use the model's own fit(), or "
                "full-sequence BPTT (unset tbptt_fwd_length) to train "
                "sharded")

    def _ensure_sharded(self):
        self._check_supported()
        if self.model.train_state is None:
            self.model.init()
        if not self._sharded:
            self.model.train_state = shard_train_state(self.model.train_state, self.strategy)
            self._sharded = True

    def _run_step(self, step_fn, batch):
        """One sharded train step, dispatching on the wrapped model's step
        signature: MultiLayerNetwork takes (ts, x, y, rng, fmask, lmask);
        ComputationGraph takes (ts, inputs_dict, labels_list, rng, masks)
        — both are wrapped by the reference ParallelWrapper too."""
        model = self.model
        rng = model.rng.next_key()
        if hasattr(model, "_coerce_batch"):  # ComputationGraph
            inputs, labels_, masks = model._coerce_batch(batch)
            for v in inputs.values():
                self._check_not_tbptt(v)
            inputs = {k: shard_batch(self.strategy, v)
                      for k, v in inputs.items()}
            labels_ = [shard_batch(self.strategy, l) for l in labels_]
            if masks is not None:
                masks = {k: (None if m is None
                             else shard_batch(self.strategy, m))
                         for k, m in masks.items()}
            model.train_state, loss = step_fn(
                model.train_state, inputs, labels_, rng, masks)
            n = next(iter(inputs.values())).shape[0]
            return loss, n
        x = jnp.asarray(batch.features)
        y = jnp.asarray(batch.labels)
        self._check_not_tbptt(x)
        fm = None if batch.features_mask is None else jnp.asarray(batch.features_mask)
        # labels mask defaults for per-timestep labels via the model's own
        # output-time alignment (a time-axis-changing layer makes the raw
        # features mask the WRONG length for the loss)
        lm = jnp.asarray(batch.labels_mask) if batch.labels_mask is not None \
            else (model._output_time_mask(fm) if y.ndim == 3 else None)
        x, y, fm, lm = shard_batch(self.strategy, x, y, fm, lm)
        model.train_state, loss = step_fn(model.train_state, x, y, rng, fm, lm)
        return loss, x.shape[0]

    def fit(self, iterator, epochs: int = 1):
        """Distributed fit: same listener/epoch semantics as the wrapped
        model's own ``fit``, with batches sharded across the mesh."""
        self._ensure_sharded()
        model = self.model
        step_fn = model._jitted("train_step", model._make_train_step)
        with self.strategy.mesh:
            for _ in range(int(epochs)):
                for lst in model._listeners:
                    lst.on_epoch_start(model, model._epoch)
                iterator.reset()
                for batch in iterator:
                    loss, n = self._run_step(step_fn, batch)
                    model._score = loss
                    model._iteration += 1
                    for lst in model._listeners:
                        if isinstance(lst, PerformanceListener):
                            lst.record_batch(n)
                        lst.iteration_done(model, model._iteration, model._epoch, loss)
                for lst in model._listeners:
                    lst.on_epoch_end(model, model._epoch)
                model._epoch += 1
        return model
