"""ParallelWrapper: multi-device training driver.

Rebuild of upstream ``org.deeplearning4j.parallelism.ParallelWrapper`` — but
where the reference spawns one trainer thread per GPU and averages params (or
exchanges threshold-encoded gradients through host-side accumulators), here
the wrapped network's OWN jitted train step runs SPMD over the mesh: the
batch is sharded on the ``data`` axis, params follow the
:class:`ShardingStrategy` (replicated for DP, sharded for FSDP/TP), and XLA
emits the gradient allreduce over ICI. There are no trainer threads, no
averaging frequency, no encoded updates — one compiled program IS the
distributed trainer, and it is mathematically equivalent to synchronous
all-reduce SGD (averaging every iteration).

The FEED path is a staged pipeline (ISSUE 4, mirroring the serving
executor): a :class:`~deeplearning4j_tpu.train.prefetch.DevicePrefetcher`
coerces batches and issues the sharded ``jax.device_put`` up to
``prefetch_buffer`` batches ahead of the running step (the reference's
``prefetchBuffer`` workspace ring, TPU-native), dispatch is unified onto
``GroupedDispatch`` (honoring ``env.dispatch_unroll`` with an unrolled
sharded step), and listener delivery rides the async completion path so a
listener reading ``float(loss)`` never stalls dispatch. Trajectories are
bit-identical to the synchronous loop — same batch order, same rng-key
sequence, same compiled step.

Multi-node: run the same script per host after
``runtime.mesh.initialize_multihost()`` — the mesh then spans hosts and the
same step runs globally (the reference needed Spark + Aeron for this).
"""

from __future__ import annotations

from typing import Optional

import jax

from deeplearning4j_tpu.parallel.sharding import (ShardingStrategy, shard_batch,
                                                  shard_batch_tree,
                                                  shard_train_state)
from deeplearning4j_tpu.runtime.environment import get_environment
from deeplearning4j_tpu.runtime.mesh import DATA_AXIS, MODEL_AXIS, create_mesh
from deeplearning4j_tpu.train.listeners import PerformanceListener


class ParallelWrapper:
    """Usage (mirrors the reference's builder)::

        pw = (ParallelWrapper.builder(net)
              .workers(8)                      # optional; defaults to all devices
              .strategy("data_parallel")       # or "fsdp" / "tensor_parallel"
              .prefetch_buffer(2)              # sharded device prefetch depth
              .build())
        pw.fit(iterator, epochs=2)
    """

    def __init__(self, model, strategy: Optional[ShardingStrategy] = None,
                 prefetch_buffer: int = 2):
        self.model = model
        if strategy is None:
            strategy = ShardingStrategy.data_parallel(create_mesh())
        self.strategy = strategy
        # batches staged on-device ahead of the step (reference default 2);
        # 0 = fully synchronous feed path (bit-identical either way)
        self.prefetch_buffer = max(0, int(prefetch_buffer))
        self._sharded = False

    # -- builder API (reference parity) --
    class Builder:
        def __init__(self, model):
            self._model = model
            self._workers = None
            self._strategy_name = "data_parallel"
            self._prefetch_buffer = 2

        def workers(self, n: int) -> "ParallelWrapper.Builder":
            self._workers = int(n)
            return self

        def strategy(self, name: str) -> "ParallelWrapper.Builder":
            self._strategy_name = name
            return self

        # reference knobs that are no-ops under sync-SPMD (documented parity):
        def averaging_frequency(self, n: int) -> "ParallelWrapper.Builder":
            return self  # sync allreduce == averaging every iteration

        def prefetch_buffer(self, n: int) -> "ParallelWrapper.Builder":
            """Sharded device-prefetch depth (reference ``prefetchBuffer``);
            0 disables the background stage."""
            self._prefetch_buffer = max(0, int(n))
            return self

        def build(self) -> "ParallelWrapper":
            devs = jax.devices()
            if self._workers:
                devs = devs[: self._workers]
            if self._strategy_name == "tensor_parallel":
                # TP needs a `model` mesh axis; default to all devices on
                # it (Megatron single-node style). Build an explicit
                # data x model ShardingStrategy for hybrid DPxTP.
                mesh = create_mesh({DATA_AXIS: 1, MODEL_AXIS: -1},
                                   devices_=devs)
            else:
                mesh = create_mesh(devices_=devs)
            factory = {
                "data_parallel": ShardingStrategy.data_parallel,
                "fsdp": ShardingStrategy.fsdp,
                "tensor_parallel": ShardingStrategy.tensor_parallel,
            }[self._strategy_name]
            return ParallelWrapper(self._model, factory(mesh),
                                   prefetch_buffer=self._prefetch_buffer)

    @staticmethod
    def builder(model) -> "ParallelWrapper.Builder":
        return ParallelWrapper.Builder(model)

    # -- training --
    def _check_supported(self):
        """ParallelWrapper drives the model's PLAIN jitted SGD step; modes
        the model's own fit() special-cases (tBPTT chunking, legacy
        solvers) would silently train with different gradients here — so
        refuse loudly instead. tBPTT is checked per-batch (the models'
        own fit engages it only for sequence batches)."""
        conf = getattr(self.model, "conf", None)
        gc = getattr(conf, "global_conf", None)
        algo = getattr(gc, "optimization_algo",
                       "STOCHASTIC_GRADIENT_DESCENT") or \
            "STOCHASTIC_GRADIENT_DESCENT"
        if algo != "STOCHASTIC_GRADIENT_DESCENT":
            raise NotImplementedError(
                f"ParallelWrapper supports optimization_algo=SGD only "
                f"(got {algo!r}); legacy solvers run single-context via "
                "the model's own fit()")

    def _check_not_tbptt(self, x):
        from deeplearning4j_tpu.models._tbptt import is_sequence_array
        if getattr(getattr(self.model, "conf", None),
                   "tbptt_fwd_length", None) and is_sequence_array(x):
            raise NotImplementedError(
                "tBPTT training under ParallelWrapper is not supported — "
                "the wrapper would run full-sequence BPTT instead of the "
                "model's tBPTT chunking; use the model's own fit(), or "
                "full-sequence BPTT (unset tbptt_fwd_length) to train "
                "sharded")

    def _ensure_sharded(self):
        self._check_supported()
        if self.model.train_state is None:
            self.model.init()
        if not self._sharded:
            self.model.train_state = shard_train_state(self.model.train_state, self.strategy)
            self._sharded = True

    def _prepare_batch(self, batch):
        """Host→device for one batch: coercion (shared helper), tBPTT
        guard, then the sharded ``jax.device_put`` with the strategy's
        ``NamedSharding``s. Pure with respect to model state, so the
        prefetch worker runs it ahead of the current step. Returns
        ``(step_args_without_rng, n_examples)`` — MultiLayerNetwork steps
        take (ts, x, y, rng, fmask, lmask); ComputationGraph takes
        (ts, inputs_dict, labels_list, rng, masks)."""
        from deeplearning4j_tpu.train.prefetch import coerce_training_batch
        model = self.model
        if hasattr(model, "_coerce_batch"):  # ComputationGraph
            inputs, labels_, masks = model._coerce_batch(batch)
            for v in inputs.values():
                self._check_not_tbptt(v)
            inputs = shard_batch_tree(self.strategy, inputs)
            labels_ = shard_batch_tree(self.strategy, labels_)
            masks = None if masks is None else shard_batch_tree(
                self.strategy, masks)
            n = next(iter(inputs.values())).shape[0]
            return (inputs, labels_, masks), n
        x, y, fm, lm = coerce_training_batch(model, batch)
        self._check_not_tbptt(x)
        x, y, fm, lm = shard_batch(self.strategy, x, y, fm, lm)
        return (x, y, fm, lm), x.shape[0]

    def _insert_rng(self, args):
        """Step args with the NEXT rng key spliced in at dispatch time —
        key order (and so the trajectory) follows submission order, never
        prefetch completion order."""
        rng = self.model.rng.next_key()
        if hasattr(self.model, "_coerce_batch"):  # (inputs, labels, rng, masks)
            return (args[0], args[1], rng, args[2])
        return (args[0], args[1], rng, args[2], args[3])

    def _run_group(self, step_fn_unused, group):
        """K compatible buffered steps as ONE device dispatch
        (``env.dispatch_unroll``) — the sharded counterpart of the fit
        loops' packed grouped dispatch (sharded state cannot pack, see
        ``runtime/state_packing.py``)."""
        from deeplearning4j_tpu.runtime.state_packing import (
            make_unrolled_step, step_args_signature)
        model = self.model
        k = len(group)
        fn = model._jitted(
            f"pw_unrolled@k={k}",
            lambda: make_unrolled_step(model._train_step_fn(), k))
        model.train_state, losses = self._aot().call(
            ("pw-group", self.strategy.signature(), k,
             step_args_signature(group[0][0])),
            fn, model.train_state, [args for args, _n in group])
        return [losses[i] for i in range(k)]

    def _aot(self):
        """The sharded-dispatch AOT executable cache, stored in the model's
        jit cache so ``init()`` invalidation covers it. Lowering captures
        the committed NamedShardings, so a (graph, shape, mesh) signature
        maps to exactly one executable."""
        from deeplearning4j_tpu.runtime.compile_cache import AotCache
        return self.model._jit_cache.setdefault(
            "__aot_pw__", AotCache("pw-step"))

    def _fit_pipe(self, iterator, epochs: int, profiler=None):
        """Pipe-axis fit: the model's uniform trunk is stage-stacked and
        streamed through the GPipe shift register (``plan_exec``); each pipe
        device holds 1/S of the trunk, the ``data`` axis (if present) shards
        the batch. Same listener/epoch semantics as the SPMD path; the
        trained params are written back to ``model.train_state``."""
        from deeplearning4j_tpu.parallel.plan_exec import PipePlanExecutor
        from deeplearning4j_tpu.runtime.state_packing import (
            step_args_signature)
        from deeplearning4j_tpu.train.prefetch import batch_source
        self._check_supported()
        model = self.model
        if hasattr(model, "_coerce_batch"):
            raise NotImplementedError(
                "pipe-axis plans drive MultiLayerNetwork layer stacks; "
                "ComputationGraph topologies have no linear trunk to stage")
        if model.train_state is None:
            model.init()
        if getattr(self, "_pipe_exec", None) is None:
            self._pipe_exec = PipePlanExecutor(model, self.strategy)
        ex = self._pipe_exec
        packed_ts, tx = ex.packed_state()
        step_fn = jax.jit(ex.make_train_step(tx), donate_argnums=(0,))
        aot = self._aot()
        plan_sig = self.strategy.signature()
        if profiler is not None:
            profiler.start()

        try:
            with self.strategy.mesh:
                for _ in range(int(epochs)):
                    for lst in model._listeners:
                        lst.on_epoch_start(model, model._epoch)
                    src = batch_source(iterator, self._prepare_batch,
                                       self.prefetch_buffer, profiler)
                    try:
                        for args, n in src:
                            args = self._insert_rng(args)
                            if args[3] is not None:
                                raise NotImplementedError(
                                    "feature masks are not supported under "
                                    "pipe-axis plans")
                            packed_ts, loss = aot.call(
                                ("pw-pipe", plan_sig,
                                 step_args_signature(args)),
                                step_fn, packed_ts, *args)
                            model._score = loss
                            model._iteration += 1
                            for lst in model._listeners:
                                if isinstance(lst, PerformanceListener):
                                    lst.record_batch(n)
                                lst.iteration_done(model, model._iteration,
                                                   model._epoch, loss)
                    finally:
                        src.close()
                    for lst in model._listeners:
                        lst.on_epoch_end(model, model._epoch)
                    model._epoch += 1
        finally:
            if profiler is not None:
                profiler.stop()
        ex.sync_back(packed_ts)
        return model

    def fit(self, iterator, epochs: int = 1, profiler=None):
        """Distributed fit: same listener/epoch semantics (and bit-identical
        trajectory) as the wrapped model's own ``fit``, with batches sharded
        across the mesh, prefetched ``prefetch_buffer`` deep, and losses
        delivered on the async completion path. ``profiler`` takes a
        :class:`~deeplearning4j_tpu.train.profiler.TrainingProfiler`.

        Plans with a ``pipe`` axis route through the GPipe executor
        (:meth:`_fit_pipe`) — same call, pipelined execution."""
        if self.strategy.pipe_size > 1:
            return self._fit_pipe(iterator, epochs, profiler)
        from deeplearning4j_tpu.runtime.state_packing import GroupedDispatch
        from deeplearning4j_tpu.train.prefetch import (AsyncLossDelivery,
                                                       batch_source,
                                                       stateless_listeners)
        from deeplearning4j_tpu.train.profiler import submit_timed
        self._ensure_sharded()
        model = self.model
        step_fn = model._jitted("train_step", model._make_train_step)
        if hasattr(model, "_coerce_batch"):
            from deeplearning4j_tpu.models.computation_graph import (
                _cg_group_compatible as base_compat)
        else:
            from deeplearning4j_tpu.models.multi_layer_network import (
                _group_compatible as base_compat)
        stateless = stateless_listeners(model)
        if profiler is not None:
            profiler.start()

        from deeplearning4j_tpu.runtime.state_packing import (
            step_args_signature)
        aot = self._aot()

        def run_single(item):
            args, _n = item
            # the plan signature joins the key: plan drift (axis added or
            # resized, schedule knob changed) misses the cache and
            # recompiles — never a stale executable for the wrong mesh
            out = aot.call(("pw", self.strategy.signature(),
                            step_args_signature(args)),
                           step_fn, model.train_state, *args)
            model.train_state, loss = out
            return loss

        def deliver(n, loss):
            model._score = loss
            model._iteration += 1
            for lst in model._listeners:
                if isinstance(lst, PerformanceListener):
                    lst.record_batch(n)
                lst.iteration_done(model, model._iteration, model._epoch, loss)

        # async loss readback (see MultiLayerNetwork._fit_epochs): a
        # state-reading listener forces synchronous one-at-a-time delivery;
        # no listeners and no profiler = deliver inline, no thread
        adel = (AsyncLossDelivery(deliver, profiler=profiler)
                if (model._listeners or profiler is not None)
                and stateless else None)
        # only the batch SIZE crosses into the delivery queue — queued step
        # args would pin full sharded batches for up to max_pending steps
        sink = adel.submit if adel is not None else deliver
        gd = GroupedDispatch(
            unroll=(get_environment().dispatch_unroll if stateless else 1),
            compatible=lambda a, b: base_compat(a[0], b[0]),
            run_single=run_single,
            run_group=lambda group: self._run_group(step_fn, group),
            deliver=lambda item, loss: sink(item[1], loss))
        drain = adel.flush if adel is not None else (lambda: None)
        try:
            with self.strategy.mesh:
                for _ in range(int(epochs)):
                    for lst in model._listeners:
                        lst.on_epoch_start(model, model._epoch)
                    src = batch_source(iterator, self._prepare_batch,
                                       self.prefetch_buffer, profiler)
                    try:
                        for args, n in src:
                            submit_timed(gd, (self._insert_rng(args), n),
                                         profiler)
                    finally:
                        src.close()
                    gd.flush()
                    drain()  # on_epoch_end must observe every iteration
                    for lst in model._listeners:
                        lst.on_epoch_end(model, model._epoch)
                    model._epoch += 1
        finally:
            gd.drain_on_error()
            if adel is not None:
                adel.shutdown()  # never raises; original errors win
            if profiler is not None:
                profiler.stop()
        if adel is not None:
            adel.raise_pending()
        return model
