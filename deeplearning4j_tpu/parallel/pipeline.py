"""GPipe-style pipeline parallelism over the ``pipe`` mesh axis.

The reference has NO pipeline parallelism (SURVEY.md §2.3) — parity-plus.
This is the scaling-book shift-register formulation: each pipe-axis device
holds ONE stage's params (leading stage dim sharded by shard_map), and a
``lax.fori_loop`` of ``n_microbatches + n_stages - 1`` ticks streams
microbatches through, passing activations to the next stage with a single
``ppermute`` per tick — all inside one compiled program, collectives on ICI.

Constraint of this formulation: stages must be shape-preserving
(transformer-block-like); the in/out activation shape is the microbatch
shape. Wrap unequal-width networks so the pipelined segment is the uniform
trunk.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map  # stable location (jax >= 0.7)
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.runtime.mesh import PIPE_AXIS


def stack_stage_params(per_stage_params) -> Any:
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array],
          stacked_params: Any,
          x: jax.Array,
          *,
          mesh: Mesh,
          n_microbatches: int,
          axis_name: str = PIPE_AXIS,
          batch_axes: Optional[tuple] = None) -> jax.Array:
    """Run ``x`` through ``n_stages`` sequential applications of ``stage_fn``,
    pipelined over the mesh's ``axis_name`` dimension.

    stage_fn(params_for_one_stage, microbatch) -> microbatch (same shape).
    stacked_params: every leaf has leading dim n_stages (see
    :func:`stack_stage_params`).
    batch_axes: mesh axes the per-microbatch batch dim additionally shards
    over (a composed pipe x data plan) — each data-coordinate runs the same
    shift-register schedule on its batch slice, so per-row math (and bits)
    are unchanged by the data fan-out.
    """
    S = mesh.shape[axis_name]
    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_stages != S:
        # shard_map would hand each device a multi-stage slice and the [0]
        # squeeze would silently drop stages — reject loudly instead
        raise ValueError(f"{n_stages} stages require a {axis_name}-axis of the "
                         f"same size, mesh has {S}")
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by {n_microbatches} microbatches")
    mb = B // n_microbatches
    mbs = x.reshape((n_microbatches, mb) + x.shape[1:])
    M, T = n_microbatches, n_microbatches + S - 1

    def per_device(params, mbs_local):
        # shard_map gives each device a (1, ...) slice of the stage dim
        params = jax.tree.map(lambda a: a[0], params)
        idx = lax.axis_index(axis_name)
        shift_perm = [(d, d + 1) for d in range(S - 1)]

        def body(t, carry):
            buf, outputs = carry
            # stage 0 ingests microbatch t (clamped; garbage ticks discarded)
            feed = mbs_local[jnp.minimum(t, M - 1)]
            inp = jnp.where(idx == 0, feed, buf)
            out = stage_fn(params, inp)
            # last stage emits microbatch j = t - (S-1)
            j = t - (S - 1)
            upd = lax.dynamic_update_index_in_dim(
                outputs, out, jnp.maximum(j, 0), axis=0)
            outputs = jnp.where((idx == S - 1) & (j >= 0), upd, outputs)
            buf = lax.ppermute(out, axis_name, shift_perm)
            return buf, outputs

        buf0 = jnp.zeros_like(mbs_local[0])
        out0 = jnp.zeros_like(mbs_local)
        _, outputs = lax.fori_loop(0, T, body, (buf0, out0))
        # only the last device holds real outputs; share them
        return lax.psum(jnp.where(idx == S - 1, outputs, 0.0), axis_name)

    spec_params = jax.tree.map(lambda _: P(axis_name), stacked_params)
    spec_mbs = P(None, tuple(batch_axes)) if batch_axes else P()
    # jax.shard_map (>=0.7) spells the replication check check_vma; the
    # experimental one spelled it check_rep
    try:
        fn = shard_map(per_device, mesh=mesh,
                       in_specs=(spec_params, spec_mbs), out_specs=spec_mbs,
                       check_vma=False)
    except TypeError:
        fn = shard_map(per_device, mesh=mesh,
                       in_specs=(spec_params, spec_mbs), out_specs=spec_mbs,
                       check_rep=False)
    out = fn(stacked_params, mbs)
    return out.reshape((B,) + out.shape[2:])


def sequential_reference(stage_fn, stacked_params, x):
    """Unpipelined oracle: apply the stages one after another (for tests and
    single-device fallback)."""
    S = jax.tree.leaves(stacked_params)[0].shape[0]
    for s in range(S):
        params_s = jax.tree.map(lambda a: a[s], stacked_params)
        x = stage_fn(params_s, x)
    return x
