"""Parallel / distributed training and serving.

TPU-native replacement for the reference's entire scale-out stack (SURVEY.md
§2.3–2.4): ``ParallelWrapper`` (single-node multi-device DP),
``ParallelInference`` (multi-replica serving), Spark
``ParameterAveragingTrainingMaster`` / ``SharedTrainingMaster`` + the Aeron
``VoidParameterServer`` mesh (multi-node DP with threshold-encoded gradient
compression).

Inference serving: ``ParallelInference`` here is the reference-shaped API
over :mod:`deeplearning4j_tpu.serving`'s shape-bucketed continuous batcher;
the production surface (model registry, admission control, HTTP front end,
SLO metrics) lives in that package.

Design (SURVEY.md §7.1): parallelism is *sharding*, not frameworks. One SPMD
train step over a ``jax.sharding.Mesh``; XLA inserts fused allreduces over
ICI/DCN. The reference's four DP flavors collapse into one mechanism — and
tensor/FSDP/sequence parallelism, which the reference lacks entirely, come
from the same mechanism with different PartitionSpecs (see
``docs/parity.md``). Gradient compression (threshold encoding) is an explicit
non-goal on ICI-class interconnects.
"""

from deeplearning4j_tpu.parallel.sharding import (
    ParallelPlan,
    ShardingStrategy,
    shard_batch,
    shard_train_state,
)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.ring_attention import (
    ring_attention,
    sequence_parallel_attention,
)
from deeplearning4j_tpu.parallel.pipeline import (
    gpipe,
    sequential_reference,
    stack_stage_params,
)
from deeplearning4j_tpu.parallel.plan_exec import PipePlanExecutor

__all__ = [
    "ParallelPlan",
    "ShardingStrategy",
    "shard_batch",
    "shard_train_state",
    "ParallelWrapper",
    "ParallelInference",
    "ring_attention",
    "sequence_parallel_attention",
    "gpipe",
    "stack_stage_params",
    "sequential_reference",
    "PipePlanExecutor",
]
