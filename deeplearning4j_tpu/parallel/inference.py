"""ParallelInference: batched multi-device serving.

Rebuild of upstream ``org.deeplearning4j.parallelism.ParallelInference``:
the reference keeps N model replicas with worker threads and a dynamic
batching observable (``BatchedInferenceObservable``). Here a single jitted
forward runs SPMD over the mesh (replicated params, batch-sharded inputs),
and the dynamic batcher is a host-side queue that coalesces concurrent
``output()`` calls up to ``max_batch_size`` — same latency/throughput trade,
one compiled program instead of N replicas.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.sharding import ShardingStrategy
from deeplearning4j_tpu.runtime.mesh import create_mesh


class _Request:
    def __init__(self, x: np.ndarray):
        self.x = x
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class ParallelInference:
    """Usage::

        pi = ParallelInference(net, max_batch_size=64)
        y = pi.output(x)          # thread-safe; concurrent calls are batched
        pi.shutdown()
    """

    def __init__(self, model, strategy: Optional[ShardingStrategy] = None,
                 max_batch_size: int = 32, queue_limit: int = 256,
                 batch_timeout_ms: float = 2.0):
        self.model = model
        if model.train_state is None:
            model.init()
        self.strategy = strategy or ShardingStrategy.data_parallel(create_mesh())
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout_s = batch_timeout_ms / 1000.0
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._shutdown = False
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    class Builder:
        """Reference ``ParallelInference.Builder`` surface."""

        def __init__(self, model):
            self._model = model
            self._kw = {}

        def max_batch_size(self, n: int):
            self._kw["max_batch_size"] = int(n)
            return self

        def batch_timeout_ms(self, ms: float):
            self._kw["batch_timeout_ms"] = float(ms)
            return self

        def queue_limit(self, n: int):
            self._kw["queue_limit"] = int(n)
            return self

        def inference_mode(self, mode: str):
            mode = str(mode).lower()
            if mode not in ("batched", "sequential"):
                raise ValueError(f"unknown inference mode {mode!r}; "
                                 f"'BATCHED' or 'SEQUENTIAL'")
            self._mode = mode
            return self

        def build(self) -> "ParallelInference":
            # resolve the mode LAST so call order doesn't matter:
            # SEQUENTIAL == batch size 1 regardless of max_batch_size()
            kw = dict(self._kw)
            if getattr(self, "_mode", "batched") == "sequential":
                kw["max_batch_size"] = 1
            return ParallelInference(self._model, **kw)

    @staticmethod
    def builder(model) -> "ParallelInference.Builder":
        return ParallelInference.Builder(model)

    def output(self, x) -> np.ndarray:
        """Blocking inference; safe from many threads at once."""
        req = _Request(np.asarray(x))
        self._queue.put(req)
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def _run(self):
        while not self._shutdown:
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch: List[_Request] = [first]
            total = first.x.shape[0]
            # dynamic batching: coalesce whatever arrives within the window
            deadline = self.batch_timeout_s
            while total < self.max_batch_size:
                try:
                    nxt = self._queue.get(timeout=deadline)
                except queue.Empty:
                    break
                batch.append(nxt)
                total += nxt.x.shape[0]
            try:
                x = np.concatenate([r.x for r in batch], axis=0)
                out = np.asarray(self.model.output(x))
                ofs = 0
                for r in batch:
                    n = r.x.shape[0]
                    r.result = out[ofs:ofs + n]
                    ofs += n
            except BaseException as e:
                for r in batch:
                    r.error = e
            finally:
                for r in batch:
                    r.event.set()

    def shutdown(self):
        self._shutdown = True
        self._worker.join(timeout=1.0)
