"""ParallelInference: batched multi-device serving.

Rebuild of upstream ``org.deeplearning4j.parallelism.ParallelInference``:
the reference keeps N model replicas with worker threads and a dynamic
batching observable (``BatchedInferenceObservable``). Here the dynamic
batcher is :class:`~deeplearning4j_tpu.serving.batcher.ContinuousBatcher`
— ``ParallelInference`` is its single-model case, kept as the
reference-shaped API (``Builder``, ``output()``, ``shutdown()``) — and
``Builder.workers(n)`` means what it means upstream: N *real* model
replicas, here as device-resident parameter copies served least-loaded by
the batcher's :class:`~deeplearning4j_tpu.serving.replica.ReplicaPool`
(ISSUE 3). The full serving subsystem (registry, admission control, HTTP
front end, SLO metrics) lives in :mod:`deeplearning4j_tpu.serving`.

Semantics inherited from the shared batcher (fixes two seed bugs):

- the coalesce window is one deadline for the whole batch (the seed passed
  the full ``batch_timeout_s`` to every ``queue.get``, so worst-case added
  latency was ``max_batch_size x timeout``);
- ``shutdown()`` drains queued-but-unbatched requests and fails them with
  an explicit error instead of leaving concurrent ``output()`` callers
  blocked forever;
- multi-input ``ComputationGraph`` batches work (``output({"a": xa, ...})``
  concatenates per input name — the seed's bare ``np.concatenate(r.x)``
  only handled single-array MLN inputs).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.parallel.sharding import ShardingStrategy
from deeplearning4j_tpu.serving.batcher import ContinuousBatcher


class ParallelInference:
    """Usage::

        pi = ParallelInference(net, max_batch_size=64)
        y = pi.output(x)          # thread-safe; concurrent calls are batched
        pi.shutdown()
    """

    def __init__(self, model, strategy: Optional[ShardingStrategy] = None,
                 max_batch_size: int = 32, queue_limit: int = 256,
                 batch_timeout_ms: float = 2.0, workers: int = 1,
                 pipeline_depth: int = 2):
        self.model = model
        self.strategy = strategy  # kept for API parity; forward is one jit
        self.max_batch_size = int(max_batch_size)
        self._batcher = ContinuousBatcher(
            model, max_batch_size=max_batch_size, queue_limit=queue_limit,
            batch_timeout_ms=batch_timeout_ms, replicas=workers,
            pipeline_depth=pipeline_depth)

    @property
    def workers(self) -> int:
        """Actual replica count (requested workers clamped to the local
        device count)."""
        return self._batcher.replica_count

    class Builder:
        """Reference ``ParallelInference.Builder`` surface."""

        def __init__(self, model):
            self._model = model
            self._kw = {}

        def max_batch_size(self, n: int):
            self._kw["max_batch_size"] = int(n)
            return self

        def batch_timeout_ms(self, ms: float):
            self._kw["batch_timeout_ms"] = float(ms)
            return self

        def queue_limit(self, n: int):
            self._kw["queue_limit"] = int(n)
            return self

        def workers(self, n: int):
            """Reference ``workers(n)``: N device replicas of the model,
            routed least-loaded (clamped to the local device count)."""
            self._kw["workers"] = int(n)
            return self

        def pipeline_depth(self, n: int):
            """Batches allowed in flight between dispatch and readback
            (0 = synchronous)."""
            self._kw["pipeline_depth"] = int(n)
            return self

        def inference_mode(self, mode: str):
            mode = str(mode).lower()
            if mode not in ("batched", "sequential"):
                raise ValueError(f"unknown inference mode {mode!r}; "
                                 f"'BATCHED' or 'SEQUENTIAL'")
            self._mode = mode
            return self

        def build(self) -> "ParallelInference":
            # resolve the mode LAST so call order doesn't matter:
            # SEQUENTIAL == batch size 1 regardless of max_batch_size()
            kw = dict(self._kw)
            if getattr(self, "_mode", "batched") == "sequential":
                kw["max_batch_size"] = 1
            return ParallelInference(self._model, **kw)

    @staticmethod
    def builder(model) -> "ParallelInference.Builder":
        return ParallelInference.Builder(model)

    def output(self, x):
        """Blocking inference; safe from many threads at once. ``x`` is a
        single array, or a ``{input_name: array}`` dict for multi-input
        ``ComputationGraph`` models; returns np arrays (a list for
        multi-output graphs)."""
        out = self._batcher.submit(x)
        if isinstance(out, list):
            return [np.asarray(o) for o in out]
        return np.asarray(out)

    def shutdown(self):
        self._batcher.shutdown(drain=True)
