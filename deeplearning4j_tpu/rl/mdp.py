"""MDP environment SPI (reference ``org.deeplearning4j.rl4j.mdp.MDP``) with
built-in environments.

The reference wraps gym-java-client / ALE / Malmo; offline here, so the
built-ins are self-contained numpy environments: classic-control CartPole
(standard published dynamics) and a small deterministic GridWorld whose
optimal return is known in closed form (test oracle, like RL4J's toy MDPs).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class ObservationSpace:
    shape: Tuple[int, ...]
    low: Optional[float] = None
    high: Optional[float] = None


@dataclasses.dataclass
class DiscreteSpace:
    n: int

    def random_action(self, rng: np.random.Generator) -> int:
        return int(rng.integers(0, self.n))


class MDP:
    """reset() -> obs; step(a) -> (obs, reward, done, info); close()."""

    observation_space: ObservationSpace
    action_space: DiscreteSpace

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, Any]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def is_done(self) -> bool:
        return getattr(self, "_done", False)


class CartPole(MDP):
    """Cart-pole balancing (the classic control benchmark RL4J targets via
    gym). Euler-integrated pole-on-cart dynamics; reward +1 per step; episode
    ends on |x|>2.4, |theta|>12deg, or 500 steps."""

    GRAVITY, CART_M, POLE_M, POLE_HALF_L = 9.8, 1.0, 0.1, 0.5
    FORCE, TAU, MAX_STEPS = 10.0, 0.02, 500

    def __init__(self, seed: int = 0):
        self.observation_space = ObservationSpace((4,), -4.8, 4.8)
        self.action_space = DiscreteSpace(2)
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros(4, np.float32)
        self._steps = 0
        self._done = True

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._steps = 0
        self._done = False
        return self._state.copy()

    def step(self, action: int):
        x, x_dot, th, th_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        total_m = self.CART_M + self.POLE_M
        ml = self.POLE_M * self.POLE_HALF_L
        cos_t, sin_t = np.cos(th), np.sin(th)
        temp = (force + ml * th_dot**2 * sin_t) / total_m
        th_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_L * (4.0 / 3.0 - self.POLE_M * cos_t**2 / total_m))
        x_acc = temp - ml * th_acc * cos_t / total_m
        self._state = np.array([x + self.TAU * x_dot, x_dot + self.TAU * x_acc,
                                th + self.TAU * th_dot, th_dot + self.TAU * th_acc],
                               np.float32)
        self._steps += 1
        self._done = bool(abs(self._state[0]) > 2.4
                          or abs(self._state[2]) > 12 * np.pi / 180
                          or self._steps >= self.MAX_STEPS)
        return self._state.copy(), 1.0, self._done, {}


class GridWorld(MDP):
    """Deterministic 1-D corridor of ``n`` cells; actions left/right; reward
    +1 at the right end, -0.01 per step, episode cap 4n. Optimal policy is
    'always right' with known return — the convergence oracle for tests
    (RL4J's SimpleToy plays this role)."""

    def __init__(self, n: int = 6):
        self.n = n
        self.observation_space = ObservationSpace((n,))
        self.action_space = DiscreteSpace(2)
        self._pos = 0
        self._steps = 0
        self._done = True

    def _obs(self) -> np.ndarray:
        v = np.zeros(self.n, np.float32)
        v[self._pos] = 1.0
        return v

    def reset(self) -> np.ndarray:
        self._pos, self._steps, self._done = 0, 0, False
        return self._obs()

    def step(self, action: int):
        self._pos = min(self.n - 1, self._pos + 1) if action == 1 else max(0, self._pos - 1)
        self._steps += 1
        at_goal = self._pos == self.n - 1
        self._done = bool(at_goal or self._steps >= 4 * self.n)
        reward = 1.0 if at_goal else -0.01
        return self._obs(), reward, self._done, {}

    def optimal_return(self) -> float:
        return 1.0 - 0.01 * (self.n - 2)


class GymEnv(MDP):
    """Adapter for Gym/Gymnasium-API environments (reference
    ``rl4j-gym``'s ``GymEnv`` over gym-java-client): wraps any object
    exposing ``reset()``/``step(a)`` with either the classic 4-tuple or
    the gymnasium 5-tuple return, and ``observation_space``/
    ``action_space`` with ``shape``/``n``. Pass an environment id to have
    it constructed via ``gymnasium`` (or legacy ``gym``) if installed —
    this box is offline, so the in-repo tests drive the adapter with a
    stub environment instead."""

    def __init__(self, env_or_id):
        if isinstance(env_or_id, str):
            try:
                import gymnasium as _gym
            except ImportError:
                try:
                    import gym as _gym  # legacy API
                except ImportError:
                    raise ImportError(
                        "GymEnv('<id>') needs gymnasium or gym installed; "
                        "pass a constructed env object instead") from None
            env_or_id = _gym.make(env_or_id)
        self.env = env_or_id
        obs_space = self.env.observation_space
        self.observation_space = ObservationSpace(
            tuple(obs_space.shape),
            float(np.min(obs_space.low)) if hasattr(obs_space, "low") else None,
            float(np.max(obs_space.high)) if hasattr(obs_space, "high") else None)
        self.action_space = DiscreteSpace(int(self.env.action_space.n))
        self._done = True
        self.last_truncated = False

    def reset(self) -> np.ndarray:
        out = self.env.reset()
        # gymnasium returns (obs, info); classic gym returns obs
        obs = out[0] if isinstance(out, tuple) else out
        self._done = False
        self.last_truncated = False
        return np.asarray(obs, np.float32)

    def step(self, action: int):
        out = self.env.step(int(action))
        if len(out) == 5:  # gymnasium: obs, reward, terminated, truncated, info
            obs, reward, terminated, truncated, info = out
            done = bool(terminated or truncated)
            # The MDP SPI carries one done bit (reference-era RL4J API),
            # but the terminated/truncated distinction matters for TD
            # bootstrapping (a time-limit truncation is NOT a terminal
            # state) — preserve it in info and on the adapter so learners
            # that know about it can keep the gamma*maxQ(s') term.
            info = dict(info or {})
            info.setdefault("terminated", bool(terminated))
            info.setdefault("truncated", bool(truncated))
            self.last_truncated = bool(truncated) and not bool(terminated)
        else:  # classic gym: obs, reward, done, info
            obs, reward, done, info = out
            done = bool(done)
            # classic gym signals time-limit truncation via the TimeLimit
            # wrapper's info key (no 5-tuple)
            self.last_truncated = bool(
                (info or {}).get("TimeLimit.truncated", False))
        self._done = done
        return np.asarray(obs, np.float32), float(reward), done, info

    def close(self) -> None:
        if hasattr(self.env, "close"):
            self.env.close()
