"""MDP environment SPI (reference ``org.deeplearning4j.rl4j.mdp.MDP``) with
built-in environments.

The reference wraps gym-java-client / ALE / Malmo; offline here, so the
built-ins are self-contained numpy environments: classic-control CartPole
(standard published dynamics) and a small deterministic GridWorld whose
optimal return is known in closed form (test oracle, like RL4J's toy MDPs).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class ObservationSpace:
    shape: Tuple[int, ...]
    low: Optional[float] = None
    high: Optional[float] = None


@dataclasses.dataclass
class DiscreteSpace:
    n: int

    def random_action(self, rng: np.random.Generator) -> int:
        return int(rng.integers(0, self.n))


class MDP:
    """reset() -> obs; step(a) -> (obs, reward, done, info); close()."""

    observation_space: ObservationSpace
    action_space: DiscreteSpace

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, Any]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def is_done(self) -> bool:
        return getattr(self, "_done", False)


class CartPole(MDP):
    """Cart-pole balancing (the classic control benchmark RL4J targets via
    gym). Euler-integrated pole-on-cart dynamics; reward +1 per step; episode
    ends on |x|>2.4, |theta|>12deg, or 500 steps."""

    GRAVITY, CART_M, POLE_M, POLE_HALF_L = 9.8, 1.0, 0.1, 0.5
    FORCE, TAU, MAX_STEPS = 10.0, 0.02, 500

    def __init__(self, seed: int = 0):
        self.observation_space = ObservationSpace((4,), -4.8, 4.8)
        self.action_space = DiscreteSpace(2)
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros(4, np.float32)
        self._steps = 0
        self._done = True

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._steps = 0
        self._done = False
        return self._state.copy()

    def step(self, action: int):
        x, x_dot, th, th_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        total_m = self.CART_M + self.POLE_M
        ml = self.POLE_M * self.POLE_HALF_L
        cos_t, sin_t = np.cos(th), np.sin(th)
        temp = (force + ml * th_dot**2 * sin_t) / total_m
        th_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_L * (4.0 / 3.0 - self.POLE_M * cos_t**2 / total_m))
        x_acc = temp - ml * th_acc * cos_t / total_m
        self._state = np.array([x + self.TAU * x_dot, x_dot + self.TAU * x_acc,
                                th + self.TAU * th_dot, th_dot + self.TAU * th_acc],
                               np.float32)
        self._steps += 1
        self._done = bool(abs(self._state[0]) > 2.4
                          or abs(self._state[2]) > 12 * np.pi / 180
                          or self._steps >= self.MAX_STEPS)
        return self._state.copy(), 1.0, self._done, {}


class GridWorld(MDP):
    """Deterministic 1-D corridor of ``n`` cells; actions left/right; reward
    +1 at the right end, -0.01 per step, episode cap 4n. Optimal policy is
    'always right' with known return — the convergence oracle for tests
    (RL4J's SimpleToy plays this role)."""

    def __init__(self, n: int = 6):
        self.n = n
        self.observation_space = ObservationSpace((n,))
        self.action_space = DiscreteSpace(2)
        self._pos = 0
        self._steps = 0
        self._done = True

    def _obs(self) -> np.ndarray:
        v = np.zeros(self.n, np.float32)
        v[self._pos] = 1.0
        return v

    def reset(self) -> np.ndarray:
        self._pos, self._steps, self._done = 0, 0, False
        return self._obs()

    def step(self, action: int):
        self._pos = min(self.n - 1, self._pos + 1) if action == 1 else max(0, self._pos - 1)
        self._steps += 1
        at_goal = self._pos == self.n - 1
        self._done = bool(at_goal or self._steps >= 4 * self.n)
        reward = 1.0 if at_goal else -0.01
        return self._obs(), reward, self._done, {}

    def optimal_return(self) -> float:
        return 1.0 - 0.01 * (self.n - 2)
