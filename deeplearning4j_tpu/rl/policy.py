"""Action-selection policies (reference ``org.deeplearning4j.rl4j.policy.*``:
``EpsGreedy``, ``DQNPolicy`` (greedy), ``BoltzmannQ``)."""

from __future__ import annotations

import numpy as np


class GreedyPolicy:
    """argmax over Q-values (reference ``DQNPolicy``)."""

    def select(self, q_values: np.ndarray, rng: np.random.Generator) -> int:
        return int(np.argmax(q_values))


class EpsGreedy:
    """Annealed epsilon-greedy (reference ``EpsGreedy``): epsilon decays
    linearly from 1.0 to ``min_epsilon`` over ``epsilon_nb_step`` calls,
    starting after ``update_start`` warmup steps."""

    def __init__(self, n_actions: int, min_epsilon: float = 0.1,
                 epsilon_nb_step: int = 10000, update_start: int = 0):
        self.n_actions = n_actions
        self.min_epsilon = min_epsilon
        self.epsilon_nb_step = max(1, epsilon_nb_step)
        self.update_start = update_start
        self._calls = 0

    @property
    def epsilon(self) -> float:
        t = max(0, self._calls - self.update_start)
        return max(self.min_epsilon, 1.0 - t * (1.0 - self.min_epsilon)
                   / self.epsilon_nb_step)

    def select(self, q_values: np.ndarray, rng: np.random.Generator) -> int:
        eps = self.epsilon
        self._calls += 1
        if rng.random() < eps:
            return int(rng.integers(0, self.n_actions))
        return int(np.argmax(q_values))


class BoltzmannPolicy:
    """Softmax sampling over Q-values at ``temperature`` (reference
    ``BoltzmannQ``)."""

    def __init__(self, temperature: float = 1.0):
        self.temperature = temperature

    def select(self, q_values: np.ndarray, rng: np.random.Generator) -> int:
        z = np.asarray(q_values, np.float64) / self.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(len(p), p=p))
