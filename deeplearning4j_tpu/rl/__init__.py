"""Reinforcement learning (rebuild of the reference's RL4J module).

Upstream RL4J (``rl4j/``, merged into the deeplearning4j monorepo ~beta7)
provides DQN (``QLearningDiscreteDense``), async actor-critic (``A3CDiscrete``),
async n-step Q-learning, experience replay, epsilon-greedy policies, and an
``MDP`` environment SPI (gym/ALE/malmo adapters).

TPU-native redesign (SURVEY.md §7.1 — capability, not translation):

- Environments run on host (numpy); the learner is ONE jitted update step
  (TD/actor-critic loss, grads, optimizer) over batched transitions.
- A3C's async worker threads are an artifact of per-op CPU/GPU dispatch; the
  TPU equivalent is synchronous advantage actor-critic over a *batch of
  vectorized environments* (same estimator, better hardware fit) —
  ``AdvantageActorCritic``.
- n-step returns are computed with a scan inside the jitted update.
"""

from deeplearning4j_tpu.rl.mdp import (MDP, CartPole, DiscreteSpace, GridWorld,
                                        GymEnv, ObservationSpace)
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition
from deeplearning4j_tpu.rl.policy import BoltzmannPolicy, EpsGreedy, GreedyPolicy
from deeplearning4j_tpu.rl.qlearning import QLearningConfiguration, QLearningDiscreteDense
from deeplearning4j_tpu.rl.a2c import A2CConfiguration, AdvantageActorCritic

__all__ = [
    "MDP", "CartPole", "GridWorld", "GymEnv", "DiscreteSpace",
    "ObservationSpace",
    "ExpReplay", "Transition", "EpsGreedy", "GreedyPolicy", "BoltzmannPolicy",
    "QLearningConfiguration", "QLearningDiscreteDense",
    "A2CConfiguration", "AdvantageActorCritic",
]
