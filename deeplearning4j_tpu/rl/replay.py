"""Experience replay (reference ``org.deeplearning4j.rl4j.learning.sync.ExpReplay``):
uniform-sampling circular buffer, preallocated numpy storage so sampling a
batch is a single fancy-index (no per-transition object churn)."""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np


@dataclasses.dataclass
class Transition:
    obs: np.ndarray
    action: int
    reward: float
    next_obs: np.ndarray
    done: bool


class ExpReplay:
    def __init__(self, max_size: int, obs_shape: Tuple[int, ...], seed: int = 0):
        self.max_size = int(max_size)
        self._obs = np.zeros((max_size,) + tuple(obs_shape), np.float32)
        self._next_obs = np.zeros_like(self._obs)
        self._actions = np.zeros(max_size, np.int32)
        self._rewards = np.zeros(max_size, np.float32)
        self._dones = np.zeros(max_size, np.float32)
        self._rng = np.random.default_rng(seed)
        self._size = 0
        self._head = 0

    def __len__(self) -> int:
        return self._size

    def store(self, t: Transition) -> None:
        i = self._head
        self._obs[i] = t.obs
        self._next_obs[i] = t.next_obs
        self._actions[i] = t.action
        self._rewards[i] = t.reward
        self._dones[i] = 1.0 if t.done else 0.0
        self._head = (i + 1) % self.max_size
        self._size = min(self._size + 1, self.max_size)

    def sample(self, batch_size: int):
        idx = self._rng.integers(0, self._size, batch_size)
        return (self._obs[idx], self._actions[idx], self._rewards[idx],
                self._next_obs[idx], self._dones[idx])
