"""Deep Q-learning (reference ``org.deeplearning4j.rl4j.learning.sync.qlearning.
discrete.QLearningDiscreteDense``).

The Q-network is an ordinary ``MultiLayerNetwork`` built from the same config
DSL users write; the learner compiles ONE jitted TD-update step (target
computation, double-DQN action selection, Huber/MSE loss, grads, optimizer —
the reference instead sets Q-labels host-side and calls ``fit`` per batch).
Target-network sync is a pytree copy every ``target_dqn_update_freq`` steps.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork, TrainState
from deeplearning4j_tpu.nn import DenseLayer, InputType, NeuralNetConfiguration, OutputLayer
from deeplearning4j_tpu.rl.mdp import MDP
from deeplearning4j_tpu.rl.policy import EpsGreedy, GreedyPolicy
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition
from deeplearning4j_tpu.train.updaters import Adam


@dataclasses.dataclass
class QLearningConfiguration:
    """Reference ``QLearning.QLConfiguration`` fields, same semantics."""

    seed: int = 123
    max_epoch_step: int = 200          # max steps per episode
    max_step: int = 15000              # total env steps
    exp_rep_max_size: int = 150000
    batch_size: int = 32
    target_dqn_update_freq: int = 500
    update_start: int = 10             # steps before learning starts
    reward_factor: float = 1.0         # reward scaling
    gamma: float = 0.99
    error_clamp: float = 1.0           # TD-error clamp -> Huber delta (0 = MSE)
    min_epsilon: float = 0.1
    epsilon_nb_step: int = 1000
    double_dqn: bool = True


class QLearningDiscreteDense:
    def __init__(self, mdp: MDP, conf: Optional[QLearningConfiguration] = None,
                 hidden: tuple = (64, 64), network: Optional[MultiLayerNetwork] = None,
                 updater=None):
        self.mdp = mdp
        self.conf = conf or QLearningConfiguration()
        self.n_actions = mdp.action_space.n
        obs_dim = int(np.prod(mdp.observation_space.shape))
        self.net = network or self._build_net(obs_dim, hidden, updater)
        if self.net.train_state is None:
            self.net.init()
        self.target_params = jax.tree.map(jnp.copy, self.net.train_state.params)
        self.policy = EpsGreedy(self.n_actions, self.conf.min_epsilon,
                                self.conf.epsilon_nb_step, self.conf.update_start)
        self._rng = np.random.default_rng(self.conf.seed)
        self._key = jax.random.PRNGKey(self.conf.seed)
        self._update_step = None
        self._q_fn = None
        self.episode_rewards: List[float] = []

    def _build_net(self, obs_dim: int, hidden: tuple, updater) -> MultiLayerNetwork:
        b = (NeuralNetConfiguration.builder()
             .seed(self.conf.seed)
             .updater(updater or Adam(1e-3))
             .weight_init("relu")
             .list())
        for h in hidden:
            b.layer(DenseLayer(n_out=h, activation="relu"))
        b.layer(OutputLayer(n_out=self.n_actions, activation="identity",
                            loss="mse"))
        return MultiLayerNetwork(
            b.set_input_type(InputType.feed_forward(obs_dim)).build())

    # ------------------------------------------------------------- jitted ops
    def _make_update(self) -> Callable:
        net, c = self.net, self.conf

        def update(ts: TrainState, target_params, s, a, r, s2, done, rng):
            q_next_t, _, _, _ = net._forward(target_params, ts.model_state, s2,
                                             training=False, rng=None)
            if c.double_dqn:
                q_next_o, _, _, _ = net._forward(ts.params, ts.model_state, s2,
                                                 training=False, rng=None)
                a2 = jnp.argmax(q_next_o, axis=-1)
                q_next = jnp.take_along_axis(q_next_t, a2[:, None], -1)[:, 0]
            else:
                q_next = q_next_t.max(axis=-1)
            target = r + c.gamma * q_next * (1.0 - done)

            def loss_fn(params):
                q, _, _, _ = net._forward(params, ts.model_state, s,
                                          training=True, rng=rng)
                qa = jnp.take_along_axis(q, a[:, None], -1)[:, 0]
                err = qa - jax.lax.stop_gradient(target)
                if c.error_clamp and c.error_clamp > 0:
                    return jnp.mean(optax.huber_loss(err, delta=c.error_clamp))
                return jnp.mean(err * err)

            loss, grads = jax.value_and_grad(loss_fn)(ts.params)
            updates, new_opt = net._tx.update(grads, ts.opt_state, ts.params)
            new_params = optax.apply_updates(ts.params, updates)
            return TrainState(params=new_params, model_state=ts.model_state,
                              opt_state=new_opt, step=ts.step + 1), loss

        return jax.jit(update, donate_argnums=(0,))

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        if self._q_fn is None:
            net = self.net

            def q_fn(params, model_state, x):
                q, _, _, _ = net._forward(params, model_state, x,
                                          training=False, rng=None)
                return q

            self._q_fn = jax.jit(q_fn)
        ts = self.net.train_state
        flat = np.asarray(obs, np.float32).reshape(1, -1)
        return np.asarray(self._q_fn(ts.params, ts.model_state, flat)[0])

    # ---------------------------------------------------------------- train
    def train(self, listeners: Optional[list] = None) -> "QLearningDiscreteDense":
        c = self.conf
        replay = ExpReplay(c.exp_rep_max_size, self.mdp.observation_space.shape,
                           seed=c.seed)
        if self._update_step is None:
            self._update_step = self._make_update()
        step_count, ep_reward, ep_steps = 0, 0.0, 0
        obs = self.mdp.reset()
        while step_count < c.max_step:
            action = self.policy.select(self.q_values(obs), self._rng)
            next_obs, reward, done, _ = self.mdp.step(action)
            ep_reward += reward
            ep_steps += 1
            replay.store(Transition(obs, action, reward * c.reward_factor,
                                    next_obs, done))
            obs = next_obs
            step_count += 1
            if len(replay) >= max(c.batch_size, c.update_start):
                s, a, r, s2, d = replay.sample(c.batch_size)
                s = s.reshape(len(s), -1)
                s2 = s2.reshape(len(s2), -1)
                self._key, sub = jax.random.split(self._key)
                self.net.train_state, loss = self._update_step(
                    self.net.train_state, self.target_params, s, a, r, s2, d, sub)
                self.net._score = loss
            if step_count % c.target_dqn_update_freq == 0:
                self.target_params = jax.tree.map(
                    jnp.copy, self.net.train_state.params)
            if done or ep_steps >= c.max_epoch_step:
                self.episode_rewards.append(ep_reward)
                for lst in (listeners or []):
                    lst.on_epoch_end(self, len(self.episode_rewards))
                obs, ep_reward, ep_steps = self.mdp.reset(), 0.0, 0
        return self

    # ---------------------------------------------------------------- play
    def play(self, max_steps: Optional[int] = None) -> float:
        """One greedy episode; returns total reward (reference
        ``Policy.play``)."""
        greedy = GreedyPolicy()
        obs = self.mdp.reset()
        total, steps = 0.0, 0
        limit = max_steps or self.conf.max_epoch_step
        while steps < limit:
            action = greedy.select(self.q_values(obs), self._rng)
            obs, reward, done, _ = self.mdp.step(action)
            total += reward
            steps += 1
            if done:
                break
        return total

    def get_policy(self) -> GreedyPolicy:
        return GreedyPolicy()
