"""Advantage actor-critic (reference ``org.deeplearning4j.rl4j.learning.async.
a3c.discrete.A3CDiscreteDense`` + ``AsyncNStepQLearning``).

RL4J runs asynchronous worker threads because its per-op dispatch engine
cannot batch across actors; on TPU the same estimator is computed
synchronously over a *vector of environments* — one jitted update per n-step
rollout (policy gradient with n-step advantage, entropy bonus, value MSE).
The trunk/policy-head/value-head network is a two-output ``ComputationGraph``
from the standard DSL, exactly how a user would build it.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.models.computation_graph import ComputationGraph, TrainState
from deeplearning4j_tpu.nn import DenseLayer, InputType, OutputLayer
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.rl.mdp import MDP
from deeplearning4j_tpu.train.updaters import Adam


@dataclasses.dataclass
class A2CConfiguration:
    """Reference ``A3CConfiguration``, plus the env-batch width that replaces
    the thread count (``num_threads`` -> ``num_envs``)."""

    seed: int = 123
    max_epoch_step: int = 200
    max_step: int = 20000
    num_envs: int = 8                  # reference: numThread
    n_step: int = 5                    # reference: nstep (t_max)
    gamma: float = 0.99
    entropy_coef: float = 0.01
    value_coef: float = 0.5
    reward_factor: float = 1.0


class AdvantageActorCritic:
    def __init__(self, mdp_factory, conf: Optional[A2CConfiguration] = None,
                 hidden: tuple = (64,), updater=None):
        self.conf = conf or A2CConfiguration()
        self.envs: List[MDP] = [mdp_factory(i) for i in range(self.conf.num_envs)]
        proto = self.envs[0]
        self.n_actions = proto.action_space.n
        self.obs_dim = int(np.prod(proto.observation_space.shape))
        self.net = self._build_net(hidden, updater)
        self.net.init()
        self._rng = np.random.default_rng(self.conf.seed)
        self._key = jax.random.PRNGKey(self.conf.seed)
        self._update = None
        self._pi_v = None
        self.episode_rewards: List[float] = []

    def _build_net(self, hidden: tuple, updater) -> ComputationGraph:
        g = (NeuralNetConfiguration.builder()
             .seed(self.conf.seed)
             .updater(updater or Adam(7e-4))
             .weight_init("xavier")
             .graph_builder()
             .add_inputs("obs"))
        prev = "obs"
        for i, h in enumerate(hidden):
            g.add_layer(f"trunk{i}", DenseLayer(n_out=h, activation="tanh"), prev)
            prev = f"trunk{i}"
        g.add_layer("pi", OutputLayer(n_out=self.n_actions, activation="softmax",
                                      loss="mcxent"), prev)
        g.add_layer("v", OutputLayer(n_out=1, activation="identity", loss="mse"),
                    prev)
        g.set_outputs("pi", "v")
        g.set_input_types(InputType.feed_forward(self.obs_dim))
        return ComputationGraph(g.build())

    # ------------------------------------------------------------- jitted ops
    def _make_pi_v(self):
        net = self.net

        def pi_v(params, model_state, obs):
            acts, _, _ = net._forward_all(params, model_state, {"obs": obs},
                                          training=False, rng=None)
            return acts["pi"], acts["v"][:, 0]

        return jax.jit(pi_v)

    def _make_update(self):
        net, c = self.net, self.conf

        def update(ts: TrainState, obs, actions, returns, rng):
            """obs (T*B, D), actions (T*B,), returns (T*B,) n-step targets."""
            def loss_fn(params):
                acts, _, _ = net._forward_all(params, ts.model_state,
                                              {"obs": obs}, training=True,
                                              rng=rng)
                pi, v = acts["pi"], acts["v"][:, 0]
                logp = jnp.log(jnp.clip(pi, 1e-8))
                logp_a = jnp.take_along_axis(logp, actions[:, None], -1)[:, 0]
                adv = jax.lax.stop_gradient(returns - v)
                policy_loss = -jnp.mean(logp_a * adv)
                value_loss = jnp.mean((returns - v) ** 2)
                entropy = -jnp.mean(jnp.sum(pi * logp, axis=-1))
                total = (policy_loss + c.value_coef * value_loss
                         - c.entropy_coef * entropy)
                return total, (policy_loss, value_loss, entropy)

            (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(ts.params)
            updates, new_opt = net._tx.update(grads, ts.opt_state, ts.params)
            new_params = optax.apply_updates(ts.params, updates)
            return TrainState(params=new_params, model_state=ts.model_state,
                              opt_state=new_opt, step=ts.step + 1), loss

        return jax.jit(update, donate_argnums=(0,))

    # ---------------------------------------------------------------- train
    def train(self) -> "AdvantageActorCritic":
        c = self.conf
        if self._update is None:
            self._update = self._make_update()
            self._pi_v = self._make_pi_v()
        B = c.num_envs
        obs = np.stack([e.reset() for e in self.envs]).reshape(B, -1)
        ep_rewards = np.zeros(B)
        ep_steps = np.zeros(B, np.int64)
        total_steps = 0
        while total_steps < c.max_step:
            ts = self.net.train_state
            tr_obs, tr_act, tr_rew, tr_done = [], [], [], []
            for _ in range(c.n_step):
                pi, v = self._pi_v(ts.params, ts.model_state,
                                   obs.astype(np.float32))
                pi = np.asarray(pi, np.float64)
                pi /= pi.sum(-1, keepdims=True)
                acts = np.array([self._rng.choice(self.n_actions, p=pi[i])
                                 for i in range(B)], np.int32)
                step_out = [self.envs[i].step(int(acts[i])) for i in range(B)]
                next_obs = np.stack([o for o, _, _, _ in step_out]).reshape(B, -1)
                rewards = np.array([r for _, r, _, _ in step_out], np.float32)
                dones = np.array([d for _, _, d, _ in step_out], np.float32)
                tr_obs.append(obs.copy())
                tr_act.append(acts)
                tr_rew.append(rewards * c.reward_factor)
                tr_done.append(dones)
                ep_rewards += rewards
                ep_steps += 1
                for i in range(B):
                    if dones[i] or ep_steps[i] >= c.max_epoch_step:
                        self.episode_rewards.append(float(ep_rewards[i]))
                        next_obs[i] = self.envs[i].reset().reshape(-1)
                        ep_rewards[i], ep_steps[i] = 0.0, 0
                        dones[i] = 1.0  # truncation bootstraps like termination
                obs = next_obs
                total_steps += B
            # n-step discounted returns, bootstrapped with V(s_T)
            _, v_last = self._pi_v(ts.params, ts.model_state,
                                   obs.astype(np.float32))
            ret = np.asarray(v_last, np.float32)
            returns = np.zeros((c.n_step, B), np.float32)
            for t in reversed(range(c.n_step)):
                ret = tr_rew[t] + c.gamma * ret * (1.0 - tr_done[t])
                returns[t] = ret
            self._key, sub = jax.random.split(self._key)
            self.net.train_state, loss = self._update(
                self.net.train_state,
                np.concatenate(tr_obs).astype(np.float32),
                np.concatenate(tr_act),
                returns.reshape(-1), sub)
            self.net._score = loss
        return self

    # ---------------------------------------------------------------- play
    def play(self, max_steps: Optional[int] = None) -> float:
        """One greedy (argmax-policy) episode on env 0."""
        if self._pi_v is None:
            self._pi_v = self._make_pi_v()
        env = self.envs[0]
        obs = env.reset().reshape(1, -1)
        total, steps = 0.0, 0
        limit = max_steps or self.conf.max_epoch_step
        ts = self.net.train_state
        while steps < limit:
            pi, _ = self._pi_v(ts.params, ts.model_state, obs.astype(np.float32))
            o, reward, done, _ = env.step(int(np.argmax(np.asarray(pi)[0])))
            obs = o.reshape(1, -1)
            total += reward
            steps += 1
            if done:
                break
        return total
