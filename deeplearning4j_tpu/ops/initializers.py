"""Weight initialisation schemes.

Matches the reference's ``WeightInit`` enum semantics (upstream
``org.deeplearning4j.nn.weights.WeightInit`` + ``WeightInitUtil``) so that loss
curves are comparable layer-for-layer:

- XAVIER            N(0, 2/(fanIn+fanOut))
- XAVIER_UNIFORM    U(-a, a), a = sqrt(6/(fanIn+fanOut))  (Glorot uniform)
- XAVIER_FAN_IN     N(0, 1/fanIn)
- RELU              N(0, 2/fanIn)  (He)
- RELU_UNIFORM      U(-a, a), a = sqrt(6/fanIn)
- LECUN_NORMAL      N(0, 1/fanIn)
- LECUN_UNIFORM     U(-a, a), a = sqrt(3/fanIn)
- SIGMOID_UNIFORM   U(-a, a), a = 4*sqrt(6/(fanIn+fanOut))
- NORMAL            N(0, 1/fanIn)  (DL4J 'NORMAL' is fan-in scaled)
- UNIFORM           U(-a, a), a = 1/sqrt(fanIn)
- ZERO / ONES / IDENTITY / DISTRIBUTION / VAR_SCALING_*
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class WeightInit(str, enum.Enum):
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    NORMAL = "normal"
    UNIFORM = "uniform"
    ZERO = "zero"
    ONES = "ones"
    IDENTITY = "identity"
    VAR_SCALING_NORMAL_FAN_IN = "var_scaling_normal_fan_in"
    VAR_SCALING_NORMAL_FAN_OUT = "var_scaling_normal_fan_out"
    VAR_SCALING_NORMAL_FAN_AVG = "var_scaling_normal_fan_avg"
    VAR_SCALING_UNIFORM_FAN_IN = "var_scaling_uniform_fan_in"
    VAR_SCALING_UNIFORM_FAN_OUT = "var_scaling_uniform_fan_out"
    VAR_SCALING_UNIFORM_FAN_AVG = "var_scaling_uniform_fan_avg"
    DISTRIBUTION = "distribution"


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    scheme: WeightInit | str = WeightInit.XAVIER,
    fan: Optional[Tuple[int, int]] = None,
    dtype=jnp.float32,
    distribution: Optional[dict] = None,
) -> jax.Array:
    """Draw a weight tensor.

    ``fan`` is (fan_in, fan_out); if omitted it is inferred from ``shape``
    with the convention used throughout this framework: last dim = fan_out,
    product of the rest = fan_in (correct for dense ``(in, out)`` and for
    HWIO conv kernels ``(kh, kw, in, out)`` where receptive field multiplies
    fan_in, matching the reference's conv fan computation).
    """
    scheme = WeightInit(scheme) if not isinstance(scheme, WeightInit) else scheme
    shape = tuple(int(s) for s in shape)
    if fan is None:
        fan_out = shape[-1] if len(shape) >= 1 else 1
        fan_in = 1
        for s in shape[:-1]:
            fan_in *= s
        if len(shape) == 1:
            fan_in = shape[0]
    else:
        fan_in, fan_out = fan
    fan_in = max(1, int(fan_in))
    fan_out = max(1, int(fan_out))

    def normal(std):
        return jax.random.normal(key, shape, dtype) * jnp.asarray(std, dtype)

    def uniform(limit):
        return jax.random.uniform(key, shape, dtype, -limit, limit)

    s = scheme
    W = WeightInit
    if s == W.XAVIER:
        return normal(jnp.sqrt(2.0 / (fan_in + fan_out)))
    if s == W.XAVIER_UNIFORM:
        return uniform(jnp.sqrt(6.0 / (fan_in + fan_out)))
    if s == W.XAVIER_FAN_IN:
        return normal(jnp.sqrt(1.0 / fan_in))
    if s == W.RELU:
        return normal(jnp.sqrt(2.0 / fan_in))
    if s == W.RELU_UNIFORM:
        return uniform(jnp.sqrt(6.0 / fan_in))
    if s == W.LECUN_NORMAL:
        return normal(jnp.sqrt(1.0 / fan_in))
    if s == W.LECUN_UNIFORM:
        return uniform(jnp.sqrt(3.0 / fan_in))
    if s == W.SIGMOID_UNIFORM:
        return uniform(4.0 * jnp.sqrt(6.0 / (fan_in + fan_out)))
    if s == W.NORMAL:
        return normal(jnp.sqrt(1.0 / fan_in))
    if s == W.UNIFORM:
        return uniform(1.0 / jnp.sqrt(fan_in))
    if s == W.ZERO:
        return jnp.zeros(shape, dtype)
    if s == W.ONES:
        return jnp.ones(shape, dtype)
    if s == W.IDENTITY:
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    if s in (W.VAR_SCALING_NORMAL_FAN_IN, W.VAR_SCALING_UNIFORM_FAN_IN):
        n = fan_in
    elif s in (W.VAR_SCALING_NORMAL_FAN_OUT, W.VAR_SCALING_UNIFORM_FAN_OUT):
        n = fan_out
    else:
        n = (fan_in + fan_out) / 2.0
    if s == W.DISTRIBUTION:
        return _from_distribution(key, shape, dtype, distribution or {})
    if "uniform" in s.value:
        return uniform(jnp.sqrt(3.0 / n))
    return normal(jnp.sqrt(1.0 / n))


def _from_distribution(key, shape, dtype, dist: dict):
    """DL4J ``Distribution`` configs: {"type": "normal"|"uniform"|"truncated_normal"|
    "constant"|"orthogonal", ...params}."""
    kind = dist.get("type", "normal").lower()
    if kind == "normal":
        return dist.get("mean", 0.0) + jax.random.normal(key, shape, dtype) * dist.get("std", 1.0)
    if kind == "truncated_normal":
        std = dist.get("std", 1.0)
        return dist.get("mean", 0.0) + jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) * std
    if kind == "uniform":
        return jax.random.uniform(key, shape, dtype, dist.get("lower", -1.0), dist.get("upper", 1.0))
    if kind == "constant":
        return jnp.full(shape, dist.get("value", 0.0), dtype)
    if kind == "orthogonal":
        return jax.nn.initializers.orthogonal(scale=dist.get("gain", 1.0))(key, shape, dtype)
    raise ValueError(f"Unknown distribution type {kind!r}")
