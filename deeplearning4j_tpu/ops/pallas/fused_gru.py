"""Persistent fused GRU as Pallas TPU kernels (forward AND backward).

Companion to :mod:`fused_lstm` (SURVEY.md §7.2's hand-written-kernel layer):
the input projection ``x @ W + b`` for the whole sequence is hoisted to one
MXU matmul outside the kernel; the sequential recurrence runs with ``W_rec``
pinned in VMEM and ``h`` carried in VMEM scratch across the grid.

Gate order matches the layer convention [r, u, n] (reset, update, new):

    zh  = h @ W_rec                       (one (B,H)@(H,3H) matmul per step)
    r   = sigmoid(zx_r + zh_r)
    u   = sigmoid(zx_u + zh_u)
    n   = tanh(zx_n + r * zh_n)
    h'  = (1 - u) * n + u * h

Backward (reverse-time kernel): with dh' arriving from t+1 and dys_t,

    du    = dh' * (h - n) * u * (1-u)
    da    = dh' * (1-u) * (1-n^2)          (pre-tanh grad of n)
    dr    = da * zh_n;  ds_r = dr * r * (1-r)
    dzx   = [ds_r, ds_u, da]               (input-projection grad, streamed)
    ds_rec= [ds_r, ds_u, da * r]           (recurrent-projection grad)
    dh    = dh' * u + ds_rec @ W_rec^T

The weight gradients are large matmuls OUTSIDE the kernel:
``dW_rec = h_prev^T @ ds_rec`` where ``ds_rec`` is rebuilt from the streamed
``dzx`` and the saved reset gate (only the n-third differs by the factor r).

Residuals saved by the forward for backward: activated gates [r, u, n]
(T, B, 3H) and the pre-activation recurrent n-slice ``zh_n`` (T, B, H).

Applicability mirrors the LSTM kernel: default activations, no mask,
tile-aligned shapes within the VMEM budget, T >= 32.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.pallas.common import VMEM_BUDGET as _VMEM_BUDGET
from deeplearning4j_tpu.ops.pallas.common import interpret_mode as _interpret


def _vmem_bytes(b: int, h: int, itemsize: int) -> int:
    """Worst-case (backward) footprint: pinned W_rec^T + double-buffered
    streams (dys, gates, zh_n, h_prev, dzx) + boundary blocks + f32
    scratch."""
    w_rec = h * 3 * h * itemsize
    streams = 2 * (b * h + b * 3 * h + b * h + b * h + b * 3 * h) * itemsize
    boundary = 2 * b * h * itemsize
    scratch = b * h * 4
    return w_rec + streams + boundary + scratch


def fused_gru_compatible(zx, h0) -> bool:
    if zx.ndim != 3 or h0.ndim != 2:
        return False
    t, b, h3 = zx.shape
    h = h0.shape[1]
    if h3 != 3 * h:
        return False
    if b % 8 or h % 128:
        return False
    if t < 32 and not _interpret():
        return False
    if zx.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if _vmem_bytes(b, h, jnp.dtype(zx.dtype).itemsize) > _VMEM_BUDGET:
        return False
    if _interpret():
        return True
    platform = jax.devices()[0].platform
    return platform in ("tpu", "axon")


# ---------------------------------------------------------------- forward


def _fwd_kernel(zx_ref, wrec_ref, h0_ref,
                ys_ref, hT_ref, gates_ref, zhn_ref,
                h_scr, *, hidden: int):
    t = pl.program_id(0)
    n_t = pl.num_programs(0)
    H = hidden

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    in_dtype = zx_ref.dtype
    zh = jax.lax.dot(h.astype(in_dtype), wrec_ref[:],
                     preferred_element_type=jnp.float32)
    zx = zx_ref[0].astype(jnp.float32)
    r = jax.nn.sigmoid(zx[:, :H] + zh[:, :H])
    u = jax.nn.sigmoid(zx[:, H:2 * H] + zh[:, H:2 * H])
    zh_n = zh[:, 2 * H:]
    n = jnp.tanh(zx[:, 2 * H:] + r * zh_n)
    h_new = (1.0 - u) * n + u * h

    ys_ref[0] = h_new.astype(ys_ref.dtype)
    if gates_ref is not None:
        gates_ref[0, :, :H] = r.astype(gates_ref.dtype)
        gates_ref[0, :, H:2 * H] = u.astype(gates_ref.dtype)
        gates_ref[0, :, 2 * H:] = n.astype(gates_ref.dtype)
        zhn_ref[0] = zh_n.astype(zhn_ref.dtype)
    h_scr[:] = h_new

    @pl.when(t == n_t - 1)
    def _():
        hT_ref[:] = h_new.astype(hT_ref.dtype)


def _gru_fwd(zx, w_rec, h0, save_residuals):
    t, b, h3 = zx.shape
    h = h3 // 3
    dtype = zx.dtype
    out_shape = [
        jax.ShapeDtypeStruct((t, b, h), dtype),   # ys
        jax.ShapeDtypeStruct((b, h), dtype),      # hT
    ]
    out_specs = [
        pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
        pl.BlockSpec((b, h), lambda i: (0, 0)),
    ]
    if save_residuals:
        out_shape += [
            jax.ShapeDtypeStruct((t, b, h3), dtype),  # gates [r,u,n]
            jax.ShapeDtypeStruct((t, b, h), dtype),   # zh_n
        ]
        out_specs += [
            pl.BlockSpec((1, b, h3), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
        ]
    kernel = functools.partial(_fwd_kernel, hidden=h)
    if not save_residuals:
        kernel = functools.partial(
            lambda *refs, hidden: _fwd_kernel(
                *refs[:5], None, None, *refs[5:], hidden=hidden),
            hidden=h)
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, h3), lambda i: (i, 0, 0)),   # zx_t
            pl.BlockSpec((h, h3), lambda i: (0, 0)),         # W_rec (pinned)
            pl.BlockSpec((b, h), lambda i: (0, 0)),          # h0
        ],
        out_specs=out_specs,
        scratch_shapes=[pltpu.VMEM((b, h), jnp.float32)],
        interpret=_interpret(),
    )(zx, w_rec, h0)
    if save_residuals:
        ys, hT, gates, zhn = res
        return ys, hT, (gates, zhn)
    ys, hT = res
    return ys, hT, None


# ---------------------------------------------------------------- backward


def _bwd_kernel(dys_ref, dhT_ref, gates_ref, zhn_ref, hprev_ref, wrecT_ref,
                dzx_ref, dh0_ref,
                dh_scr, *, hidden: int):
    """Reverse-time step (grid index i counts BACKWARD: t = T-1-i)."""
    i_step = pl.program_id(0)
    n_t = pl.num_programs(0)
    H = hidden

    @pl.when(i_step == 0)
    def _():
        dh_scr[:] = dhT_ref[:].astype(jnp.float32)

    gates = gates_ref[0].astype(jnp.float32)
    r = gates[:, :H]
    u = gates[:, H:2 * H]
    n = gates[:, 2 * H:]
    zh_n = zhn_ref[0].astype(jnp.float32)
    h_prev = hprev_ref[0].astype(jnp.float32)

    dh = dh_scr[:] + dys_ref[0].astype(jnp.float32)
    du = dh * (h_prev - n) * u * (1.0 - u)
    da = dh * (1.0 - u) * (1.0 - n * n)
    dr = da * zh_n
    ds_r = dr * r * (1.0 - r)

    in_dtype = dzx_ref.dtype
    dzx_ref[0, :, :H] = ds_r.astype(in_dtype)
    dzx_ref[0, :, H:2 * H] = du.astype(in_dtype)
    dzx_ref[0, :, 2 * H:] = da.astype(in_dtype)

    # ds_rec differs from dzx only in the n-third: da * r
    ds_rec_n = (da * r).astype(in_dtype)
    # dh_prev = dh*u + ds_rec @ W_rec^T, assembled from the three thirds
    wT = wrecT_ref[:]  # (3H, H)
    dh_prev = (dh * u
               + jax.lax.dot(ds_r.astype(in_dtype), wT[:H],
                             preferred_element_type=jnp.float32)
               + jax.lax.dot(du.astype(in_dtype), wT[H:2 * H],
                             preferred_element_type=jnp.float32)
               + jax.lax.dot(ds_rec_n, wT[2 * H:],
                             preferred_element_type=jnp.float32))
    dh_scr[:] = dh_prev

    @pl.when(i_step == n_t - 1)
    def _():
        dh0_ref[:] = dh_scr[:].astype(dh0_ref.dtype)


def _gru_bwd_kernel_call(dys, dhT, gates, zhn, h_prev_seq, w_rec):
    t, b, h3 = gates.shape
    h = h3 // 3
    dtype = gates.dtype
    w_rec_t = w_rec.T  # (3H, H)
    rev = lambda i: (t - 1 - i, 0, 0)  # noqa: E731 — reverse-time index map
    dzx, dh0 = pl.pallas_call(
        functools.partial(_bwd_kernel, hidden=h),
        out_shape=[
            jax.ShapeDtypeStruct((t, b, h3), dtype),
            jax.ShapeDtypeStruct((b, h), dtype),
        ],
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, h), rev),                    # dys_t
            pl.BlockSpec((b, h), lambda i: (0, 0)),          # dhT
            pl.BlockSpec((1, b, h3), rev),                   # gates_t
            pl.BlockSpec((1, b, h), rev),                    # zh_n
            pl.BlockSpec((1, b, h), rev),                    # h_{t-1}
            pl.BlockSpec((h3, h), lambda i: (0, 0)),         # W_rec^T (pinned)
        ],
        out_specs=[
            pl.BlockSpec((1, b, h3), rev),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((b, h), jnp.float32)],
        interpret=_interpret(),
    )(dys, dhT, gates, zhn, h_prev_seq, w_rec_t)
    return dzx, dh0


# ------------------------------------------------------------- public VJP


@jax.custom_vjp
def fused_gru(zx, w_rec, h0):
    """Run the fused GRU recurrence. ``zx`` is the hoisted input projection
    ``x @ W + b`` laid out (T, B, 3H); returns ``(ys, hT)``. Check
    :func:`fused_gru_compatible` first."""
    ys, hT, _ = _gru_fwd(zx, w_rec, h0, save_residuals=False)
    return ys, hT


def _fused_gru_vjp_fwd(zx, w_rec, h0):
    ys, hT, (gates, zhn) = _gru_fwd(zx, w_rec, h0, save_residuals=True)
    return (ys, hT), (ys, gates, zhn, w_rec, h0)


def _fused_gru_vjp_bwd(res, cotangents):
    dys, dhT = cotangents
    ys, gates, zhn, w_rec, h0 = res
    h_prev = jnp.concatenate([h0[None], ys[:-1]], axis=0)
    dzx, dh0 = _gru_bwd_kernel_call(dys, dhT, gates, zhn, h_prev, w_rec)
    # ds_rec rebuilt from dzx: only the n-third is scaled by the reset gate
    h = h0.shape[1]
    r = gates[..., :h]
    ds_rec = jnp.concatenate(
        [dzx[..., :2 * h],
         (dzx[..., 2 * h:].astype(jnp.float32)
          * r.astype(jnp.float32)).astype(dzx.dtype)], axis=-1)
    hp = h_prev.reshape(-1, h)
    dsf = ds_rec.reshape(-1, 3 * h)
    dw_rec = jax.lax.dot_general(
        hp, dsf, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w_rec.dtype)
    return dzx, dw_rec, dh0


fused_gru.defvjp(_fused_gru_vjp_fwd, _fused_gru_vjp_bwd)
