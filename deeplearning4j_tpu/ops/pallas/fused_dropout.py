"""Fused inverted dropout (+ optional residual add) with IN-KERNEL PRNG.

Why a kernel: profiled on v5e, XLA materialises every dropout site three
times over — the ``rng-bit-generator`` writes a u32[batch, T, d] bits tensor
(25 MB at BERT-base shape), a layout ``copy`` of it follows (the rbg output
tiling never matches the consumer), and the bool keep-mask is saved for the
backward pass.  At 25 dropout sites per BERT-base train step that is
gigabytes of pure mask traffic per step (the round-3 profile showed
~1500 copy ops/step, the largest being exactly these u32 bits tensors).

Here the mask NEVER exists in HBM, in either pass:

- forward:  seed the per-core PRNG (``pltpu.prng_seed``) from a scalar
  folded with the grid position, draw the bits straight into VMEM, apply
  ``x + where(bits < keep_threshold, h/keep, 0)`` and write only the output.
- backward: re-seed identically, regenerate the SAME bits, and scale the
  incoming cotangent — recompute-in-backward at the kernel level, so the
  residual set is empty (the custom_vjp saves only the scalar seed).

This is the cuDNN-style fused-dropout role from the reference's helper layer
(SURVEY.md §7.2, upstream ``org.deeplearning4j.cuda`` dropout helpers),
designed TPU-first: the VPU generates bits faster than HBM could store them.

The mask distribution matches ``nn.base.dropout_mask`` statistically
(Bernoulli(keep) per element) but uses the Mosaic PRNG stream, not the jax
rbg stream — seeds produce different (equally valid) masks than the jnp
path. Tests assert statistics + determinism-given-seed + fwd/bwd mask
consistency, not specific bits.

CPU/test path: ``DL4J_TPU_PALLAS_INTERPRET=1`` runs the same kernels under
the Pallas interpreter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports cleanly only where jaxlib has Mosaic support
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from deeplearning4j_tpu.ops.pallas.common import interpret_mode as _interpret

# Rows per grid step over the flattened (rows, features) view. 512 rows of
# bf16[*, 768] = 0.77 MB in + out + 1.5 MB of u32 bits — far under VMEM.
BLOCK_ROWS = 512


def _fwd_kernel(seed_ref, h_ref, x_ref, o_ref, *, thresh, inv_keep):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.prng_random_bits(h_ref.shape).astype(jnp.uint32)
    kept = bits < jnp.uint32(thresh)
    y = jnp.where(kept, h_ref[...] * jnp.asarray(inv_keep, h_ref.dtype),
                  jnp.zeros((), h_ref.dtype))
    if x_ref is not None:
        y = x_ref[...] + y
    o_ref[...] = y


def _bwd_kernel(seed_ref, g_ref, o_ref, *, thresh, inv_keep):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.prng_random_bits(g_ref.shape).astype(jnp.uint32)
    kept = bits < jnp.uint32(thresh)
    o_ref[...] = jnp.where(kept, g_ref[...] * jnp.asarray(inv_keep, g_ref.dtype),
                           jnp.zeros((), g_ref.dtype))


def _flatten(h):
    d = h.shape[-1]
    return h.reshape(-1, d)


def fused_dropout_compatible(h, rate: float) -> bool:
    """Kernel eligibility: TPU (or interpret mode), 0<rate<1, flattenable to
    (rows, d) with rows % BLOCK_ROWS == 0 and d % 128 == 0."""
    if pltpu is None:
        return False
    if not (0.0 < float(rate) < 1.0):
        return False
    if not _interpret():
        try:
            if jax.default_backend() not in ("tpu", "axon"):
                return False
        except Exception:
            return False
    if h.ndim < 2:
        return False
    d = h.shape[-1]
    rows = int(np.prod(h.shape[:-1]))
    return rows % BLOCK_ROWS == 0 and d % 128 == 0


def _ref_bits(seed, rows, d):
    """Interpreter/CPU emulation of the in-kernel draw: the Mosaic PRNG
    primitives have no interpreter lowering in this jax version, so tests
    use a jax-rbg stream keyed by the same scalar seed. Statistically
    identical, deterministic given the seed, consistent between fwd and bwd
    (both call this) — but a DIFFERENT stream than the TPU kernel's."""
    key = jax.random.wrap_key_data(
        jnp.stack([seed.astype(jnp.uint32)] * 4).reshape(4), impl="rbg")
    return jax.random.bits(key, (rows, d), jnp.uint32)


def _call(kernel, seed, args, out_dtype, rows, d, thresh, inv_keep):
    seed = jnp.reshape(seed, (1,)).astype(jnp.int32)
    if _interpret():
        bits = _ref_bits(seed[0], rows, d)
        kept = bits < jnp.uint32(thresh)
        h = args[0]
        y = jnp.where(kept, h * jnp.asarray(inv_keep, h.dtype),
                      jnp.zeros((), h.dtype))
        if len(args) > 1:
            y = args[1] + y
        return y
    grid = (rows // BLOCK_ROWS,)
    # index_map receives the scalar-prefetch ref after the grid indices
    spec = pl.BlockSpec((BLOCK_ROWS, d), lambda i, *_: (i, 0))
    return pl.pallas_call(
        functools.partial(kernel, thresh=thresh, inv_keep=inv_keep),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[spec] * len(args),
            out_specs=spec,
        ),
        out_shape=jax.ShapeDtypeStruct((rows, d), out_dtype),
        interpret=_interpret(),
    )(seed, *args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_dropout_add(x, h, seed, rate: float):
    """``x + inverted_dropout(h, rate)`` (x may be None for plain dropout).

    ``seed``: int32 scalar array — fold the training step's PRNG key down
    with ``seed_from_key``. Same seed -> same mask, forward and backward.
    """
    y, _ = _fwd_res(x, h, seed, rate)
    return y


def _thresh(rate: float) -> int:
    keep = 1.0 - float(rate)
    return min(int(keep * 4294967296.0), 4294967295)


def _fwd_res(x, h, seed, rate):
    d = h.shape[-1]
    rows = int(np.prod(h.shape[:-1]))
    keep = 1.0 - float(rate)
    hf = _flatten(h)
    args = (hf,) if x is None else (hf, _flatten(x))
    # kernel positional order is (seed, h, x, o); adapt when x is None
    if x is None:
        def kern(seed_ref, h_ref, o_ref, *, thresh, inv_keep):
            return _fwd_kernel(seed_ref, h_ref, None, o_ref,
                               thresh=thresh, inv_keep=inv_keep)
    else:
        kern = _fwd_kernel
    y = _call(kern, seed, args, h.dtype, rows, d, _thresh(rate), 1.0 / keep)
    return y.reshape(h.shape), (seed,)


def _fwd_vjp(x, h, seed, rate):
    y, res = _fwd_res(x, h, seed, rate)
    return y, (res, x is None)


def _bwd_vjp(rate, packed, gy):
    (seed,), x_was_none = packed
    d = gy.shape[-1]
    rows = int(np.prod(gy.shape[:-1]))
    keep = 1.0 - float(rate)
    dh = _call(_bwd_kernel, seed, (_flatten(gy),), gy.dtype, rows, d,
               _thresh(rate), 1.0 / keep).reshape(gy.shape)
    dx = None if x_was_none else gy
    return (dx, dh, jnp.zeros_like(seed))


fused_dropout_add.defvjp(_fwd_vjp, _bwd_vjp)


def fused_dropout(h, seed, rate: float):
    """Plain fused inverted dropout (no residual)."""
    return fused_dropout_add(None, h, seed, rate)


def seed_from_key(key) -> jax.Array:
    """Fold a jax PRNG key to the kernel's int32 scalar seed (one tiny
    threefry draw; fuses into the surrounding program)."""
    return jax.random.bits(key, (), jnp.uint32).astype(jnp.int32)
