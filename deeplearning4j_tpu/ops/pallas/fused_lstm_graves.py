"""Persistent fused LSTM with peepholes and sequence masks (Pallas TPU).

Generalisation of :mod:`fused_lstm` covering the reference's ``GravesLSTM``
cell (peephole connections, ``org.deeplearning4j.nn.layers.recurrent.
GravesLSTM`` / cuDNN-helper role, SURVEY.md §2.1) and DL4J's masked-sequence
semantics (masked steps hold h/c and emit the held h). With zero peepholes
this is exactly the plain cell, so it also serves as the fast path for
masked ``LSTM`` layers — the two cases round 1 left on the scan path
(BASELINE config #3 benches GravesLSTM!).

Same structure as fused_lstm: whole-sequence input projection hoisted
outside; ``W_rec`` (and the tiny peephole row) pinned in VMEM; h/c carried
in f32 scratch across the sequential grid; per-step tensors streamed.
Backward runs the reverse-time recurrence in-kernel producing pre-activation
grads ``ds``; weight/peephole grads are large fused contractions outside.

Cell (gate order [i, f, g, o], peephole rows [p_i, p_f, p_o]):

    z   = zx_t + h @ W_rec
    i   = sigmoid(z_i + c * p_i)
    f   = sigmoid(z_f + c * p_f)
    g   = tanh(z_g)
    c~  = f * c + i * g
    o   = sigmoid(z_o + c~ * p_o)
    h~  = o * tanh(c~)
    h'  = m * h~ + (1-m) * h          (m: per-step mask, 1.0 when unmasked)
    c'  = m * c~ + (1-m) * c
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.pallas.common import VMEM_BUDGET as _VMEM_BUDGET
from deeplearning4j_tpu.ops.pallas.common import interpret_mode as _interpret


def _vmem_bytes(b: int, h: int, itemsize: int) -> int:
    w_rec = h * 4 * h * itemsize
    streams = 2 * (b * h + 2 * b * 4 * h + b * h + b + b * h) * itemsize
    boundary = 4 * b * h * itemsize
    scratch = 2 * b * h * 4
    peep = b * 3 * h * itemsize
    return w_rec + streams + boundary + scratch + peep


def fused_graves_lstm_compatible(zx, h0) -> bool:
    """Same applicability rules as the plain kernel (tile-aligned B/H,
    T>=32, dtype, VMEM budget)."""
    if zx.ndim != 3 or h0.ndim != 2:
        return False
    t, b, h4 = zx.shape
    h = h0.shape[1]
    if h4 != 4 * h or b % 8 or h % 128:
        return False
    if t < 32 and not _interpret():
        return False
    if zx.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if _vmem_bytes(b, h, jnp.dtype(zx.dtype).itemsize) > _VMEM_BUDGET:
        return False
    if _interpret():
        return True
    return jax.devices()[0].platform in ("tpu", "axon")


# ---------------------------------------------------------------- forward
def _fwd_kernel(zx_ref, wrec_ref, peep_ref, h0_ref, c0_ref, mask_ref,
                ys_ref, hT_ref, cT_ref, gates_ref, cseq_ref,
                h_scr, c_scr, *, hidden: int):
    t = pl.program_id(0)
    n_t = pl.num_programs(0)
    H = hidden

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    c = c_scr[:]
    in_dtype = zx_ref.dtype
    z = zx_ref[0].astype(jnp.float32) + jax.lax.dot(
        h.astype(in_dtype), wrec_ref[:], preferred_element_type=jnp.float32)
    p = peep_ref[:].astype(jnp.float32)  # (B, 3H) pre-broadcast
    i = jax.nn.sigmoid(z[:, :H] + c * p[:, :H])
    f = jax.nn.sigmoid(z[:, H:2 * H] + c * p[:, H:2 * H])
    g = jnp.tanh(z[:, 2 * H:3 * H])
    c_til = f * c + i * g
    o = jax.nn.sigmoid(z[:, 3 * H:] + c_til * p[:, 2 * H:])
    h_til = o * jnp.tanh(c_til)
    m = mask_ref[0, 0].astype(jnp.float32)[:, None]  # (B, 1)
    h_new = m * h_til + (1.0 - m) * h
    c_new = m * c_til + (1.0 - m) * c

    ys_ref[0] = h_new.astype(ys_ref.dtype)
    if gates_ref is not None:
        gates_ref[0, :, :H] = i.astype(gates_ref.dtype)
        gates_ref[0, :, H:2 * H] = f.astype(gates_ref.dtype)
        gates_ref[0, :, 2 * H:3 * H] = g.astype(gates_ref.dtype)
        gates_ref[0, :, 3 * H:] = o.astype(gates_ref.dtype)
        cseq_ref[0] = c_new.astype(cseq_ref.dtype)  # CARRIED cell (masked)
    h_scr[:] = h_new
    c_scr[:] = c_new

    @pl.when(t == n_t - 1)
    def _():
        hT_ref[:] = h_new.astype(hT_ref.dtype)
        cT_ref[:] = c_new.astype(cT_ref.dtype)


def _graves_fwd(zx, w_rec, peep, h0, c0, mask, save_residuals):
    t, b, h4 = zx.shape
    h = h4 // 4
    dtype = zx.dtype
    out_shape = [
        jax.ShapeDtypeStruct((t, b, h), dtype),
        jax.ShapeDtypeStruct((b, h), dtype),
        jax.ShapeDtypeStruct((b, h), dtype),
    ]
    out_specs = [
        pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
        pl.BlockSpec((b, h), lambda i: (0, 0)),
        pl.BlockSpec((b, h), lambda i: (0, 0)),
    ]
    if save_residuals:
        out_shape += [
            jax.ShapeDtypeStruct((t, b, h4), dtype),
            jax.ShapeDtypeStruct((t, b, h), dtype),
        ]
        out_specs += [
            pl.BlockSpec((1, b, h4), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
        ]
    kernel = functools.partial(_fwd_kernel, hidden=h)
    if not save_residuals:
        kernel = functools.partial(
            lambda *refs, hidden: _fwd_kernel(
                *refs[:9], None, None, *refs[9:], hidden=hidden),
            hidden=h)
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, h4), lambda i: (i, 0, 0)),   # zx_t
            pl.BlockSpec((h, h4), lambda i: (0, 0)),         # W_rec (pinned)
            # peepholes pre-broadcast to (B, 3H) outside: Mosaic cannot
            # broadcast a lane-offset slice of a (1, 3H) vreg to (B, H)
            pl.BlockSpec((b, 3 * h), lambda i: (0, 0)),      # peepholes (pinned)
            pl.BlockSpec((b, h), lambda i: (0, 0)),          # h0
            pl.BlockSpec((b, h), lambda i: (0, 0)),          # c0
            # (T, 1, B) layout: Mosaic requires the last two block dims
            # to tile (8, 128) or equal the array dims — (1, B) of a (T, B)
            # array does neither, (1, 1, B) of (T, 1, B) does
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0)),    # mask_t
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),
            pltpu.VMEM((b, h), jnp.float32),
        ],
        interpret=_interpret(),
    )(zx, w_rec, jnp.broadcast_to(peep.reshape(1, 3 * h), (b, 3 * h)),
      h0, c0, mask.reshape(t, 1, b))
    if save_residuals:
        ys, hT, cT, gates, cseq = res
        return ys, hT, cT, (gates, cseq)
    ys, hT, cT = res
    return ys, hT, cT, None


# ---------------------------------------------------------------- backward
def _bwd_kernel(dys_ref, dhT_ref, dcT_ref, gates_ref, cprev_ref, mask_ref,
                wrecT_ref, peep_ref,
                ds_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr, *, hidden: int):
    """Reverse-time step (grid index counts backward)."""
    i_step = pl.program_id(0)
    n_t = pl.num_programs(0)
    H = hidden

    @pl.when(i_step == 0)
    def _():
        dh_scr[:] = dhT_ref[:].astype(jnp.float32)
        dc_scr[:] = dcT_ref[:].astype(jnp.float32)

    gates = gates_ref[0].astype(jnp.float32)
    i_g = gates[:, :H]
    f_g = gates[:, H:2 * H]
    g_g = gates[:, 2 * H:3 * H]
    o_g = gates[:, 3 * H:]
    c_prev = cprev_ref[0].astype(jnp.float32)
    c_til = f_g * c_prev + i_g * g_g
    tanh_c = jnp.tanh(c_til)
    p = peep_ref[:].astype(jnp.float32)
    m = mask_ref[0, 0].astype(jnp.float32)[:, None]

    dh_tot = dh_scr[:] + dys_ref[0].astype(jnp.float32)
    dc_tot = dc_scr[:]
    dh_til = m * dh_tot
    dc_til = m * dc_tot

    do = dh_til * tanh_c * o_g * (1.0 - o_g)
    dc_til = dc_til + dh_til * o_g * (1.0 - tanh_c * tanh_c) \
        + do * p[:, 2 * H:]
    di = dc_til * g_g * i_g * (1.0 - i_g)
    df = dc_til * c_prev * f_g * (1.0 - f_g)
    dg = dc_til * i_g * (1.0 - g_g * g_g)

    in_dtype = ds_ref.dtype
    ds_ref[0, :, :H] = di.astype(in_dtype)
    ds_ref[0, :, H:2 * H] = df.astype(in_dtype)
    ds_ref[0, :, 2 * H:3 * H] = dg.astype(in_dtype)
    ds_ref[0, :, 3 * H:] = do.astype(in_dtype)
    ds = ds_ref[0]
    dh_scr[:] = jax.lax.dot(ds, wrecT_ref[:],
                            preferred_element_type=jnp.float32) \
        + (1.0 - m) * dh_tot
    dc_scr[:] = dc_til * f_g + di * p[:, :H] + df * p[:, H:2 * H] \
        + (1.0 - m) * dc_tot

    @pl.when(i_step == n_t - 1)
    def _():
        dh0_ref[:] = dh_scr[:].astype(dh0_ref.dtype)
        dc0_ref[:] = dc_scr[:].astype(dc0_ref.dtype)


def _graves_bwd_kernel_call(dys, dhT, dcT, gates, c_prev_seq, mask, w_rec,
                            peep):
    t, b, h4 = gates.shape
    h = h4 // 4
    dtype = gates.dtype
    w_rec_t = w_rec.T
    rev3 = lambda i: (t - 1 - i, 0, 0)  # noqa: E731
    ds, dh0, dc0 = pl.pallas_call(
        functools.partial(_bwd_kernel, hidden=h),
        out_shape=[
            jax.ShapeDtypeStruct((t, b, h4), dtype),
            jax.ShapeDtypeStruct((b, h), dtype),
            jax.ShapeDtypeStruct((b, h), dtype),
        ],
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, h), rev3),                   # dys_t
            pl.BlockSpec((b, h), lambda i: (0, 0)),          # dhT
            pl.BlockSpec((b, h), lambda i: (0, 0)),          # dcT
            pl.BlockSpec((1, b, h4), rev3),                  # gates_t
            pl.BlockSpec((1, b, h), rev3),                   # c_{t-1}
            pl.BlockSpec((1, 1, b), lambda i: (t - 1 - i, 0, 0)),  # mask_t
            pl.BlockSpec((h4, h), lambda i: (0, 0)),         # W_rec^T
            pl.BlockSpec((b, 3 * h), lambda i: (0, 0)),      # peepholes
        ],
        out_specs=[
            pl.BlockSpec((1, b, h4), rev3),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),
            pltpu.VMEM((b, h), jnp.float32),
        ],
        interpret=_interpret(),
    )(dys, dhT, dcT, gates, c_prev_seq, mask.reshape(t, 1, b), w_rec_t,
      jnp.broadcast_to(peep.reshape(1, 3 * h), (b, 3 * h)))
    return ds, dh0, dc0


# ------------------------------------------------------------- public VJP
@jax.custom_vjp
def fused_graves_lstm(zx, w_rec, peep, h0, c0, mask):
    """Peephole+masked fused recurrence. ``zx`` (T, B, 4H) hoisted input
    projection, ``peep`` (3H,), ``mask`` (T, B) with 1.0 = real step.
    Returns ``(ys, hT, cT)``; check :func:`fused_graves_lstm_compatible`."""
    ys, hT, cT, _ = _graves_fwd(zx, w_rec, peep, h0, c0, mask,
                                save_residuals=False)
    return ys, hT, cT


def _vjp_fwd(zx, w_rec, peep, h0, c0, mask):
    ys, hT, cT, (gates, cseq) = _graves_fwd(zx, w_rec, peep, h0, c0, mask,
                                            save_residuals=True)
    return (ys, hT, cT), (ys, gates, cseq, w_rec, peep, h0, c0, mask)


def _vjp_bwd(res, cotangents):
    dys, dhT, dcT = cotangents
    ys, gates, cseq, w_rec, peep, h0, c0, mask = res
    h = h0.shape[-1]
    c_prev = jnp.concatenate([c0[None].astype(cseq.dtype), cseq[:-1]], axis=0)
    ds, dh0, dc0 = _graves_bwd_kernel_call(dys, dhT, dcT, gates, c_prev,
                                           mask, w_rec, peep)
    h_prev = jnp.concatenate([h0[None].astype(ys.dtype), ys[:-1]], axis=0)
    hp = h_prev.reshape(-1, h)
    dsf = ds.reshape(-1, 4 * h)
    dw_rec = jax.lax.dot_general(
        hp, dsf, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w_rec.dtype)
    # Peephole grads: three fused (T,B,H) reductions outside the kernel.
    dsf32 = ds.astype(jnp.float32)
    cpf = c_prev.astype(jnp.float32)
    i_g = gates[..., :h].astype(jnp.float32)
    f_g = gates[..., h:2 * h].astype(jnp.float32)
    g_g = gates[..., 2 * h:3 * h].astype(jnp.float32)
    c_til = f_g * cpf + i_g * g_g
    dp_i = jnp.sum(dsf32[..., :h] * cpf, axis=(0, 1))
    dp_f = jnp.sum(dsf32[..., h:2 * h] * cpf, axis=(0, 1))
    dp_o = jnp.sum(dsf32[..., 3 * h:] * c_til, axis=(0, 1))
    dpeep = jnp.concatenate([dp_i, dp_f, dp_o]).astype(peep.dtype)
    return ds, dw_rec, dpeep, dh0, dc0, jnp.zeros_like(mask)


fused_graves_lstm.defvjp(_vjp_fwd, _vjp_bwd)
