"""Fused multi-head attention for SHORT sequences (BERT-class T <= 512).

.. deprecated:: round 6
   This kernel has no winning regime and is kept only as a measured
   negative result (BASELINE.md round-6 update; VERDICT r5 weak #2). The
   round-4 "4x vs XLA in isolation" figure was a single-shot per-call wall
   timing through the remote tunnel, which charges the multi-op XLA
   reference one dispatch per op but the single-kernel Pallas path one
   total — the bench-of-record chain-amortised A/B
   (``verify_kernels``, ``short_attn_isolated_speedup_vs_xla``) reads
   **parity** (0.98-1.01 across rounds), and auto-routing it in-model was
   a measured LOSS (51-55 ms/step vs 37 for BERT-base: each pallas_call
   boundary in the big traced step costs ~0.5-0.7 ms of lost fusion/async
   overlap, x24 sites). Nothing routes to it; correctness tests and the
   bench row remain so the record stays auditable. Use the XLA softmax
   path (``nn.attention_layers.dot_product_attention``) at short T and the
   flash kernel beyond ``MIN_SEQ_FOR_KERNEL``.

The flash kernel (``flash_attention.py``) exists for long sequences where
the (T, T) score matrix cannot live on chip; below ``MIN_SEQ_FOR_KERNEL``
it loses to XLA and bows out. But the XLA path it bows out TO is itself
slow at short T: profiled on v5e at BERT-base fine-tune shape
(64x128, 12 heads, d=64), the six per-layer batched attention matmuls run
as 72 standalone ``convolution`` ops at ~5% MXU utilisation (5.6 ms of a
32 ms step) plus layout copies for the (b,h,t,d) transposes and the saved
softmax tensor.

This kernel owns the whole short-T case: one grid step per BATCH ROW
processes ALL heads of that row — q/k/v blocks (H, T, d) live entirely in
VMEM, scores are computed per-head with a batched ``dot_general``, the
softmax never touches HBM, and the backward saves NOTHING: it re-reads
q/k/v, recomputes scores and probabilities, and emits dq/dk/dv in a single
kernel (the per-row correction ``ds = p * (dp - rowsum(dp*p))`` needs no
forward output, so there is no lse/delta residual either — T fits, so the
softmax is exact, not streaming).

Reference role: the cuDNN fused-attention helper layer
(``org.deeplearning4j.cuda`` attention helpers; SURVEY.md §7.2), built
TPU-first for the MXU + VMEM regime instead of translated.

Numerics: scores/softmax in f32 (same as the XLA path's effective
accumulation), output in the input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deeplearning4j_tpu.ops.pallas.common import VMEM_BUDGET
from deeplearning4j_tpu.ops.pallas.common import interpret_mode as _interpret

MASK_VALUE = -1e30
MAX_SEQ = 512  # beyond this the streaming flash kernel takes over


def _scores(q, k, scale):
    # (H, Tq, d) x (H, Tk, d) -> (H, Tq, Tk), f32 accumulation on the MXU
    return jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale):
    s = _scores(q_ref[0], k_ref[0], scale)
    if bias_ref is not None:
        s = s + bias_ref[0][None]
    p = jax.nn.softmax(s, axis=-1)
    o = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                            (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    o_ref[0] = o.astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref,
                dq_ref, dk_ref, dv_ref, *, scale):
    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s = _scores(q, k, scale)
    if bias_ref is not None:
        s = s + bias_ref[0][None]
    p = jax.nn.softmax(s, axis=-1)                      # (H, Tq, Tk) f32
    pc = p.astype(do.dtype)
    # dv = p^T @ do   -> (H, Tk, d)
    dv = jax.lax.dot_general(pc, do, (((1,), (1,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    # dp = do @ v^T   -> (H, Tq, Tk)
    dp = jax.lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True)) * scale
    dsc = ds.astype(q.dtype)
    # dq = ds @ k     -> (H, Tq, d)
    dq = jax.lax.dot_general(dsc, k, (((2,), (1,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    # dk = ds^T @ q   -> (H, Tk, d)
    dk = jax.lax.dot_general(dsc, q, (((1,), (1,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    dq_ref[0] = dq.astype(dq_ref.dtype)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bias_from_mask(mask, b, t):
    """(b, t_k) key-padding mask -> additive f32 bias, or None."""
    if mask is None:
        return None
    m = mask
    if m.ndim == 4:  # (b, 1, 1, t) broadcast form
        m = m[:, 0, 0, :]
    m = m.astype(bool)
    # (b, 1, t): Mosaic wants the last two block dims 8/128-divisible or
    # full; a (1, 1, t) block over (b, 1, t) satisfies that exactly
    return jnp.where(m, 0.0, MASK_VALUE).astype(jnp.float32)[:, None, :]


def short_attention_compatible(q, k, v, mask=None, causal: bool = False) -> bool:
    """(b, h, t, d) self-attention, t_q == t_k <= MAX_SEQ, d a multiple of
    64, whole (h, t, t) score block fitting in VMEM."""
    if causal:
        return False  # short-T causal stays on XLA (decode shapes vary)
    if q.ndim != 4 or q.shape != k.shape or k.shape != v.shape:
        return False
    b, h, t, d = q.shape
    if t > MAX_SEQ or t % 128 != 0 or d % 64 != 0:
        return False
    if mask is not None:
        m = mask
        if m.ndim == 4:
            if m.shape != (b, 1, 1, t):
                return False
        elif m.shape != (b, t):
            return False
    if not _interpret():
        try:
            if jax.default_backend() not in ("tpu", "axon"):
                return False
        except Exception:
            return False
    # VMEM: q/k/v/o + do/dq/dk/dv plus ~4 f32 (h,t,t) temporaries
    need = 8 * h * t * d * q.dtype.itemsize + 4 * h * t * t * 4
    return need < VMEM_BUDGET


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def short_attention(q, k, v, mask=None, scale: float | None = None):
    """softmax(q k^T * scale + mask) v for (b, h, t, d), t <= MAX_SEQ."""
    y, _ = _short_fwd(q, k, v, mask, scale)
    return y


def _specs(b, h, t, d, with_bias):
    qspec = pl.BlockSpec((1, h, t, d), lambda i: (i, 0, 0, 0))
    bspec = pl.BlockSpec((1, 1, t), lambda i: (i, 0, 0)) if with_bias else None
    return qspec, bspec


def _short_fwd(q, k, v, mask, scale):
    b, h, t, d = q.shape
    scale = float(scale) if scale is not None else float(d) ** -0.5
    bias = _bias_from_mask(mask, b, t)
    qspec, bspec = _specs(b, h, t, d, bias is not None)
    in_specs = [qspec, qspec, qspec] + ([bspec] if bias is not None else [])
    args = (q, k, v) + ((bias,) if bias is not None else ())
    kern = _fwd_kernel if bias is not None else \
        (lambda q_ref, k_ref, v_ref, o_ref, *, scale:
         _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, scale=scale))
    y = pl.pallas_call(
        functools.partial(kern, scale=scale),
        grid=(b,),
        in_specs=in_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(*args)
    return y, (q, k, v, mask)


def _short_fwd_vjp(q, k, v, mask, scale):
    return _short_fwd(q, k, v, mask, scale)


def _short_bwd_vjp(scale, res, gy):
    q, k, v, mask = res
    b, h, t, d = q.shape
    sc = float(scale) if scale is not None else float(d) ** -0.5
    bias = _bias_from_mask(mask, b, t)
    qspec, bspec = _specs(b, h, t, d, bias is not None)
    in_specs = [qspec, qspec, qspec] + \
        ([bspec] if bias is not None else []) + [qspec]
    args = (q, k, v) + ((bias,) if bias is not None else ()) + (gy,)
    kern = _bwd_kernel if bias is not None else \
        (lambda q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref, *, scale:
         _bwd_kernel(q_ref, k_ref, v_ref, None, do_ref,
                     dq_ref, dk_ref, dv_ref, scale=scale))
    dq, dk, dv = pl.pallas_call(
        functools.partial(kern, scale=sc),
        grid=(b,),
        in_specs=in_specs,
        out_specs=(qspec, qspec, qspec),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),) * 3,
        interpret=_interpret(),
    )(*args)
    return dq, dk, dv, None


short_attention.defvjp(_short_fwd_vjp, _short_bwd_vjp)


# ---------------------------------------------------------------------------
# Native-layout variant: q/k/v in (B, T, H*Dh) exactly as the QKV projections
# produce them. The (b,h,t,d) form above needs a transpose before the call
# and a 64-lane last dim (half-filled lane tiles, strided DMAs) — measured
# 13 ms/step SLOWER in-model despite the kernel itself being 4x faster than
# XLA in isolation. Here the block is (T, H*Dh) = lane-perfect, the head
# split happens in VMEM via static lane slices, and the output feeds the
# O-projection without any transpose either.
# ---------------------------------------------------------------------------


def _fwd_btd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale, heads):
    d = q_ref.shape[-1] // heads
    q, k, v = q_ref[0], k_ref[0], v_ref[0]
    for h in range(heads):
        sl = slice(h * d, (h + 1) * d)
        s = jax.lax.dot_general(q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0]
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        o_ref[0, :, sl] = jnp.dot(
            p, v[:, sl], preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _bwd_btd_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref,
                    dq_ref, dk_ref, dv_ref, *, scale, heads):
    d = q_ref.shape[-1] // heads
    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    for h in range(heads):
        sl = slice(h * d, (h + 1) * d)
        qh, kh, vh, doh = q[:, sl], k[:, sl], v[:, sl], do[:, sl]
        s = jax.lax.dot_general(qh, kh, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0]
        p = jax.nn.softmax(s, axis=-1)
        pc = p.astype(doh.dtype)
        dv = jax.lax.dot_general(pc, doh, (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(doh, vh, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True)) * scale
              ).astype(qh.dtype)
        dq_ref[0, :, sl] = jnp.dot(
            ds, kh, preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_ref[0, :, sl] = jax.lax.dot_general(
            ds, qh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)
        dv_ref[0, :, sl] = dv.astype(dv_ref.dtype)


def short_attention_btd_compatible(q, mask=None, heads: int = 0,
                                   causal: bool = False) -> bool:
    """(b, t, h*dh) layout eligibility."""
    if causal or q.ndim != 3 or heads <= 0:
        return False
    b, t, hd = q.shape
    if hd % heads or t > MAX_SEQ or t % 128 != 0:
        return False
    d = hd // heads
    if d % 64 != 0 or hd % 128 != 0:
        return False
    if mask is not None:
        m = mask
        if m.ndim == 4:
            if m.shape != (b, 1, 1, t):
                return False
        elif m.shape != (b, t):
            return False
    if not _interpret():
        try:
            if jax.default_backend() not in ("tpu", "axon"):
                return False
        except Exception:
            return False
    need = 8 * t * hd * q.dtype.itemsize + 6 * t * t * 4
    return need < VMEM_BUDGET


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def short_attention_btd(q, k, v, mask=None, heads: int = 12,
                        scale: float | None = None):
    """Multi-head attention on (b, t, h*dh) without ever forming the
    (b, h, t, d) transposed view."""
    y, _ = _btd_fwd(q, k, v, mask, heads, scale)
    return y


def _btd_specs(b, t, hd, with_bias):
    qspec = pl.BlockSpec((1, t, hd), lambda i: (i, 0, 0))
    bspec = pl.BlockSpec((1, 1, t), lambda i: (i, 0, 0)) if with_bias else None
    return qspec, bspec


def _btd_fwd(q, k, v, mask, heads, scale):
    b, t, hd = q.shape
    d = hd // heads
    sc = float(scale) if scale is not None else float(d) ** -0.5
    bias = _bias_from_mask(mask, b, t)
    qspec, bspec = _btd_specs(b, t, hd, bias is not None)
    in_specs = [qspec, qspec, qspec] + ([bspec] if bias is not None else [])
    args = (q, k, v) + ((bias,) if bias is not None else ())
    if bias is not None:
        kern = _fwd_btd_kernel
    else:
        def kern(q_ref, k_ref, v_ref, o_ref, *, scale, heads):
            return _fwd_btd_kernel(q_ref, k_ref, v_ref, None, o_ref,
                                   scale=scale, heads=heads)
    y = pl.pallas_call(
        functools.partial(kern, scale=sc, heads=heads),
        grid=(b,),
        in_specs=in_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(*args)
    return y, (q, k, v, mask)


def _btd_fwd_vjp(q, k, v, mask, heads, scale):
    return _btd_fwd(q, k, v, mask, heads, scale)


def _btd_bwd_vjp(heads, scale, res, gy):
    q, k, v, mask = res
    b, t, hd = q.shape
    d = hd // heads
    sc = float(scale) if scale is not None else float(d) ** -0.5
    bias = _bias_from_mask(mask, b, t)
    qspec, bspec = _btd_specs(b, t, hd, bias is not None)
    in_specs = [qspec, qspec, qspec] + \
        ([bspec] if bias is not None else []) + [qspec]
    args = (q, k, v) + ((bias,) if bias is not None else ()) + (gy,)
    if bias is not None:
        kern = _bwd_btd_kernel
    else:
        def kern(q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref, *,
                 scale, heads):
            return _bwd_btd_kernel(q_ref, k_ref, v_ref, None, do_ref,
                                   dq_ref, dk_ref, dv_ref,
                                   scale=scale, heads=heads)
    dq, dk, dv = pl.pallas_call(
        functools.partial(kern, scale=sc, heads=heads),
        grid=(b,),
        in_specs=in_specs,
        out_specs=(qspec, qspec, qspec),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),) * 3,
        interpret=_interpret(),
    )(*args)
    return dq, dk, dv, None


short_attention_btd.defvjp(_btd_fwd_vjp, _btd_bwd_vjp)
