"""Pallas TPU kernels — the framework's hand-written-kernel layer.

The reference implements its hot ops as C++/CUDA in libnd4j
(``libnd4j/include/ops/declarable/helpers/cuda/*``) with cuDNN fast paths.
The TPU equivalent: XLA emits fused code for almost everything; for the ops
where hand-scheduling beats XLA (flash attention's blockwise softmax, fused
dropout RNG), kernels live here, written with ``jax.experimental.pallas``
against the MXU/VMEM model (see /opt/skills/guides/pallas_guide.md).
"""
