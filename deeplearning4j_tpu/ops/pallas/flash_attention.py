"""Flash attention as a Pallas TPU kernel.

The hand-written-kernel layer of the framework (the role cuDNN's fused
attention / libnd4j's CUDA helpers play in the reference — SURVEY.md §7.2):
blockwise softmax with running max/denominator so the (T, T) score matrix is
never materialised in HBM. Q is tiled over the grid; K/V stream through VMEM
in BLOCK_K chunks with the classic flash update:

    m' = max(m, rowmax(S_blk))
    l' = l * e^{m-m'} + rowsum(e^{S_blk - m'})
    acc' = acc * e^{m-m'} + e^{S_blk - m'} @ V_blk

Backward is jax.custom_vjp with XLA recompute (standard softmax form) —
correct everywhere; a fused Pallas backward is a future optimisation.

Used automatically by ``nn.attention_layers.dot_product_attention`` when
shapes/platform allow; fall back is the XLA softmax form. Set
``DL4J_TPU_PALLAS_INTERPRET=1`` to run the kernel in interpreter mode on CPU
(test path).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK_Q = 128
BLOCK_K = 128


def _interpret() -> bool:
    return os.environ.get("DL4J_TPU_PALLAS_INTERPRET", "") == "1"


def flash_attention_compatible(q, k, v, mask=None) -> bool:
    """Kernel applicability: no mask (padding masks fall back to XLA),
    block-divisible sequence, head dim that tiles onto the MXU lanes."""
    if mask is not None:
        return False
    if q.ndim != 4:
        return False
    t_q, d = q.shape[2], q.shape[3]
    t_k = k.shape[2]
    if t_q % BLOCK_Q or t_k % BLOCK_K:
        return False
    if d > 256:
        return False
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    platform = jax.devices()[0].platform
    if platform in ("tpu", "axon") or _interpret():
        return True
    return False


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, block_k: int):
    q = q_ref[0].astype(jnp.float32)  # (BLOCK_Q, D)
    t_k = k_ref.shape[1]
    n_blocks = t_k // block_k

    def body(i, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ()))) * scale
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot(p, v_blk)
        return acc_new, m_new, l_new

    bq, d_v = q.shape[0], v_ref.shape[2]
    acc = jnp.zeros((bq, d_v), jnp.float32)
    m = jnp.full((bq,), -jnp.inf, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_blocks, body, (acc, m, l))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale):
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    qf = q.reshape(b * h, t_q, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, v.shape[-1])
    grid = (b * h, t_q // BLOCK_Q)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_k=BLOCK_K),
        out_shape=jax.ShapeDtypeStruct((b * h, t_q, vf.shape[-1]), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, t_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, t_k, vf.shape[-1]), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, vf.shape[-1]), lambda bh, qi: (bh, qi, 0)),
        interpret=_interpret(),
    )(qf, kf, vf)
    return out.reshape(b, h, t_q, vf.shape[-1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, scale):
    return _flash_fwd(q, k, v, scale)


def _flash_vjp_fwd(q, k, v, scale):
    return _flash_fwd(q, k, v, scale), (q, k, v)


def _flash_vjp_bwd(scale, res, g):
    q, k, v = res

    def ref_attn(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        w = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32)).astype(q.dtype)

    _, vjp = jax.vjp(ref_attn, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, mask=None):
    """(batch, heads, time, d) flash attention. ``mask`` must be None (check
    :func:`flash_attention_compatible` first)."""
    if mask is not None:
        raise ValueError("flash_attention kernel does not take a mask; "
                         "use the XLA fallback for masked attention")
    scale = 1.0 / float(q.shape[-1]) ** 0.5
    return _flash(q, k, v, scale)
