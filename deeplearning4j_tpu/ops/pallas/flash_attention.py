"""Flash attention as Pallas TPU kernels — forward AND fused backward, with
key-padding-mask and causal support.

The hand-written-kernel layer of the framework (the role cuDNN's fused
attention / libnd4j's CUDA helpers play in the reference — SURVEY.md §7.2):
blockwise softmax with running max/denominator so the (T, T) score matrix is
never materialised in HBM. Q is tiled over the grid; K/V stream through VMEM
in BLOCK_K chunks with the classic flash update:

    m' = max(m, rowmax(S_blk))
    l' = l * e^{m-m'} + rowsum(e^{S_blk - m'})
    acc' = acc * e^{m-m'} + e^{S_blk - m'} @ V_blk

The forward additionally emits the per-row logsumexp L = m + log(l), which
the backward uses to recompute P = exp(S - L) blockwise (never storing the
(T, T) matrix):

    D   = rowsum(dO * O)                  (precomputed, fused by XLA)
    dV += P^T @ dO
    dP  = dO @ V^T
    dS  = P * (dP - D) * scale
    dQ += dS @ K        (dq kernel: grid over query blocks)
    dK += dS^T @ Q      (dkv kernel: grid over key blocks)

Masking: a key-padding mask becomes an additive bias (0 / -1e30) of shape
(batch, T_k, 1) streamed per batch row (the grid runs over batch*heads; the
index map divides by heads so the bias is NOT materialised per head).
Sequence lengths: up to T=8192 the BACKWARD kernels keep the full K/V (dq
pass) and Q/dO (dkv pass) VMEM-resident per grid step; past that
(`BWD_CHUNK_THRESHOLD`) the round-5 CHUNKED backward kernels stream those
operands through VMEM in `BWD_CHUNK`-row chunks over a third grid
dimension, accumulating in f32 scratch that persists across the
sequential minor grid steps — single-chip fwd+bwd verified at T=16384,
D=64 on v5e. Longer contexts still shard across chips via ring attention
(parallel/ring_attention).

``causal=True`` masks the upper triangle AND skips fully-masked key blocks:
the forward/dq loops stop at the diagonal, the dk/dv loop starts there —
roughly halving the FLOPs, which XLA's dense softmax cannot do.

Used automatically by ``nn.attention_layers.dot_product_attention`` when
shapes/platform allow; fall back is the XLA softmax form. Set
``DL4J_TPU_PALLAS_INTERPRET=1`` to run the kernels in interpreter mode on
CPU (test path).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_Q = 512
BLOCK_K = 512
# Mosaic requires the last block dim to be 128-divisible or equal to the full
# array dim, so per-row residuals (logsumexp, delta) are stored lane-broadcast
# with a narrow trailing axis rather than as 1-D vectors.
RES_LANES = 8
# Large-but-finite mask value (the standard flash choice): -inf would poison
# the running max for fully-masked rows.
MASK_VALUE = -1e30

# Below this key length XLA's unfused softmax attention measures faster on
# v5e (the (T, T) scores still fit cache-friendly HBM tiles and the kernel's
# fixed overhead dominates): fwd+bwd speedup was 0.86x @T=128, 0.94x @512,
# 1.26x @2048, 1.40x @4096.
MIN_SEQ_FOR_KERNEL = 1024


from deeplearning4j_tpu.ops.pallas.common import interpret_mode as _interpret


def _pick_block(t: int, limit: int) -> int:
    """Largest 128-multiple <= limit that divides t (measured on v5e: 512
    beats 128 by ~2x — bigger tiles keep the MXU busy and amortise loop
    overhead; past 512 returns diminish and VMEM pressure grows)."""
    b = min(limit, t)
    while b > 128 and t % b:
        b -= 128
    return b


def _padding_mask_2d(mask, b: int, t_k: int):
    """Reduce a broadcastable attention mask to a (batch, t_k) key-padding
    mask, or None if it is not that shape family."""
    if mask is None:
        return None
    if mask.ndim == 2 and mask.shape == (b, t_k):
        return mask
    if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1 \
            and mask.shape[0] == b and mask.shape[3] == t_k:
        return mask[:, 0, 0, :]
    return None


def flash_attention_compatible(q, k, v, mask=None, causal: bool = False) -> bool:
    """Kernel applicability: key-padding masks only (other mask shapes fall
    back to XLA), block-divisible sequence, head dim that tiles onto the MXU
    lanes, and a key length long enough that the kernel beats XLA."""
    if q.ndim != 4:
        return False
    t_q, d = q.shape[2], q.shape[3]
    t_k = k.shape[2]
    if mask is not None and _padding_mask_2d(mask, q.shape[0], t_k) is None:
        return False
    if causal and t_q != t_k:
        return False
    if t_q % 128 or t_k % 128:  # adaptive blocks bottom out at 128
        return False
    if d > 256:
        return False
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if k.dtype != q.dtype or v.dtype != q.dtype:
        return False
    if _interpret():
        return True  # CPU test path exercises the kernel at any size
    if t_k < MIN_SEQ_FOR_KERNEL:
        return False
    platform = jax.devices()[0].platform
    return platform in ("tpu", "axon")


def _causal_hi(qi, block_q: int, block_k: int):
    """Number of key blocks needed for query block qi under causal masking."""
    return (qi * block_q + block_q + block_k - 1) // block_k


def _diag_mask(s, qi, i, block_q: int, block_k: int):
    """Apply the causal triangle inside a (block_q, block_k) score tile."""
    rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = i * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(cols <= rows, s, MASK_VALUE)


# ---------------------------------------------------------------- forward


def _fwd_kernel(*refs, scale: float, block_k: int, has_bias: bool,
                causal: bool, save_residuals: bool):
    if has_bias:
        q_ref, k_ref, v_ref, bias_ref = refs[:4]
        rest = refs[4:]
    else:
        q_ref, k_ref, v_ref = refs[:3]
        bias_ref = None
        rest = refs[3:]
    o_ref = rest[0]
    lse_ref = rest[1] if save_residuals else None

    # Matmul operands stay in the input dtype (bf16 on the fast path) so the
    # MXU runs at full rate; accumulation and softmax stats are f32.
    q = q_ref[0]  # (BLOCK_Q, D)
    in_dtype = q.dtype
    qi = pl.program_id(1)
    t_k = k_ref.shape[1]
    n_blocks = t_k // block_k
    block_q = q.shape[0]

    def body(i, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, pl.ds(i * block_k, block_k), 0][None, :]
        if causal:
            s = _diag_mask(s, qi, i, block_q, block_k)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot(
            p.astype(in_dtype), v_blk, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    bq, d_v = q.shape[0], v_ref.shape[2]
    acc = jnp.zeros((bq, d_v), jnp.float32)
    m = jnp.full((bq,), -jnp.inf, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)
    hi = _causal_hi(qi, block_q, block_k) if causal else n_blocks
    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc, m, l))
    l_safe = jnp.maximum(l, 1e-20)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    if lse_ref is not None:  # residuals only requested under differentiation
        lse = m + jnp.log(l_safe)
        lse_ref[0] = jax.lax.broadcast_in_dim(lse, (bq, RES_LANES), (0,))


def _flash_fwd(q, k, v, bias, scale, causal, has_bias, save_residuals=True):
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    d_v = v.shape[-1]
    qf = q.reshape(b * h, t_q, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, d_v)
    block_q = _pick_block(t_q, BLOCK_Q)
    block_k = _pick_block(t_k, BLOCK_K)
    grid = (b * h, t_q // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, t_k, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, t_k, d_v), lambda bh, qi: (bh, 0, 0)),
    ]
    args = [qf, kf, vf]
    if has_bias:
        # bias is (b, t_k, 1); the index map divides the grid's batch*heads
        # row by heads, so all heads of one batch share the same block.
        in_specs.append(
            pl.BlockSpec((1, t_k, 1), lambda bh, qi: (bh // h, 0, 0)))
        args.append(bias)
    out_shape = [jax.ShapeDtypeStruct((b * h, t_q, d_v), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, d_v), lambda bh, qi: (bh, qi, 0))]
    if save_residuals:
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, t_q, RES_LANES), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, RES_LANES), lambda bh, qi: (bh, qi, 0)))
    res = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_k=block_k,
                          has_bias=has_bias, causal=causal,
                          save_residuals=save_residuals),
        out_shape=out_shape,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        interpret=_interpret(),
    )(*args)
    out = res[0].reshape(b, h, t_q, d_v)
    return (out, res[1]) if save_residuals else (out, None)


# ---------------------------------------------------------------- backward


def _bwd_dq_kernel(*refs, scale: float, block_k: int, has_bias: bool,
                   causal: bool):
    if has_bias:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref = refs[:7]
        dq_ref = refs[7]
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
        bias_ref = None
        dq_ref = refs[6]
    q = q_ref[0]                              # (BQ, D)
    do = do_ref[0]                            # (BQ, Dv)
    in_dtype = q.dtype
    lse = lse_ref[0][:, 0]                    # (BQ,)
    delta = delta_ref[0][:, 0]                # (BQ,)
    qi = pl.program_id(1)
    t_k = k_ref.shape[1]
    n_blocks = t_k // block_k
    block_q = q.shape[0]

    def body(i, dq_acc):
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, pl.ds(i * block_k, block_k), 0][None, :]
        if causal:
            s = _diag_mask(s, qi, i, block_q, block_k)
        p = jnp.exp(s - lse[:, None])                       # (BQ, BK)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(in_dtype)
        return dq_acc + jax.lax.dot(ds, k_blk,
                                    preferred_element_type=jnp.float32)

    hi = _causal_hi(qi, block_q, block_k) if causal else n_blocks
    dq = jax.lax.fori_loop(0, hi,
                           body, jnp.zeros(q.shape, jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale: float, block_q: int, has_bias: bool,
                    causal: bool):
    if has_bias:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref = refs[:7]
        dk_ref, dv_ref = refs[7:9]
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
        bias_ref = None
        dk_ref, dv_ref = refs[6:8]
    k = k_ref[0]                              # (BK, D)
    v = v_ref[0]                              # (BK, Dv)
    in_dtype = k.dtype
    ki = pl.program_id(1)
    t_q = q_ref.shape[1]
    n_blocks = t_q // block_q
    block_k = k.shape[0]
    # this key block's bias column (shared across q blocks)
    bias_col = (bias_ref[0, pl.ds(ki * block_k, block_k), 0]
                if bias_ref is not None else None)

    def body(i, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse_blk = lse_ref[0, pl.ds(i * block_q, block_q), :][:, 0]
        delta_blk = delta_ref[0, pl.ds(i * block_q, block_q), :][:, 0]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if bias_col is not None:
            s = s + bias_col[None, :]
        if causal:
            s = _diag_mask(s, i, ki, block_q, block_k)
        p = jnp.exp(s - lse_blk[:, None])                   # (BQ, BK)
        p_cast = p.astype(in_dtype)
        dv_acc = dv_acc + jax.lax.dot_general(
            p_cast, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BK, Dv)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_blk[:, None]) * scale).astype(in_dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BK, D)
        return dk_acc, dv_acc

    # under causal masking, query blocks strictly above the diagonal
    # contribute nothing to this key block
    lo = (ki * block_k) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(
        lo, n_blocks, body,
        (jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# Above this sequence length the backward switches to the CHUNKED kernels:
# the single-chunk forms keep full K/V (dq pass) and full Q/dO (dkv pass)
# VMEM-resident per grid step, which blows the ~16 MB VMEM budget past
# T=8192; the chunked forms stream those operands through VMEM in
# BWD_CHUNK-row chunks via a third grid dimension, accumulating in f32
# scratch that persists across the (sequential) minor grid steps. The two
# kernel families are NOT unified into always-chunked (measured on v5e:
# chunked == resident at T=8192, 17.9 ms both, but causal T=2048 runs
# 6.3 vs 4.8 ms chunked — the 3-D grid + scratch structure costs ~30% at
# short causal lengths, so the resident forms stay for T <= threshold).
BWD_CHUNK_THRESHOLD = 8192
BWD_CHUNK = 4096


def _bwd_dq_kernel_chunked(*refs, scale: float, block_k: int,
                           has_bias: bool, causal: bool, n_chunks: int):
    """dq pass with K/V streamed in chunks: grid (bh, qi, ci); K/V blocks
    are the ci-th chunk; dq accumulates in scratch, flushed at the last
    chunk."""
    if has_bias:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref = refs[:7]
        dq_ref, acc_ref = refs[7], refs[8]
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
        bias_ref = None
        dq_ref, acc_ref = refs[6], refs[7]
    q = q_ref[0]
    do = do_ref[0]
    in_dtype = q.dtype
    lse = lse_ref[0][:, 0]
    delta = delta_ref[0][:, 0]
    qi = pl.program_id(1)
    ci = pl.program_id(2)
    chunk_k = k_ref.shape[1]
    nb = chunk_k // block_k
    block_q = q.shape[0]

    @pl.when(ci == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def body(i, dq_acc):
        kb = ci * nb + i  # global key-block index (for the causal mask)
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, pl.ds(i * block_k, block_k), 0][None, :]
        if causal:
            s = _diag_mask(s, qi, kb, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(in_dtype)
        return dq_acc + jax.lax.dot(ds, k_blk,
                                    preferred_element_type=jnp.float32)

    if causal:
        hi_global = _causal_hi(qi, block_q, block_k)
        nblk = jnp.clip(hi_global - ci * nb, 0, nb)
    else:
        nblk = nb
    acc_ref[...] = jax.lax.fori_loop(0, nblk, body, acc_ref[...])

    @pl.when(ci == n_chunks - 1)
    def _flush():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_chunked(*refs, scale: float, block_q: int,
                            has_bias: bool, causal: bool, n_chunks: int):
    """dk/dv pass with Q/dO/lse/delta streamed in chunks: grid
    (bh, ki, ci); scratch accumulators flushed at the last chunk."""
    if has_bias:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref = refs[:7]
        dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = refs[7:11]
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
        bias_ref = None
        dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = refs[6:10]
    k = k_ref[0]
    v = v_ref[0]
    in_dtype = k.dtype
    ki = pl.program_id(1)
    ci = pl.program_id(2)
    chunk_q = q_ref.shape[1]
    nb = chunk_q // block_q
    block_k = k.shape[0]
    bias_col = (bias_ref[0, :, 0] if bias_ref is not None else None)

    @pl.when(ci == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    def body(i, carry):
        dk_acc, dv_acc = carry
        qb = ci * nb + i  # global query-block index
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse_blk = lse_ref[0, pl.ds(i * block_q, block_q), :][:, 0]
        delta_blk = delta_ref[0, pl.ds(i * block_q, block_q), :][:, 0]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if bias_col is not None:
            s = s + bias_col[None, :]
        if causal:
            s = _diag_mask(s, qb, ki, block_q, block_k)
        p = jnp.exp(s - lse_blk[:, None])
        p_cast = p.astype(in_dtype)
        dv_acc = dv_acc + jax.lax.dot_general(
            p_cast, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_blk[:, None]) * scale).astype(in_dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    if causal:
        lo_global = (ki * block_k) // block_q
        lo = jnp.clip(lo_global - ci * nb, 0, nb)
    else:
        lo = 0
    dk, dv = jax.lax.fori_loop(lo, nb, body,
                               (dk_acc_ref[...], dv_acc_ref[...]))
    dk_acc_ref[...] = dk
    dv_acc_ref[...] = dv

    @pl.when(ci == n_chunks - 1)
    def _flush():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd_chunked(q, k, v, bias, out, lse, g, scale, causal, has_bias):
    """Backward for T > BWD_CHUNK_THRESHOLD: same math as ``_flash_bwd``,
    with the full-sequence operands streamed chunkwise (third grid dim)."""
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    d_v = v.shape[-1]
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    qf = q.reshape(b * h, t_q, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, d_v)
    dof = g.reshape(b * h, t_q, d_v)
    lsef = lse
    deltaf = jnp.broadcast_to(delta.reshape(b * h, t_q, 1),
                              (b * h, t_q, RES_LANES))
    block_q = _pick_block(t_q, BLOCK_Q)
    block_k = _pick_block(t_k, BLOCK_K)

    def _pick_chunk(t, block):
        # largest multiple of `block` <= BWD_CHUNK that divides t (the
        # kernels index sub-blocks inside the chunk, so block | chunk)
        c = (BWD_CHUNK // block) * block
        while c > block and t % c:
            c -= block
        return c

    chunk_k = _pick_chunk(t_k, block_k)
    chunk_q = _pick_chunk(t_q, block_q)
    n_chunks_k = t_k // chunk_k
    n_chunks_q = t_q // chunk_q

    if causal:
        # Steps whose whole K/V chunk lies above the causal diagonal are
        # compute-skipped in the kernel (nblk clips to 0) — ALSO skip
        # their DMA by re-mapping the chunk index to the last needed
        # chunk: consecutive grid steps with the same block index reuse
        # the resident block, so dead chunks are never fetched.
        def _k_chunk(bh, qi, ci):
            return (bh, jnp.minimum(ci, ((qi + 1) * block_q - 1) // chunk_k),
                    0)
    else:
        def _k_chunk(bh, qi, ci):
            return (bh, ci, 0)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ci: (bh, qi, 0)),
        pl.BlockSpec((1, chunk_k, d), _k_chunk),
        pl.BlockSpec((1, chunk_k, d_v), _k_chunk),
        pl.BlockSpec((1, block_q, d_v), lambda bh, qi, ci: (bh, qi, 0)),
        pl.BlockSpec((1, block_q, RES_LANES), lambda bh, qi, ci: (bh, qi, 0)),
        pl.BlockSpec((1, block_q, RES_LANES), lambda bh, qi, ci: (bh, qi, 0)),
    ]
    args = [qf, kf, vf, dof, lsef, deltaf]
    if has_bias:
        in_specs.append(
            pl.BlockSpec((1, chunk_k, 1),
                         lambda bh, qi, ci: (bh // h,) + _k_chunk(bh, qi, ci)[1:]))
        args.append(bias)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_chunked, scale=scale,
                          block_k=block_k, has_bias=has_bias, causal=causal,
                          n_chunks=n_chunks_k),
        out_shape=jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
        grid=(b * h, t_q // block_q, n_chunks_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ci: (bh, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*args)

    if causal:
        # mirror of the dq-pass DMA skip: query chunks strictly above the
        # diagonal for this key block re-map to the first needed chunk
        def _q_chunk(bh, ki, ci):
            return (bh, jnp.maximum(ci, (ki * block_k) // chunk_q), 0)
    else:
        def _q_chunk(bh, ki, ci):
            return (bh, ci, 0)
    in_specs_kv = [
        pl.BlockSpec((1, chunk_q, d), _q_chunk),
        pl.BlockSpec((1, block_k, d), lambda bh, ki, ci: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d_v), lambda bh, ki, ci: (bh, ki, 0)),
        pl.BlockSpec((1, chunk_q, d_v), _q_chunk),
        pl.BlockSpec((1, chunk_q, RES_LANES), _q_chunk),
        pl.BlockSpec((1, chunk_q, RES_LANES), _q_chunk),
    ]
    args_kv = [qf, kf, vf, dof, lsef, deltaf]
    if has_bias:
        in_specs_kv.append(
            pl.BlockSpec((1, block_k, 1), lambda bh, ki, ci: (bh // h, ki, 0)))
        args_kv.append(bias)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_chunked, scale=scale,
                          block_q=block_q, has_bias=has_bias, causal=causal,
                          n_chunks=n_chunks_q),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t_k, d_v), v.dtype),
        ],
        grid=(b * h, t_k // block_k, n_chunks_q),
        in_specs=in_specs_kv,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki, ci: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d_v), lambda bh, ki, ci: (bh, ki, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d_v), jnp.float32)],
        interpret=_interpret(),
    )(*args_kv)

    return (dq.reshape(b, h, t_q, d), dk.reshape(b, h, t_k, d),
            dv.reshape(b, h, t_k, d_v))


def _flash_bwd(q, k, v, bias, out, lse, g, scale, causal, has_bias):
    if max(q.shape[2], k.shape[2]) > BWD_CHUNK_THRESHOLD:
        return _flash_bwd_chunked(q, k, v, bias, out, lse, g, scale,
                                  causal, has_bias)
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    d_v = v.shape[-1]
    # D = rowsum(dO * O): cheap elementwise-reduce, fused by XLA, stored
    # lane-broadcast like lse (Mosaic block layout requirement).
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    qf = q.reshape(b * h, t_q, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, d_v)
    dof = g.reshape(b * h, t_q, d_v)
    lsef = lse  # already (b*h, t_q, RES_LANES) from the forward
    deltaf = jnp.broadcast_to(delta.reshape(b * h, t_q, 1),
                              (b * h, t_q, RES_LANES))
    block_q = _pick_block(t_q, BLOCK_Q)
    block_k = _pick_block(t_k, BLOCK_K)
    bias_spec_q = pl.BlockSpec((1, t_k, 1), lambda bh, qi: (bh // h, 0, 0))
    bias_spec_k = pl.BlockSpec((1, t_k, 1), lambda bh, ki: (bh // h, 0, 0))

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, t_k, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, t_k, d_v), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, block_q, d_v), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, block_q, RES_LANES), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, block_q, RES_LANES), lambda bh, qi: (bh, qi, 0)),
    ]
    args = [qf, kf, vf, dof, lsef, deltaf]
    if has_bias:
        in_specs.append(bias_spec_q)
        args.append(bias)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_k=block_k,
                          has_bias=has_bias, causal=causal),
        out_shape=jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
        grid=(b * h, t_q // block_q),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        interpret=_interpret(),
    )(*args)

    in_specs_kv = [
        pl.BlockSpec((1, t_q, d), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d_v), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, t_q, d_v), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, t_q, RES_LANES), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, t_q, RES_LANES), lambda bh, ki: (bh, 0, 0)),
    ]
    args_kv = [qf, kf, vf, dof, lsef, deltaf]
    if has_bias:
        in_specs_kv.append(bias_spec_k)
        args_kv.append(bias)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          has_bias=has_bias, causal=causal),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t_k, d_v), v.dtype),
        ],
        grid=(b * h, t_k // block_k),
        in_specs=in_specs_kv,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d_v), lambda bh, ki: (bh, ki, 0)),
        ],
        interpret=_interpret(),
    )(*args_kv)

    return (dq.reshape(b, h, t_q, d), dk.reshape(b, h, t_k, d),
            dv.reshape(b, h, t_k, d_v))


# ------------------------------------------------------------- public VJP


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, bias, scale, causal, has_bias):
    out, _ = _flash_fwd(q, k, v, bias, scale, causal, has_bias,
                        save_residuals=False)
    return out


def _flash_vjp_fwd(q, k, v, bias, scale, causal, has_bias):
    out, lse = _flash_fwd(q, k, v, bias, scale, causal, has_bias)
    return out, (q, k, v, bias, out, lse)


def _flash_vjp_bwd(scale, causal, has_bias, res, g):
    q, k, v, bias, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, bias, out, lse, g, scale, causal,
                            has_bias)
    return dq, dk, dv, jnp.zeros_like(bias)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, mask=None, causal: bool = False):
    """(batch, heads, time, d) flash attention. ``mask`` may be a key-padding
    mask of shape (batch, t_k) or (batch, 1, 1, t_k) — 1/True = attend (check
    :func:`flash_attention_compatible` first). ``causal=True`` applies the
    autoregressive triangle with diagonal block skipping."""
    b, t_k = q.shape[0], k.shape[2]
    kmask = _padding_mask_2d(mask, b, t_k)
    if mask is not None and kmask is None:
        raise ValueError("flash_attention supports key-padding masks only; "
                         "use the XLA fallback for other mask shapes")
    scale = 1.0 / float(q.shape[-1]) ** 0.5
    has_bias = kmask is not None
    if has_bias:
        bias = jnp.where(kmask.astype(bool), 0.0, MASK_VALUE)
        bias = bias.astype(jnp.float32)[:, :, None]  # (b, t_k, 1)
    else:
        bias = jnp.zeros((b, t_k, 1), jnp.float32)  # unused dummy
    return _flash(q, k, v, bias, scale, bool(causal), has_bias)
