"""Shared knobs for the Pallas kernel modules."""

from __future__ import annotations

import os

# Scoped-VMEM budget per core (v5e exposes 16 MB; leave headroom for
# Mosaic's own stack). Kernels gate their eligibility on fitting here.
VMEM_BUDGET = 15 * 1024 * 1024


def interpret_mode() -> bool:
    """CPU interpreter-mode test path (DL4J_TPU_PALLAS_INTERPRET=1)."""
    return os.environ.get("DL4J_TPU_PALLAS_INTERPRET", "") == "1"
