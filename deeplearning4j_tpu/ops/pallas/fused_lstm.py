"""Persistent fused LSTM as Pallas TPU kernels (forward AND backward).

The role cuDNN's fused LSTM (``CudnnLSTMHelper`` in the reference,
SURVEY.md §2.1/§7.2) plays on GPU, done the TPU way: the input projection
``x @ W + b`` for the WHOLE sequence is one big MXU matmul outside the
kernel (hoisted, as the scan path already does); the kernel then runs the
sequential recurrence with

- ``W_rec`` pinned in VMEM for the entire sequence (the scan path re-reads
  it from HBM every timestep — at H=512 that is 2 MB x T of pure HBM
  traffic this kernel eliminates),
- h/c carried in VMEM scratch across grid steps (TPU grids execute
  sequentially, so scratch persists from t to t+1),
- per-timestep inputs/outputs streamed through the grid pipeline
  (Pallas double-buffers the DMAs automatically).

The backward kernel runs the reverse-time recurrence producing the
per-step pre-activation gradients ``ds`` (and dh0/dc0); the weight/input
gradients are then three large MXU matmuls OUTSIDE the kernel:

    dzx    = ds                      (input-projection grad, streamed out)
    dW_rec = h_prev^T @ ds           (one (H, B*T) @ (B*T, 4H) matmul)
    dh0    = ds_0 @ W_rec^T          (computed in-kernel as the dh carry)

Gate order matches the layer convention [i, f, g, o]. Residuals saved for
backward: activated gates (T, B, 4H) and the cell sequence (T, B, H).

Applicability: default activations (sigmoid gates, tanh cell), no
per-timestep mask (masked sequences fall back to the scan path), shapes
aligned to TPU tiles. Set ``DL4J_TPU_PALLAS_INTERPRET=1`` to run in
interpreter mode on CPU (test path).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from deeplearning4j_tpu.ops.pallas.common import VMEM_BUDGET as _VMEM_BUDGET
from deeplearning4j_tpu.ops.pallas.common import interpret_mode as _interpret


def _vmem_bytes(b: int, h: int, itemsize: int) -> int:
    """Worst-case kernel VMEM footprint — the BACKWARD kernel is the larger
    one: pinned W_rec^T plus double-buffered per-step streams (dys, gates,
    c_prev, ds) plus the boundary blocks (dhT/dcT/dh0/dc0) and f32 dh/dc
    scratch."""
    w_rec = h * 4 * h * itemsize
    streams = 2 * (b * h + b * 4 * h + b * h + b * 4 * h) * itemsize
    boundary = 4 * b * h * itemsize
    scratch = 2 * b * h * 4
    return w_rec + streams + boundary + scratch


def fused_lstm_compatible(zx, h0) -> bool:
    """Kernel applicability for ``(T, B, 4H)`` projected inputs and ``(B, H)``
    initial state: tile-aligned B/H, supported dtype, pinned weights within
    the VMEM budget, TPU (or interpreter)."""
    if zx.ndim != 3 or h0.ndim != 2:
        return False
    t, b, h4 = zx.shape
    h = h0.shape[1]
    if h4 != 4 * h:
        return False
    if b % 8 or h % 128:
        return False
    # Below ~T=32 the fixed kernel launch/DMA cost loses to the plain scan
    # (measured on v5e: 0.80x @T=4, 0.88x @16, 1.17x @64) — and T=1 is the
    # latency-critical rnnTimeStep path.
    if t < 32 and not _interpret():
        return False
    if zx.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if _vmem_bytes(b, h, jnp.dtype(zx.dtype).itemsize) > _VMEM_BUDGET:
        return False
    if _interpret():
        return True
    platform = jax.devices()[0].platform
    return platform in ("tpu", "axon")


# ---------------------------------------------------------------- forward


def _fwd_kernel(zx_ref, wrec_ref, h0_ref, c0_ref,
                ys_ref, hT_ref, cT_ref, gates_ref, cseq_ref,
                h_scr, c_scr, *, hidden: int):
    t = pl.program_id(0)
    n_t = pl.num_programs(0)
    H = hidden

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    c = c_scr[:]
    in_dtype = zx_ref.dtype
    z = zx_ref[0].astype(jnp.float32) + jax.lax.dot(
        h.astype(in_dtype), wrec_ref[:],
        preferred_element_type=jnp.float32)
    i = jax.nn.sigmoid(z[:, :H])
    f = jax.nn.sigmoid(z[:, H:2 * H])
    g = jnp.tanh(z[:, 2 * H:3 * H])
    o = jax.nn.sigmoid(z[:, 3 * H:])
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)

    ys_ref[0] = h_new.astype(ys_ref.dtype)
    if gates_ref is not None:
        # sliced writes (no in-kernel concatenate — that is a VPU copy)
        gates_ref[0, :, :H] = i.astype(gates_ref.dtype)
        gates_ref[0, :, H:2 * H] = f.astype(gates_ref.dtype)
        gates_ref[0, :, 2 * H:3 * H] = g.astype(gates_ref.dtype)
        gates_ref[0, :, 3 * H:] = o.astype(gates_ref.dtype)
        cseq_ref[0] = c_new.astype(cseq_ref.dtype)
    h_scr[:] = h_new
    c_scr[:] = c_new

    @pl.when(t == n_t - 1)
    def _():
        hT_ref[:] = h_new.astype(hT_ref.dtype)
        cT_ref[:] = c_new.astype(cT_ref.dtype)


def _lstm_fwd(zx, w_rec, h0, c0, save_residuals):
    t, b, h4 = zx.shape
    h = h4 // 4
    dtype = zx.dtype
    out_shape = [
        jax.ShapeDtypeStruct((t, b, h), dtype),      # ys
        jax.ShapeDtypeStruct((b, h), dtype),         # hT
        jax.ShapeDtypeStruct((b, h), dtype),         # cT
    ]
    out_specs = [
        pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
        pl.BlockSpec((b, h), lambda i: (0, 0)),
        pl.BlockSpec((b, h), lambda i: (0, 0)),
    ]
    if save_residuals:
        out_shape += [
            jax.ShapeDtypeStruct((t, b, h4), dtype),  # activated gates
            jax.ShapeDtypeStruct((t, b, h), dtype),   # cell sequence
        ]
        out_specs += [
            pl.BlockSpec((1, b, h4), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
        ]
    kernel = functools.partial(_fwd_kernel, hidden=h)
    if not save_residuals:
        kernel = functools.partial(
            lambda *refs, hidden: _fwd_kernel(
                *refs[:7], None, None, *refs[7:], hidden=hidden),
            hidden=h)
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, h4), lambda i: (i, 0, 0)),   # zx_t
            pl.BlockSpec((h, h4), lambda i: (0, 0)),         # W_rec (pinned)
            pl.BlockSpec((b, h), lambda i: (0, 0)),          # h0
            pl.BlockSpec((b, h), lambda i: (0, 0)),          # c0
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),
            pltpu.VMEM((b, h), jnp.float32),
        ],
        interpret=_interpret(),
    )(zx, w_rec, h0, c0)
    if save_residuals:
        ys, hT, cT, gates, cseq = res
        return ys, hT, cT, (gates, cseq)
    ys, hT, cT = res
    return ys, hT, cT, None


# ---------------------------------------------------------------- backward


def _bwd_kernel(dys_ref, dhT_ref, dcT_ref, gates_ref, cprev_ref, wrecT_ref,
                ds_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr, *, hidden: int):
    """Reverse-time step (grid index i counts BACKWARD: t = T-1-i)."""
    i_step = pl.program_id(0)
    n_t = pl.num_programs(0)
    H = hidden

    @pl.when(i_step == 0)
    def _():
        dh_scr[:] = dhT_ref[:].astype(jnp.float32)
        dc_scr[:] = dcT_ref[:].astype(jnp.float32)

    gates = gates_ref[0].astype(jnp.float32)
    i_g = gates[:, :H]
    f_g = gates[:, H:2 * H]
    g_g = gates[:, 2 * H:3 * H]
    o_g = gates[:, 3 * H:]
    c_prev = cprev_ref[0].astype(jnp.float32)
    # c_t rebuilt from the saved residuals instead of re-streaming cseq:
    c_t = f_g * c_prev + i_g * g_g
    tanh_c = jnp.tanh(c_t)

    dh = dh_scr[:] + dys_ref[0].astype(jnp.float32)
    dc = dc_scr[:] + dh * o_g * (1.0 - tanh_c * tanh_c)

    di = dc * g_g * i_g * (1.0 - i_g)
    df = dc * c_prev * f_g * (1.0 - f_g)
    dg = dc * i_g * (1.0 - g_g * g_g)
    do = dh * tanh_c * o_g * (1.0 - o_g)

    in_dtype = ds_ref.dtype
    ds_ref[0, :, :H] = di.astype(in_dtype)
    ds_ref[0, :, H:2 * H] = df.astype(in_dtype)
    ds_ref[0, :, 2 * H:3 * H] = dg.astype(in_dtype)
    ds_ref[0, :, 3 * H:] = do.astype(in_dtype)
    ds = ds_ref[0]
    dh_scr[:] = jax.lax.dot(ds, wrecT_ref[:],
                            preferred_element_type=jnp.float32)
    dc_scr[:] = dc * f_g

    @pl.when(i_step == n_t - 1)
    def _():
        dh0_ref[:] = dh_scr[:].astype(dh0_ref.dtype)
        dc0_ref[:] = dc_scr[:].astype(dc0_ref.dtype)


def _lstm_bwd_kernel_call(dys, dhT, dcT, gates, c_prev_seq, w_rec):
    t, b, h4 = gates.shape
    h = h4 // 4
    dtype = gates.dtype
    w_rec_t = w_rec.T  # (4H, H); one transpose outside the loop
    rev = lambda i: (t - 1 - i, 0, 0)  # noqa: E731 — reverse-time index map
    ds, dh0, dc0 = pl.pallas_call(
        functools.partial(_bwd_kernel, hidden=h),
        out_shape=[
            jax.ShapeDtypeStruct((t, b, h4), dtype),
            jax.ShapeDtypeStruct((b, h), dtype),
            jax.ShapeDtypeStruct((b, h), dtype),
        ],
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, h), rev),                    # dys_t
            pl.BlockSpec((b, h), lambda i: (0, 0)),          # dhT
            pl.BlockSpec((b, h), lambda i: (0, 0)),          # dcT
            pl.BlockSpec((1, b, h4), rev),                   # gates_t
            pl.BlockSpec((1, b, h), rev),                    # c_{t-1}
            pl.BlockSpec((h4, h), lambda i: (0, 0)),         # W_rec^T (pinned)
        ],
        out_specs=[
            pl.BlockSpec((1, b, h4), rev),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),
            pltpu.VMEM((b, h), jnp.float32),
        ],
        interpret=_interpret(),
    )(dys, dhT, dcT, gates, c_prev_seq, w_rec_t)
    return ds, dh0, dc0


# ------------------------------------------------------------- public VJP


@jax.custom_vjp
def fused_lstm(zx, w_rec, h0, c0):
    """Run the fused recurrence. ``zx`` is the hoisted input projection
    ``x @ W + b`` laid out (T, B, 4H); returns ``(ys, hT, cT)`` with ys
    (T, B, H). Check :func:`fused_lstm_compatible` first."""
    ys, hT, cT, _ = _lstm_fwd(zx, w_rec, h0, c0, save_residuals=False)
    return ys, hT, cT


def _fused_lstm_vjp_fwd(zx, w_rec, h0, c0):
    ys, hT, cT, (gates, cseq) = _lstm_fwd(zx, w_rec, h0, c0,
                                          save_residuals=True)
    return (ys, hT, cT), (ys, gates, cseq, w_rec, h0, c0)


def _fused_lstm_vjp_bwd(res, cotangents):
    dys, dhT, dcT = cotangents
    ys, gates, cseq, w_rec, h0, c0 = res
    t = gates.shape[0]
    # c_{t-1} sequence: c0 then cseq[:-1]
    c_prev = jnp.concatenate([c0[None], cseq[:-1]], axis=0)
    ds, dh0, dc0 = _lstm_bwd_kernel_call(dys, dhT, dcT, gates, c_prev, w_rec)
    # Weight gradient as ONE large MXU matmul: h_{t-1} sequence is h0 ++ ys[:-1].
    h_prev = jnp.concatenate([h0[None], ys[:-1]], axis=0)
    hp = h_prev.reshape(-1, h_prev.shape[-1])
    dsf = ds.reshape(-1, ds.shape[-1])
    dw_rec = jax.lax.dot_general(
        hp, dsf, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w_rec.dtype)
    return ds, dw_rec, dh0, dc0


fused_lstm.defvjp(_fused_lstm_vjp_fwd, _fused_lstm_vjp_bwd)
