"""Op library: activations, losses, initializers, and Pallas TPU kernels.

TPU-native replacement for the reference's op stack: libnd4j's enumerated
transform/reduce loops and ~500 declarable ops become jax.numpy/lax programs
fused by XLA; the cuDNN/oneDNN platform helpers become XLA conv/rnn emitters;
ops XLA fuses poorly get hand-written Pallas kernels under ``ops.pallas``.
"""

from deeplearning4j_tpu.ops.activations import Activation, get_activation
from deeplearning4j_tpu.ops.initializers import WeightInit, init_weights
from deeplearning4j_tpu.ops.losses import LossFunction, get_loss

__all__ = [
    "Activation",
    "get_activation",
    "WeightInit",
    "init_weights",
    "LossFunction",
    "get_loss",
]
