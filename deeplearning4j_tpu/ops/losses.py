"""Loss functions.

Covers the reference's ``LossFunctions.LossFunction`` set (upstream
``org.nd4j.linalg.lossfunctions.impl.*``): MCXENT, XENT, MSE, L1, L2, MAE,
NEGATIVELOGLIKELIHOOD, HINGE, SQUARED_HINGE, POISSON, COSINE_PROXIMITY,
KL_DIVERGENCE, MSLE, plus per-example weighting and sequence masks.

Conventions (matching the reference for loss parity, SURVEY.md §7.5):
- Loss is averaged over the minibatch (DL4J "score" divides by examples).
- Per-output losses sum over the output dimension, then average over examples.
- Masks zero out masked timesteps AND renormalise by the mask sum.
- MCXENT expects probabilities after softmax; here each loss takes
  (labels, preoutput, activation_fn) and fuses the activation so we can use
  the numerically-stable logsumexp forms under jit.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.activations import get_activation

_EPS = 1e-7


class LossFunction(str, enum.Enum):
    MCXENT = "mcxent"
    XENT = "xent"
    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    MAE = "mae"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    POISSON = "poisson"
    COSINE_PROXIMITY = "cosine_proximity"
    KL_DIVERGENCE = "kl_divergence"
    MSLE = "msle"
    SPARSE_MCXENT = "sparse_mcxent"


def _apply_activation(preout, activation):
    return get_activation(activation)(preout) if activation is not None else preout


def _per_example(loss_per_elem, mask):
    """Sum per-output losses -> per-example (or per-timestep) scalar, apply mask."""
    per_ex = jnp.sum(loss_per_elem, axis=-1)
    if mask is not None:
        per_ex = per_ex * mask
    return per_ex


def _reduce(per_ex, mask):
    if mask is not None:
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.sum(per_ex) / denom
    return jnp.mean(per_ex) if per_ex.ndim == 1 else jnp.sum(per_ex) / per_ex.shape[0]


def compute_loss(
    loss: Union[str, LossFunction, Callable],
    labels: jax.Array,
    preoutput: jax.Array,
    activation=None,
    mask: Optional[jax.Array] = None,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Scalar loss. ``mask``: (batch,) or (batch, time) validity mask.

    ``weights``: per-output-column label weights (DL4J loss constructors).
    For rank-3 recurrent outputs (batch, time, out) the time axis is folded
    into the example axis, mirroring DL4J's rank-3 loss handling.
    """
    if callable(loss) and not isinstance(loss, (str, LossFunction)):
        return loss(labels, preoutput, mask)
    # Losses compute in >= float32 even under a bfloat16 compute policy
    # (softmax/log terms are unstable in bf16); float64 grad-checks keep f64.
    if jnp.issubdtype(preoutput.dtype, jnp.floating):
        ldt = jnp.promote_types(preoutput.dtype, jnp.float32)
        preoutput = preoutput.astype(ldt)
        if jnp.issubdtype(jnp.asarray(labels).dtype, jnp.floating):
            labels = jnp.asarray(labels).astype(ldt)
    fn = _LOSSES[_coerce(loss)]
    if preoutput.ndim == 3:  # (batch, time, out) -> fold time into batch
        b, t = preoutput.shape[0], preoutput.shape[1]
        preoutput = preoutput.reshape(b * t, -1)
        if labels.ndim == 3:
            labels = labels.reshape(b * t, -1)
        else:
            labels = labels.reshape(b * t)
        if mask is not None:
            mask = mask.reshape(b * t)
    return fn(labels, preoutput, activation, mask, weights)


def _mcxent(labels, preout, activation, mask, weights):
    act = "softmax" if activation is None else activation
    name = act.value if isinstance(act, enum.Enum) else str(act)
    if str(name).lower() == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
    else:
        logp = jnp.log(jnp.clip(_apply_activation(preout, act), _EPS, 1.0))
    ll = labels * logp
    if weights is not None:
        ll = ll * weights
    return _reduce(_per_example(-ll, mask), mask)


def _sparse_mcxent(labels, preout, activation, mask, weights):
    logp = jax.nn.log_softmax(preout, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if mask is not None:
        ll = ll * mask
    return _reduce(-ll, mask)


def _xent(labels, preout, activation, mask, weights):
    act = "sigmoid" if activation is None else activation
    name = str(act.value if isinstance(act, enum.Enum) else act).lower()
    if name == "sigmoid":
        # stable: max(x,0) - x*z + log(1+exp(-|x|))
        x, z = preout, labels
        per = jnp.maximum(x, 0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))
    else:
        p = jnp.clip(_apply_activation(preout, act), _EPS, 1.0 - _EPS)
        per = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
    if weights is not None:
        per = per * weights
    return _reduce(_per_example(per, mask), mask)


def _mse(labels, preout, activation, mask, weights):
    d = _apply_activation(preout, activation) - labels
    per = d * d
    if weights is not None:
        per = per * weights
    return _reduce(_per_example(per, mask), mask)


def _l2(labels, preout, activation, mask, weights):
    # DL4J L2 = sum of squared errors per example (MSE without the /n over outputs);
    # identical to our MSE convention since we sum over outputs already.
    return _mse(labels, preout, activation, mask, weights)


def _mae(labels, preout, activation, mask, weights):
    per = jnp.abs(_apply_activation(preout, activation) - labels)
    if weights is not None:
        per = per * weights
    return _reduce(_per_example(per, mask), mask)


def _hinge(labels, preout, activation, mask, weights):
    # labels in {-1, 1} or {0,1} -> map to ±1
    y = jnp.where(labels > 0, 1.0, -1.0)
    out = _apply_activation(preout, activation)
    per = jnp.maximum(0.0, 1.0 - y * out)
    return _reduce(_per_example(per, mask), mask)


def _squared_hinge(labels, preout, activation, mask, weights):
    y = jnp.where(labels > 0, 1.0, -1.0)
    out = _apply_activation(preout, activation)
    per = jnp.square(jnp.maximum(0.0, 1.0 - y * out))
    return _reduce(_per_example(per, mask), mask)


def _poisson(labels, preout, activation, mask, weights):
    out = jnp.clip(_apply_activation(preout, activation), _EPS, None)
    per = out - labels * jnp.log(out)
    return _reduce(_per_example(per, mask), mask)


def _cosine(labels, preout, activation, mask, weights):
    out = _apply_activation(preout, activation)
    num = jnp.sum(labels * out, axis=-1)
    den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1)
    per = -num / jnp.maximum(den, _EPS)
    if mask is not None:
        per = per * mask
    return _reduce(per, mask)


def _kld(labels, preout, activation, mask, weights):
    act = "softmax" if activation is None else activation
    out = jnp.clip(_apply_activation(preout, act), _EPS, 1.0)
    lab = jnp.clip(labels, _EPS, 1.0)
    per = lab * (jnp.log(lab) - jnp.log(out))
    return _reduce(_per_example(per, mask), mask)


def _msle(labels, preout, activation, mask, weights):
    out = _apply_activation(preout, activation)
    per = jnp.square(jnp.log1p(jnp.clip(out, -1 + _EPS, None)) - jnp.log1p(labels))
    return _reduce(_per_example(per, mask), mask)


_LOSSES = {
    LossFunction.MCXENT: _mcxent,
    LossFunction.SPARSE_MCXENT: _sparse_mcxent,
    LossFunction.NEGATIVELOGLIKELIHOOD: _mcxent,  # DL4J: same math given softmax output
    LossFunction.XENT: _xent,
    LossFunction.MSE: _mse,
    LossFunction.L2: _l2,
    LossFunction.L1: _mae,
    LossFunction.MAE: _mae,
    LossFunction.HINGE: _hinge,
    LossFunction.SQUARED_HINGE: _squared_hinge,
    LossFunction.POISSON: _poisson,
    LossFunction.COSINE_PROXIMITY: _cosine,
    LossFunction.KL_DIVERGENCE: _kld,
    LossFunction.MSLE: _msle,
}


def _coerce(name: Union[str, LossFunction]) -> LossFunction:
    if isinstance(name, LossFunction):
        return name
    return LossFunction(str(name).lower())


def get_loss(name: Union[str, LossFunction]) -> Callable:
    return _LOSSES[_coerce(name)]
