"""Activation functions.

Covers the reference's activation set (upstream
``org.nd4j.linalg.activations.Activation`` enum — IDENTITY..THRESHOLDEDRELU).
All are plain jnp functions: XLA fuses them into the surrounding matmul, which
is exactly the "cuDNN fused activation" fast path the reference needed helper
classes for.

Names are matched case-insensitively so configs serialized with DL4J-style
UPPERCASE names round-trip.
"""

from __future__ import annotations

import enum
from typing import Callable, Union

import jax
import jax.numpy as jnp


class Activation(str, enum.Enum):
    IDENTITY = "identity"
    RELU = "relu"
    RELU6 = "relu6"
    LEAKYRELU = "leakyrelu"
    ELU = "elu"
    SELU = "selu"
    GELU = "gelu"
    TANH = "tanh"
    SIGMOID = "sigmoid"
    HARDSIGMOID = "hardsigmoid"
    HARDTANH = "hardtanh"
    SOFTMAX = "softmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    SWISH = "swish"
    MISH = "mish"
    CUBE = "cube"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    THRESHOLDEDRELU = "thresholdedrelu"

    def __call__(self, x):
        return get_activation(self)(x)


def _rationaltanh(x):
    # DL4J's rational tanh approximation: 1.7159 * tanh(2x/3) (fast tanh family).
    return 1.7159 * jnp.tanh((2.0 / 3.0) * x)


@jax.custom_vjp
def gelu_tanh_recompute(a):
    """tanh-approximate gelu whose backward saves ONLY the input and
    recomputes tanh — XLA's autodiff of the plain composition keeps the
    (batch, ffn) tanh intermediate as a residual, which on BERT-base/v5e
    was ~0.6 ms/step of pure save traffic (37.9 -> 37.3 ms measured). The
    input is the producing matmul's output, materialised regardless, so
    the residual set adds nothing. Values identical to
    ``jax.nn.gelu(approximate=True)``; grads match to 1e-6.

    Deviation: custom_vjp functions reject forward-mode autodiff — a
    custom_jvp here would save the derivative tensor as the linearisation
    residual and defeat the traffic cut. ``jax.jacfwd`` through a
    gelu-activated layer raises; use ``jax.nn.gelu`` directly for
    forward-mode work (the reference has no forward-mode surface at all)."""
    return jax.nn.gelu(a, approximate=True)


_GELU_C = 0.7978845608028654  # sqrt(2/pi)


def _acc_dtype(dt):
    # f32 accumulation for low precision; f64 stays f64 (x64 grad-checks)
    return jnp.promote_types(dt, jnp.float32)


def _gelu_tanh_fwd(a):
    return jax.nn.gelu(a, approximate=True), a


def _gelu_tanh_bwd(a, g):
    af = a.astype(_acc_dtype(a.dtype))
    t = jnp.tanh(_GELU_C * (af + 0.044715 * af ** 3))
    d = 0.5 * (1.0 + t) + 0.5 * af * (1.0 - t * t) * _GELU_C * (
        1.0 + 3 * 0.044715 * af * af)
    return ((g.astype(af.dtype) * d).astype(a.dtype),)


gelu_tanh_recompute.defvjp(_gelu_tanh_fwd, _gelu_tanh_bwd)


def _fusable_erf(z):
    """Abramowitz–Stegun 7.1.26 rational erf (|abs err| < 1.5e-7) in plain
    mul/add/div/exp ops. The builtin ``erf`` lowers on XLA:TPU to a ~30-op
    guarded erfc expansion that the fusion pass refuses to duplicate into
    consumers — so every erf-gelu activation (64,128,3072 on BERT-base)
    was MATERIALIZED to HBM twice per layer (forward value + backward
    gelu'), ~0.46 + 0.28 ms/layer of the imported-vs-zoo device gap. This
    form is small enough that XLA input-fuses it into the consuming
    matmuls, like the zoo's tanh-gelu. Error is ~50x below bf16 rounding
    and well inside the 1e-5 import-golden tolerance."""
    s = jnp.sign(z)
    a = jnp.abs(z)
    t = 1.0 / (1.0 + 0.3275911 * a)
    poly = t * (0.254829592 + t * (-0.284496736 + t * (1.421413741
                + t * (-1.453152027 + t * 1.061405429))))
    return s * (1.0 - poly * jnp.exp(-a * a))


def _gelu_exact_value(af):
    return 0.5 * af * (1.0 + _fusable_erf(af * 0.7071067811865476))


@jax.custom_vjp
def gelu_exact_recompute(a):
    """Exact (erf) gelu with the same save-only-the-input backward as
    ``gelu_tanh_recompute`` — imported BERT's erf-gelu residual was
    ~2.6 GB/step of saved erf intermediates (1326 -> 1424 samples/s on
    v5e when recomputed). erf itself is the fusable rational form (see
    ``_fusable_erf``). Same forward-mode deviation applies."""
    af = a.astype(_acc_dtype(a.dtype))
    return _gelu_exact_value(af).astype(a.dtype)


def _gelu_exact_fwd(a):
    af = a.astype(_acc_dtype(a.dtype))
    return _gelu_exact_value(af).astype(a.dtype), a


def _gelu_exact_bwd(a, g):
    af = a.astype(_acc_dtype(a.dtype))
    cdf = 0.5 * (1.0 + _fusable_erf(af * 0.7071067811865476))
    pdf = jnp.exp(-0.5 * af * af) * 0.3989422804014327
    return ((g.astype(af.dtype) * (cdf + af * pdf)).astype(a.dtype),)


gelu_exact_recompute.defvjp(_gelu_exact_fwd, _gelu_exact_bwd)


_FNS: dict[str, Callable] = {
    "identity": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, negative_slope=0.01),
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": gelu_tanh_recompute,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    # DL4J/Keras hardSigmoid is clip(0.2x+0.5) — a DIFFERENT slope from
    # jax.nn.hard_sigmoid's relu6(x+3)/6; both names resolve to the
    # reference-exact formula (imported legacy models depend on it)
    "hardsigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "hardtanh": lambda x: jnp.clip(x, -1.0, 1.0),
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "swish": jax.nn.swish,
    "mish": jax.nn.mish,
    "cube": lambda x: x**3,
    "rationaltanh": _rationaltanh,
    "rectifiedtanh": lambda x: jnp.maximum(0.0, jnp.tanh(x)),
    "thresholdedrelu": lambda x: jnp.where(x > 1.0, x, 0.0),
}


def get_activation(name: Union[str, Activation, Callable]) -> Callable:
    """Resolve an activation by enum, name (any case), or pass a callable through."""
    if callable(name) and not isinstance(name, (str, Activation)):
        return name
    key = (name.value if isinstance(name, Activation) else str(name)).lower()
    if key not in _FNS:
        raise ValueError(f"Unknown activation {name!r}; known: {sorted(_FNS)}")
    return _FNS[key]


def single_pass_norm_stats(x, axis=-1):
    """Shifted single-pass (mean, var) in f32 over ``axis`` — ONE fused read
    of ``x``. Subtracting a per-row pivot (the first element along the axis,
    gradient-stopped — free, no extra pass) before accumulating avoids the
    E[x^2]-E[x]^2 catastrophic cancellation of the raw single-pass form for
    large-mean/small-variance rows. Shared by the zoo layers'
    ``layer_norm`` and the op registry's ``layer_norm``
    (``BatchNormalization`` uses the same idiom with its running mean as the
    pivot). Returns f32 ``(mean, var)`` with ``keepdims=True``."""
    import jax
    import jax.numpy as jnp
    xf = x.astype(jnp.float32)
    idx = [slice(None)] * xf.ndim
    idx[axis if axis >= 0 else xf.ndim + axis] = slice(0, 1)
    shift = jax.lax.stop_gradient(xf[tuple(idx)])
    d = xf - shift
    dmean = jnp.mean(d, axis=axis, keepdims=True)
    mean = shift + dmean
    var = jnp.maximum(jnp.mean(d * d, axis=axis, keepdims=True)
                      - dmean * dmean, 0.0)
    return mean, var
