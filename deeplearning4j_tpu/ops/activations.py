"""Activation functions.

Covers the reference's activation set (upstream
``org.nd4j.linalg.activations.Activation`` enum — IDENTITY..THRESHOLDEDRELU).
All are plain jnp functions: XLA fuses them into the surrounding matmul, which
is exactly the "cuDNN fused activation" fast path the reference needed helper
classes for.

Names are matched case-insensitively so configs serialized with DL4J-style
UPPERCASE names round-trip.
"""

from __future__ import annotations

import enum
from typing import Callable, Union

import jax
import jax.numpy as jnp


class Activation(str, enum.Enum):
    IDENTITY = "identity"
    RELU = "relu"
    RELU6 = "relu6"
    LEAKYRELU = "leakyrelu"
    ELU = "elu"
    SELU = "selu"
    GELU = "gelu"
    TANH = "tanh"
    SIGMOID = "sigmoid"
    HARDSIGMOID = "hardsigmoid"
    HARDTANH = "hardtanh"
    SOFTMAX = "softmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    SWISH = "swish"
    MISH = "mish"
    CUBE = "cube"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    THRESHOLDEDRELU = "thresholdedrelu"

    def __call__(self, x):
        return get_activation(self)(x)


def _rationaltanh(x):
    # DL4J's rational tanh approximation: 1.7159 * tanh(2x/3) (fast tanh family).
    return 1.7159 * jnp.tanh((2.0 / 3.0) * x)


_FNS: dict[str, Callable] = {
    "identity": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, negative_slope=0.01),
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    # DL4J/Keras hardSigmoid is clip(0.2x+0.5) — a DIFFERENT slope from
    # jax.nn.hard_sigmoid's relu6(x+3)/6; both names resolve to the
    # reference-exact formula (imported legacy models depend on it)
    "hardsigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "hardtanh": lambda x: jnp.clip(x, -1.0, 1.0),
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "swish": jax.nn.swish,
    "mish": jax.nn.mish,
    "cube": lambda x: x**3,
    "rationaltanh": _rationaltanh,
    "rectifiedtanh": lambda x: jnp.maximum(0.0, jnp.tanh(x)),
    "thresholdedrelu": lambda x: jnp.where(x > 1.0, x, 0.0),
}


def get_activation(name: Union[str, Activation, Callable]) -> Callable:
    """Resolve an activation by enum, name (any case), or pass a callable through."""
    if callable(name) and not isinstance(name, (str, Activation)):
        return name
    key = (name.value if isinstance(name, Activation) else str(name)).lower()
    if key not in _FNS:
        raise ValueError(f"Unknown activation {name!r}; known: {sorted(_FNS)}")
    return _FNS[key]
