"""Mixture-of-Experts layer with expert parallelism.

The reference has NO MoE (SURVEY.md §2.3: expert parallelism absent) — this
is parity-plus, built because EP is a first-class axis of the TPU design.
Routing follows the Switch/GShard recipe: a linear router, top-k gating,
and a differentiable load-balancing auxiliary loss. Dispatch is DENSE
(every expert runs on every token, combined by gate weights): on TPU this
is einsum-friendly, has no dynamic shapes, and under a ``NamedSharding``
that shards the expert dimension over the ``expert`` mesh axis GSPMD
partitions the expert computation across devices — expert parallelism
without any hand-written all-to-all.

The aux loss rides the model-state channel: forward returns it under
``_aux_loss`` and ``MultiLayerNetwork._loss`` adds every such entry to the
training loss (in-trace, so gradients flow to the router).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer, register_layer
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.initializers import init_weights


@register_layer
@dataclasses.dataclass
class MixtureOfExperts(Layer):
    """Top-k routed MoE FFN block: ``y = Σ_e gate_e(x) · FFN_e(x)``.

    Parameters carry a leading expert dimension — ``W_e1 (E, nIn, hidden)``,
    ``W_e2 (E, hidden, nOut)`` — which :meth:`ShardingStrategy.expert_parallel
    <deeplearning4j_tpu.parallel.sharding.ShardingStrategy.expert_parallel>`
    shards over the ``expert`` mesh axis."""

    n_out: int = 0
    n_experts: int = 4
    hidden_size: Optional[int] = None  # default 4 * n_out
    top_k: int = 2
    aux_loss_coef: float = 0.01
    router_noise: float = 0.0  # stddev of train-time router logit jitter

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.recurrent(self.n_out, input_type.timesteps)
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type, g: GlobalConfig):
        n_in = input_type.size
        h = self.hidden_size or 4 * self.n_out
        E = self.n_experts
        kr, k1, k2 = jax.random.split(key, 3)
        winit = self._winit(g)
        params = {
            "W_router": init_weights(kr, (n_in, E), winit, fan=(n_in, E), dtype=g.dtype),
            "W_e1": init_weights(k1, (E, n_in, h), winit, fan=(n_in, h), dtype=g.dtype),
            "b_e1": jnp.zeros((E, h), dtype=g.dtype),
            "W_e2": init_weights(k2, (E, h, self.n_out), winit, fan=(h, self.n_out),
                                 dtype=g.dtype),
            "b_e2": jnp.zeros((E, self.n_out), dtype=g.dtype),
        }
        return params, {"_aux_loss": jnp.zeros((), jnp.float32)}

    def regularizable_params(self):
        return ("W_router", "W_e1", "W_e2")

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        shape = x.shape
        tokens = x.reshape(-1, shape[-1])  # (N, nIn)
        E, k = self.n_experts, min(self.top_k, self.n_experts)

        logits = tokens @ params["W_router"]  # (N, E)
        if training and self.router_noise > 0.0 and rng is not None:
            # distinct subkey: rng was already consumed by input dropout
            logits = logits + self.router_noise * jax.random.normal(
                jax.random.fold_in(rng, 1), logits.shape, logits.dtype)
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k gates, renormalized over the selected experts
        top_vals, top_idx = jax.lax.top_k(probs, k)  # (N, k)
        gates = top_vals / jnp.maximum(top_vals.sum(-1, keepdims=True), 1e-9)
        combine = jnp.zeros_like(probs)  # (N, E) sparse gate matrix
        combine = combine.at[jnp.arange(tokens.shape[0])[:, None], top_idx].set(gates)

        act = get_activation(self._act(self._g) if self._act(self._g) is not None
                             else "relu")
        # dense expert compute: (N, E, h) -> (N, E, out), gate-combined.
        h = act(jnp.einsum("nf,efh->neh", tokens, params["W_e1"]) + params["b_e1"])
        y_e = jnp.einsum("neh,eho->neo", h, params["W_e2"]) + params["b_e2"]
        y = jnp.einsum("neo,ne->no", y_e, combine.astype(y_e.dtype))

        # Switch-style load balancing: fraction routed (top-1) x mean prob.
        # Masked (padding) tokens are excluded — balancing garbage tokens
        # would bias the router against real ones.
        top1 = jax.nn.one_hot(top_idx[:, 0], E, dtype=probs.dtype)
        if mask is not None and len(shape) == 3:
            w = mask.reshape(-1, 1).astype(probs.dtype)
            denom = jnp.maximum(w.sum(), 1.0)
            frac = jnp.sum(top1 * w, axis=0) / denom
            mean_prob = jnp.sum(probs * w, axis=0) / denom
        else:
            frac = jnp.mean(top1, axis=0)
            mean_prob = jnp.mean(probs, axis=0)
        aux = self.aux_loss_coef * E * jnp.sum(frac * mean_prob)

        new_state = dict(state)
        new_state["_aux_loss"] = aux.astype(jnp.float32)
        return y.reshape(*shape[:-1], self.n_out), new_state

    def expert_load(self, params, x) -> jnp.ndarray:
        """Fraction of tokens whose top-1 expert is e (diagnostic)."""
        tokens = jnp.asarray(x).reshape(-1, x.shape[-1])
        top1 = jnp.argmax(tokens @ params["W_router"], axis=-1)
        return jnp.mean(jax.nn.one_hot(top1, self.n_experts), axis=0)
