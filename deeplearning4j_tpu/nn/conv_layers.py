"""Convolutional / normalization / pooling layers.

Rebuild of upstream ``org.deeplearning4j.nn.conf.layers`` CNN set
(``ConvolutionLayer``, ``SubsamplingLayer``, ``BatchNormalization``,
``LocalResponseNormalization``, ``Upsampling2D``, ``ZeroPaddingLayer``,
``SeparableConvolution2D``, ``Deconvolution2D``, ``SpaceToDepthLayer``,
``GlobalPoolingLayer``) on XLA's native conv emitters — the TPU replacement
for the reference's cuDNN helper classes (``CudnnConvolutionHelper`` etc.).

Layout: NHWC activations, HWIO kernels (TPU-native; reference is NCHW).
Convolution mode: DL4J's ``ConvolutionMode.Truncate`` ≙ padding "VALID" with
explicit pad, ``Same`` ≙ "SAME".
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer, register_layer
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.initializers import init_weights

_DIMNUMS = ("NHWC", "HWIO", "NHWC")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _out_size(size: int, k: int, s: int, p: int, same: bool, dilation: int = 1) -> int:
    if same:
        return -(-size // s)  # ceil
    eff = (k - 1) * dilation + 1
    return (size + 2 * p - eff) // s + 1


class PoolingType(str, enum.Enum):
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


@register_layer
@dataclasses.dataclass
class ConvolutionLayer(Layer):
    """2-D convolution. Kernel HWIO (kh, kw, in, out)."""

    n_out: int = 0
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = (0, 0)
    dilation: Any = (1, 1)
    convolution_mode: str = "truncate"  # "truncate" | "same"
    has_bias: bool = True

    def _geom(self):
        return (_pair(self.kernel_size), _pair(self.stride), _pair(self.padding),
                _pair(self.dilation), self.convolution_mode.lower() == "same")

    def output_type(self, input_type: InputType) -> InputType:
        (kh, kw), (sh, sw), (ph, pw), (dh, dw), same = self._geom()
        h = _out_size(input_type.height, kh, sh, ph, same, dh)
        w = _out_size(input_type.width, kw, sw, pw, same, dw)
        return InputType.convolutional(h, w, self.n_out)

    def init(self, key, input_type, g: GlobalConfig):
        (kh, kw), _, _, _, _ = self._geom()
        c_in = input_type.channels
        fan_in = kh * kw * c_in
        fan_out = kh * kw * self.n_out
        params = {"W": init_weights(key, (kh, kw, c_in, self.n_out), self._winit(g),
                                    fan=(fan_in, fan_out), dtype=g.dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self._binit(g), dtype=g.dtype)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        (kh, kw), (sh, sw), (ph, pw), (dh, dw), same = self._geom()
        pad = "SAME" if same else [(ph, ph), (pw, pw)]
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=(sh, sw), padding=pad,
            rhs_dilation=(dh, dw), dimension_numbers=_DIMNUMS)
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state


@register_layer
@dataclasses.dataclass
class Convolution1DLayer(ConvolutionLayer):
    """1-D convolution over (batch, time, features) via a width-1 2-D conv."""

    kernel_size: Any = 3
    stride: Any = 1
    padding: Any = 0
    dilation: Any = 1

    def _geom1d(self):
        k = self.kernel_size[0] if isinstance(self.kernel_size, (tuple, list)) else self.kernel_size
        s = self.stride[0] if isinstance(self.stride, (tuple, list)) else self.stride
        p = self.padding[0] if isinstance(self.padding, (tuple, list)) else self.padding
        d = self.dilation[0] if isinstance(self.dilation, (tuple, list)) else self.dilation
        return int(k), int(s), int(p), int(d), self.convolution_mode.lower() == "same"

    def _is_causal(self) -> bool:
        return self.convolution_mode.lower() == "causal"

    def output_type(self, input_type: InputType) -> InputType:
        k, s, p, d, same = self._geom1d()
        t = input_type.timesteps
        if self._is_causal():
            t_out = None if t is None else -(-t // s)  # left-pad keeps ceil(t/s)
        else:
            t_out = None if t is None else _out_size(t, k, s, p, same, d)
        return InputType.recurrent(self.n_out, t_out)

    def init(self, key, input_type, g: GlobalConfig):
        k, _, _, _, _ = self._geom1d()
        c_in = input_type.size
        params = {"W": init_weights(key, (k, 1, c_in, self.n_out), self._winit(g),
                                    fan=(k * c_in, k * self.n_out), dtype=g.dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self._binit(g), dtype=g.dtype)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        k, s, p, d, same = self._geom1d()
        if self._is_causal():
            pad = [((k - 1) * d, 0), (0, 0)]  # left-only: y[t] sees x[<=t]
        else:
            pad = "SAME" if same else [(p, p), (0, 0)]
        y = lax.conv_general_dilated(
            x[:, :, None, :], params["W"], window_strides=(s, 1), padding=pad,
            rhs_dilation=(d, 1), dimension_numbers=_DIMNUMS)[:, :, 0, :]
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state

    def transform_mask(self, mask):
        """Reduce the (batch, time) mask with the conv's own geometry: an
        output step is valid if ANY input step in its window is (the
        reference's cnn1d mask reduction — max-pool with identical k/s/p)."""
        if mask is None:
            return None
        k, s, p, d, same = self._geom1d()
        eff = (k - 1) * d + 1
        if self._is_causal():
            padding = [(0, 0), (eff - 1, 0)]
        else:
            padding = "SAME" if same else [(0, 0), (p, p)]
        return lax.reduce_window(mask.astype(jnp.float32), 0.0, lax.max,
                                 (1, eff), (1, s), padding)


@register_layer
@dataclasses.dataclass
class SubsamplingLayer(Layer):
    """Pooling (reference ``SubsamplingLayer``): max / avg / sum / p-norm."""

    pooling_type: Any = PoolingType.MAX
    kernel_size: Any = (2, 2)
    stride: Any = (2, 2)
    padding: Any = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        (kh, kw), (sh, sw), (ph, pw) = _pair(self.kernel_size), _pair(self.stride), _pair(self.padding)
        same = self.convolution_mode.lower() == "same"
        h = _out_size(input_type.height, kh, sh, ph, same)
        w = _out_size(input_type.width, kw, sw, pw, same)
        return InputType.convolutional(h, w, input_type.channels)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        (kh, kw), (sh, sw), (ph, pw) = _pair(self.kernel_size), _pair(self.stride), _pair(self.padding)
        same = self.convolution_mode.lower() == "same"
        dims, strides = (1, kh, kw, 1), (1, sh, sw, 1)
        pad = "SAME" if same else [(0, 0), (ph, ph), (pw, pw), (0, 0)]
        pt = PoolingType(self.pooling_type)
        if pt == PoolingType.MAX:
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        elif pt == PoolingType.SUM:
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
        elif pt == PoolingType.AVG:
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
            y = y / counts
        else:  # PNORM
            p = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, dims, strides, pad) ** (1.0 / p)
        return y, state


@register_layer
@dataclasses.dataclass
class BatchNormalization(Layer):
    """Batch norm (reference ``BatchNormalization``): per-channel (last axis)
    stats; running stats in ``state`` updated with ``decay`` momentum."""

    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    use_gamma_beta: bool = True

    def _nchan(self, input_type: InputType) -> int:
        return input_type.channels if input_type.kind == "convolutional" else input_type.flat_size()

    def init(self, key, input_type, g: GlobalConfig):
        n = self._nchan(input_type)
        params = {}
        if self.use_gamma_beta and not self.lock_gamma_beta:
            params = {"gamma": jnp.ones((n,), g.dtype or jnp.float32),
                      "beta": jnp.zeros((n,), g.dtype or jnp.float32)}
        state = {"mean": jnp.zeros((n,), jnp.float32), "var": jnp.ones((n,), jnp.float32)}
        return params, state

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))
        if training:
            # Single-pass stats: E[x] and E[x^2] have no data dependency, so
            # XLA fuses both reductions into ONE read of x (jnp.var's
            # (x-mean)^2 form forces a second full pass — measured as the
            # dominant extra HBM traffic in conv nets). f32 accumulation.
            xf = x.astype(jnp.float32)
            n = 1
            for a in axes:
                n *= x.shape[a]
            # Shifted single-pass form: accumulating around the running mean
            # (free — already in state) avoids the catastrophic cancellation
            # of raw E[x^2]-E[x]^2 for large-mean/small-variance inputs
            # while keeping both reductions in one fused read of x.
            shift = state["mean"]
            d = xf - shift
            dmean = jnp.sum(d, axis=axes) / n
            mean = shift + dmean
            var = jnp.maximum(jnp.sum(d * d, axis=axes) / n - dmean * dmean,
                              0.0)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
            mean, var = mean.astype(x.dtype), var.astype(x.dtype)
        else:
            mean, var = state["mean"].astype(x.dtype), state["var"].astype(x.dtype)
            new_state = state
        y = (x - mean) * lax.rsqrt(var.astype(x.dtype) + self.eps)
        if "gamma" in params:
            y = y * params["gamma"] + params["beta"]
        return get_activation(self._act(self._g))(y), new_state

    def regularizable_params(self):
        return ()  # gamma/beta are never l1/l2-regularized in the reference


@register_layer
@dataclasses.dataclass
class LocalResponseNormalization(Layer):
    """LRN across channels (reference ``LocalResponseNormalization``)."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        half = self.n // 2
        sq = x * x
        # sum over a window of channels via padded cumulative trick
        pads = [(0, 0)] * (x.ndim - 1) + [(half, half)]
        padded = jnp.pad(sq, pads)
        win = sum(lax.slice_in_dim(padded, i, i + x.shape[-1], axis=x.ndim - 1)
                  for i in range(self.n))
        return x / ((self.k + self.alpha * win) ** self.beta), state


@register_layer
@dataclasses.dataclass
class Upsampling2D(Layer):
    """Nearest-neighbour upsampling (reference ``Upsampling2D``)."""

    size: Any = (2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        sh, sw = _pair(self.size)
        return InputType.convolutional(input_type.height * sh, input_type.width * sw,
                                       input_type.channels)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        sh, sw = _pair(self.size)
        return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2), state


@register_layer
@dataclasses.dataclass
class ZeroPaddingLayer(Layer):
    """Spatial zero padding (reference ``ZeroPaddingLayer``)."""

    padding: Any = (1, 1)  # (ph, pw) or ((top,bottom),(left,right))

    def _pads(self):
        p = self.padding
        if isinstance(p, (tuple, list)) and len(p) == 2 and isinstance(p[0], (tuple, list)):
            return tuple(p[0]), tuple(p[1])
        ph, pw = _pair(p)
        return (ph, ph), (pw, pw)

    def output_type(self, input_type: InputType) -> InputType:
        (pt, pb), (pl, pr) = self._pads()
        return InputType.convolutional(input_type.height + pt + pb,
                                       input_type.width + pl + pr, input_type.channels)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        (pt, pb), (pl, pr) = self._pads()
        return jnp.pad(x, [(0, 0), (pt, pb), (pl, pr), (0, 0)]), state


@register_layer
@dataclasses.dataclass
class SeparableConvolution2D(ConvolutionLayer):
    """Depthwise-separable conv (reference ``SeparableConvolution2D``):
    depthwise (feature_group_count) then 1x1 pointwise."""

    depth_multiplier: int = 1

    def init(self, key, input_type, g: GlobalConfig):
        (kh, kw), _, _, _, _ = self._geom()
        c_in = input_type.channels
        k1, k2 = jax.random.split(key)
        dm = self.depth_multiplier
        params = {
            "W_depth": init_weights(k1, (kh, kw, 1, c_in * dm), self._winit(g),
                                    fan=(kh * kw, kh * kw * dm), dtype=g.dtype),
            "W_point": init_weights(k2, (1, 1, c_in * dm, self.n_out), self._winit(g),
                                    fan=(c_in * dm, self.n_out), dtype=g.dtype),
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self._binit(g), dtype=g.dtype)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        (kh, kw), (sh, sw), (ph, pw), (dh, dw), same = self._geom()
        pad = "SAME" if same else [(ph, ph), (pw, pw)]
        c_in = x.shape[-1]
        y = lax.conv_general_dilated(
            x, params["W_depth"], window_strides=(sh, sw), padding=pad,
            rhs_dilation=(dh, dw), dimension_numbers=_DIMNUMS,
            feature_group_count=c_in)
        y = lax.conv_general_dilated(
            y, params["W_point"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=_DIMNUMS)
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state


@register_layer
@dataclasses.dataclass
class Deconvolution2D(ConvolutionLayer):
    """Transposed convolution (reference ``Deconvolution2D``)."""

    def output_type(self, input_type: InputType) -> InputType:
        (kh, kw), (sh, sw), (ph, pw), (dh, dw), same = self._geom()
        if same:
            h, w = input_type.height * sh, input_type.width * sw
        else:
            h = sh * (input_type.height - 1) + kh - 2 * ph
            w = sw * (input_type.width - 1) + kw - 2 * pw
        return InputType.convolutional(h, w, self.n_out)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        (kh, kw), (sh, sw), (ph, pw), _, same = self._geom()
        pad = "SAME" if same else [(kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)]
        y = lax.conv_transpose(x, params["W"], strides=(sh, sw), padding=pad,
                               dimension_numbers=_DIMNUMS)
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state


@register_layer
@dataclasses.dataclass
class SpaceToDepthLayer(Layer):
    """Space-to-depth (reference ``SpaceToDepthLayer``)."""

    block_size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        b = self.block_size
        return InputType.convolutional(input_type.height // b, input_type.width // b,
                                       input_type.channels * b * b)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        n, h, w, c = x.shape
        b = self.block_size
        y = x.reshape(n, h // b, b, w // b, b, c).transpose(0, 1, 3, 2, 4, 5)
        return y.reshape(n, h // b, w // b, c * b * b), state


@register_layer
@dataclasses.dataclass
class GlobalPoolingLayer(Layer):
    """Global pooling over spatial or time dims (reference
    ``GlobalPoolingLayer``); mask-aware for sequences."""

    pooling_type: Any = PoolingType.MAX
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.feed_forward(input_type.size)
        return InputType.feed_forward(input_type.channels)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        axes = tuple(range(1, x.ndim - 1))  # all dims between batch and channels
        pt = PoolingType(self.pooling_type)
        if x.ndim == 3 and mask is not None:
            m = mask[..., None].astype(x.dtype)
            if pt == PoolingType.MAX:
                y = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
            elif pt == PoolingType.SUM:
                y = jnp.sum(x * m, axis=1)
            elif pt == PoolingType.AVG:
                y = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
            else:
                p = float(self.pnorm)
                y = jnp.sum((jnp.abs(x) ** p) * m, axis=1) ** (1.0 / p)
            return y, state
        if pt == PoolingType.MAX:
            return jnp.max(x, axis=axes), state
        if pt == PoolingType.SUM:
            return jnp.sum(x, axis=axes), state
        if pt == PoolingType.AVG:
            return jnp.mean(x, axis=axes), state
        p = float(self.pnorm)
        return jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p), state
