"""Graph vertices for ComputationGraph.

Rebuild of upstream ``org.deeplearning4j.nn.conf.graph.*``: ``MergeVertex``,
``ElementWiseVertex`` (Add/Product/Subtract/Average/Max), ``SubsetVertex``,
``StackVertex``/``UnstackVertex``, ``ScaleVertex``/``ShiftVertex``,
``L2NormalizeVertex``, ``PreprocessorVertex``, ``ReshapeVertex``. Pure
functions of their inputs; XLA fuses them into the surrounding program.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple, Type

import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.preprocessors import InputPreProcessor

_VERTEX_REGISTRY: Dict[str, Type["GraphVertex"]] = {}


def register_vertex(cls):
    _VERTEX_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class GraphVertex:
    def forward(self, *inputs):
        raise NotImplementedError

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["@type"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "GraphVertex":
        d = dict(d)
        cls = _VERTEX_REGISTRY[d.pop("@type")]
        if cls is PreprocessorVertex and isinstance(d.get("preprocessor"), dict):
            d["preprocessor"] = InputPreProcessor.from_dict(d["preprocessor"])
        return cls(**d)


@register_vertex
@dataclasses.dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature (last) axis."""

    def forward(self, *inputs):
        return jnp.concatenate(inputs, axis=-1)

    def output_type(self, *its: InputType) -> InputType:
        it = its[0]
        if it.kind == "convolutional":
            return InputType.convolutional(it.height, it.width,
                                           sum(i.channels for i in its))
        if it.kind == "recurrent":
            return InputType.recurrent(sum(i.size for i in its), it.timesteps)
        return InputType.feed_forward(sum(i.flat_size() for i in its))


@register_vertex
@dataclasses.dataclass
class ElementWiseVertex(GraphVertex):
    """Pointwise combine: Add / Product / Subtract / Average / Max."""

    op: str = "add"

    def forward(self, *inputs):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op in ("product", "mul"):
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op == "subtract":
            return inputs[0] - inputs[1]
        if op in ("average", "avg"):
            return sum(inputs) / len(inputs)
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        if op == "min":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.minimum(out, x)
            return out
        if op == "dot":
            # Keras Dot(axes=-1, normalize=False) over matching feature axes
            return jnp.sum(inputs[0] * inputs[1], axis=-1, keepdims=True)
        raise ValueError(f"Unknown elementwise op {self.op!r}")


@register_vertex
@dataclasses.dataclass
class SubsetVertex(GraphVertex):
    """Feature-axis slice [from_idx, to_idx] inclusive (reference semantics)."""

    from_idx: int = 0
    to_idx: int = 0

    def forward(self, *inputs):
        return inputs[0][..., self.from_idx:self.to_idx + 1]

    def output_type(self, *its: InputType) -> InputType:
        n = self.to_idx - self.from_idx + 1
        it = its[0]
        if it.kind == "recurrent":
            return InputType.recurrent(n, it.timesteps)
        return InputType.feed_forward(n)


@register_vertex
@dataclasses.dataclass
class StackVertex(GraphVertex):
    """Stack along batch axis (reference ``StackVertex``)."""

    def forward(self, *inputs):
        return jnp.concatenate(inputs, axis=0)


@register_vertex
@dataclasses.dataclass
class UnstackVertex(GraphVertex):
    """Take the i-th of n equal batch-axis chunks."""

    from_idx: int = 0
    stack_size: int = 1

    def forward(self, *inputs):
        x = inputs[0]
        n = x.shape[0] // self.stack_size
        return x[self.from_idx * n:(self.from_idx + 1) * n]


@register_vertex
@dataclasses.dataclass
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def forward(self, *inputs):
        return inputs[0] * self.scale


@register_vertex
@dataclasses.dataclass
class ShiftVertex(GraphVertex):
    shift: float = 0.0

    def forward(self, *inputs):
        return inputs[0] + self.shift


@register_vertex
@dataclasses.dataclass
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def forward(self, *inputs):
        x = inputs[0]
        norm = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
        return x / (norm + self.eps)


@register_vertex
@dataclasses.dataclass
class PreprocessorVertex(GraphVertex):
    preprocessor: InputPreProcessor = None

    def forward(self, *inputs):
        return self.preprocessor.pre_process(inputs[0])

    def output_type(self, *its: InputType) -> InputType:
        return self.preprocessor.output_type(its[0])

    def to_dict(self) -> dict:
        return {"@type": "PreprocessorVertex", "preprocessor": self.preprocessor.to_dict()}


@register_vertex
@dataclasses.dataclass
class ReshapeVertex(GraphVertex):
    shape: Tuple[int, ...] = ()

    def forward(self, *inputs):
        return inputs[0].reshape((inputs[0].shape[0],) + tuple(self.shape))
