"""Weight constraints + weight noise (reference
``org.deeplearning4j.nn.conf.constraint.*`` — MaxNormConstraint,
MinMaxNormConstraint, UnitNormConstraint, NonNegativeConstraint — and
``org.deeplearning4j.nn.conf.weightnoise.{DropConnect,WeightNoise}``).

Constraints are projections applied to parameters AFTER each updater step
(the reference applies them in ``BaseLayer.applyConstraints``); inside our
jitted train step they are pure ops fused into the same program. Weight
noise perturbs the weights seen by the forward pass during training only
(DropConnect = Bernoulli mask on weights, the reference's formulation).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class Constraint:
    """Projection applied to a parameter after each update."""

    def apply(self, w):
        raise NotImplementedError

    def to_dict(self):
        d = {"type": type(self).__name__}
        d.update({f.name: getattr(self, f.name)
                  for f in dataclasses.fields(self)})
        return d

    @staticmethod
    def from_dict(d):
        cls = _CONSTRAINTS[d["type"]]
        kw = {k: v for k, v in d.items() if k != "type"}
        return cls(**kw)


def _norms(w, axes):
    if axes is None:
        return jnp.sqrt(jnp.sum(w * w))
    return jnp.sqrt(jnp.sum(w * w, axis=tuple(axes), keepdims=True))


@dataclasses.dataclass
class MaxNormConstraint(Constraint):
    """Scale weights down so the norm over ``axes`` is <= max_norm."""

    max_norm: float = 1.0
    axes: Optional[Sequence[int]] = (0,)

    def apply(self, w):
        n = _norms(w, self.axes)
        scale = jnp.minimum(1.0, self.max_norm / jnp.maximum(n, 1e-12))
        return w * scale


@dataclasses.dataclass
class MinMaxNormConstraint(Constraint):
    """Clamp the norm over ``axes`` into [min_norm, max_norm] with
    interpolation ``rate`` (reference MinMaxNormConstraint)."""

    min_norm: float = 0.0
    max_norm: float = 1.0
    rate: float = 1.0
    axes: Optional[Sequence[int]] = (0,)

    def apply(self, w):
        n = _norms(w, self.axes)
        clipped = jnp.clip(n, self.min_norm, self.max_norm)
        target = self.rate * clipped + (1.0 - self.rate) * n
        return w * (target / jnp.maximum(n, 1e-12))


@dataclasses.dataclass
class UnitNormConstraint(Constraint):
    axes: Optional[Sequence[int]] = (0,)

    def apply(self, w):
        return w / jnp.maximum(_norms(w, self.axes), 1e-12)


@dataclasses.dataclass
class NonNegativeConstraint(Constraint):
    def apply(self, w):
        return jnp.maximum(w, 0.0)


_CONSTRAINTS = {c.__name__: c for c in
                (MaxNormConstraint, MinMaxNormConstraint, UnitNormConstraint,
                 NonNegativeConstraint)}


def apply_layer_constraints(layer, layer_params):
    """Project one layer's params per its constraint config (weights via
    ``constraints``, biases via ``bias_constraints``)."""
    cs = getattr(layer, "constraints", None)
    bcs = getattr(layer, "bias_constraints", None)
    if not cs and not bcs:
        return layer_params
    wkeys = set(layer.regularizable_params())
    out = dict(layer_params)
    for k, v in layer_params.items():
        if not isinstance(v, jax.Array):
            continue
        active = cs if k in wkeys else (bcs if k == "b" else None)
        if active:
            for c in (active if isinstance(active, (list, tuple)) else [active]):
                v = c.apply(v)
            out[k] = v
    return out


# ------------------------------------------------------------ weight noise
@dataclasses.dataclass
class DropConnect:
    """Bernoulli mask on WEIGHTS during training (reference ``DropConnect``;
    ``p`` is the retain probability, matching our dropout convention)."""

    p: float = 0.5
    apply_to_bias: bool = False

    def apply(self, key, w):
        keep = jax.random.bernoulli(key, self.p, w.shape)
        return jnp.where(keep, w / self.p, 0.0).astype(w.dtype)

    def to_dict(self):
        return {"type": "DropConnect", "p": self.p,
                "apply_to_bias": self.apply_to_bias}


@dataclasses.dataclass
class WeightNoise:
    """Additive (or multiplicative) gaussian noise on weights during
    training (reference ``WeightNoise`` with a Normal distribution)."""

    stddev: float = 0.01
    mean: float = 0.0
    additive: bool = True
    apply_to_bias: bool = False

    def apply(self, key, w):
        noise = (self.mean
                 + self.stddev * jax.random.normal(key, w.shape)).astype(w.dtype)
        return w + noise if self.additive else w * noise

    def to_dict(self):
        return {"type": "WeightNoise", "stddev": self.stddev,
                "mean": self.mean, "additive": self.additive,
                "apply_to_bias": self.apply_to_bias}


def apply_weight_noise(layer, layer_params, rng):
    """Perturb the weights a training forward sees (no-op at inference)."""
    wn = getattr(layer, "weight_noise", None)
    if wn is None or rng is None:
        return layer_params
    wkeys = set(layer.regularizable_params())
    out = dict(layer_params)
    i = 0
    for k in sorted(layer_params):
        v = layer_params[k]
        if not isinstance(v, jax.Array):
            continue
        if k in wkeys or (k == "b" and wn.apply_to_bias):
            out[k] = wn.apply(jax.random.fold_in(rng, i), v)
        i += 1
    return out
