"""Input type system for shape inference.

Rebuild of upstream ``org.deeplearning4j.nn.conf.inputs.InputType``: each layer
declares its output type given an input type, so the network infers every
parameter shape from ``set_input_type(...)`` at build time — no manual ``nIn``.

Layout conventions (deliberately TPU-idiomatic, documented deviations from the
reference):

- feed-forward: ``(batch, size)``
- recurrent:    ``(batch, time, size)``   (reference uses (batch, size, time);
  time-last is hostile to XLA batched matmuls, so we use time-middle and the
  data layer produces it directly)
- convolutional: ``(batch, height, width, channels)`` NHWC (reference default
  NCHW; NHWC is the TPU-native conv layout)
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class InputType:
    kind: str  # "feedforward" | "recurrent" | "convolutional" | "convolutional3d"
    size: Optional[int] = None  # feedforward / recurrent feature size
    timesteps: Optional[int] = None  # recurrent (None = dynamic)
    height: Optional[int] = None
    width: Optional[int] = None
    channels: Optional[int] = None
    depth: Optional[int] = None  # 3d conv

    # -- factories (names mirror the reference API) --
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="feedforward", size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType(kind="recurrent", size=int(size),
                         timesteps=None if timesteps is None else int(timesteps))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional", height=int(height), width=int(width),
                         channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        """Flattened image input (e.g. MNIST csv rows) — a FeedForwardToCnn
        preprocessor will be auto-inserted before the first conv layer."""
        it = InputType.convolutional(height, width, channels)
        return dataclasses.replace(it, kind="convolutional_flat")

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional3d", depth=int(depth), height=int(height),
                         width=int(width), channels=int(channels))

    # -- helpers --
    def flat_size(self) -> int:
        if self.kind == "feedforward" or self.kind == "recurrent":
            return int(self.size)
        if self.kind in ("convolutional", "convolutional_flat"):
            return int(self.height * self.width * self.channels)
        if self.kind == "convolutional3d":
            return int(self.depth * self.height * self.width * self.channels)
        raise ValueError(self.kind)

    def array_shape(self, batch: int = -1) -> Tuple[int, ...]:
        """Concrete array shape (batch dim first; -1 = symbolic)."""
        if self.kind == "feedforward" or self.kind == "convolutional_flat":
            return (batch, self.flat_size())
        if self.kind == "recurrent":
            return (batch, self.timesteps or -1, self.size)
        if self.kind == "convolutional":
            return (batch, self.height, self.width, self.channels)
        if self.kind == "convolutional3d":
            return (batch, self.depth, self.height, self.width, self.channels)
        raise ValueError(self.kind)

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items() if v is not None}

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(**d)
