"""Remaining reference layer types: 3-D convolution family, cropping,
locally-connected, center-loss output, YOLOv2 detection output.

Reference classes: ``Convolution3D``, ``Subsampling3DLayer``,
``Upsampling1D/3D``, ``Cropping2D``, ``LocallyConnected2D``,
``CenterLossOutputLayer``, ``Yolo2OutputLayer``
(upstream ``org.deeplearning4j.nn.conf.layers`` + ``...layers.objdetect``).

Layouts: 3-D convs use NDHWC (channels-last, TPU-native).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer, register_layer
from deeplearning4j_tpu.nn.core_layers import OutputLayer
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.ops.losses import compute_loss


def _triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


@register_layer
@dataclasses.dataclass
class Convolution3D(Layer):
    """3-D conv over (batch, depth, height, width, channels), DHWIO kernel."""

    n_out: int = 0
    kernel_size: Any = (3, 3, 3)
    stride: Any = (1, 1, 1)
    convolution_mode: str = "same"
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        kd, kh, kw = _triple(self.kernel_size)
        sd, sh, sw = _triple(self.stride)
        same = self.convolution_mode.lower() == "same"

        def osz(size, k, s):
            return -(-size // s) if same else (size - k) // s + 1

        return InputType.convolutional3d(osz(input_type.depth, kd, sd),
                                         osz(input_type.height, kh, sh),
                                         osz(input_type.width, kw, sw), self.n_out)

    def init(self, key, input_type, g: GlobalConfig):
        kd, kh, kw = _triple(self.kernel_size)
        c_in = input_type.channels
        fan_in = kd * kh * kw * c_in
        params = {"W": init_weights(key, (kd, kh, kw, c_in, self.n_out), self._winit(g),
                                    fan=(fan_in, kd * kh * kw * self.n_out), dtype=g.dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self._binit(g), g.dtype or jnp.float32)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        same = self.convolution_mode.lower() == "same"
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=_triple(self.stride),
            padding="SAME" if same else "VALID",
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state


@register_layer
@dataclasses.dataclass
class Subsampling3DLayer(Layer):
    pooling_type: str = "max"
    kernel_size: Any = (2, 2, 2)
    stride: Any = (2, 2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        kd, kh, kw = _triple(self.kernel_size)
        sd, sh, sw = _triple(self.stride)
        return InputType.convolutional3d((input_type.depth - kd) // sd + 1,
                                         (input_type.height - kh) // sh + 1,
                                         (input_type.width - kw) // sw + 1,
                                         input_type.channels)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        dims = (1, *_triple(self.kernel_size), 1)
        strides = (1, *_triple(self.stride), 1)
        if self.pooling_type.lower() == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, "VALID"), state
        s = lax.reduce_window(x, 0.0, lax.add, dims, strides, "VALID")
        n = 1
        for k in _triple(self.kernel_size):
            n *= k
        return s / n, state


@register_layer
@dataclasses.dataclass
class Upsampling1D(Layer):
    size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        t = None if input_type.timesteps is None else input_type.timesteps * self.size
        return InputType.recurrent(input_type.size, t)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return jnp.repeat(x, self.size, axis=1), state

    def transform_mask(self, mask):
        return None if mask is None else jnp.repeat(mask, self.size, axis=1)


@register_layer
@dataclasses.dataclass
class Upsampling3D(Layer):
    size: Any = (2, 2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        sd, sh, sw = _triple(self.size)
        return InputType.convolutional3d(input_type.depth * sd, input_type.height * sh,
                                         input_type.width * sw, input_type.channels)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        sd, sh, sw = _triple(self.size)
        x = jnp.repeat(x, sd, axis=1)
        x = jnp.repeat(x, sh, axis=2)
        return jnp.repeat(x, sw, axis=3), state


@register_layer
@dataclasses.dataclass
class Cropping2D(Layer):
    """Crop spatial borders (reference ``Cropping2D``)."""

    crop: Any = (0, 0)  # (top/bottom, left/right) or ((t,b),(l,r))

    def _crops(self):
        c = self.crop
        if isinstance(c, (tuple, list)) and len(c) == 2 and isinstance(c[0], (tuple, list)):
            return tuple(c[0]), tuple(c[1])
        a, b = (c, c) if isinstance(c, int) else c
        return (a, a), (b, b)

    def output_type(self, input_type: InputType) -> InputType:
        (t, b), (l, r) = self._crops()
        return InputType.convolutional(input_type.height - t - b,
                                       input_type.width - l - r, input_type.channels)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        (t, b), (l, r) = self._crops()
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b or None, l:w - r or None, :], state


@register_layer
@dataclasses.dataclass
class LocallyConnected2D(Layer):
    """Conv with UNSHARED weights per output position (reference
    ``LocallyConnected2D``) via ``lax.conv_general_dilated_local``."""

    n_out: int = 0
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    has_bias: bool = True

    def _geom(self, it: InputType):
        kh, kw = (self.kernel_size if isinstance(self.kernel_size, (tuple, list))
                  else (self.kernel_size,) * 2)
        sh, sw = (self.stride if isinstance(self.stride, (tuple, list))
                  else (self.stride,) * 2)
        oh = (it.height - kh) // sh + 1
        ow = (it.width - kw) // sw + 1
        return int(kh), int(kw), int(sh), int(sw), oh, ow

    def output_type(self, input_type: InputType) -> InputType:
        *_, oh, ow = self._geom(input_type)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, input_type, g: GlobalConfig):
        kh, kw, _, _, oh, ow = self._geom(input_type)
        c_in = input_type.channels
        # filter shape for conv_general_dilated_local (spatial..., c_in*kh*kw, c_out)
        params = {"W": init_weights(key, (oh, ow, c_in * kh * kw, self.n_out),
                                    self._winit(g), fan=(c_in * kh * kw, self.n_out),
                                    dtype=g.dtype)}
        if self.has_bias:
            params["b"] = jnp.full((oh, ow, self.n_out), self._binit(g),
                                   g.dtype or jnp.float32)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        kh, kw, sh, sw, _, _ = self._geom(
            InputType.convolutional(x.shape[1], x.shape[2], x.shape[3]))
        y = lax.conv_general_dilated_local(
            x, params["W"], window_strides=(sh, sw), padding="VALID",
            filter_shape=(kh, kw), dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state


@register_layer
@dataclasses.dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (reference ``CenterLossOutputLayer``):
    L = CE + (lambda/2)·||f - c_y||²; per-class centers kept in layer state
    and updated with rate ``alpha`` toward the batch features."""

    alpha: float = 0.05
    lambda_: float = 2e-4

    def init(self, key, input_type, g: GlobalConfig):
        params, state = super().init(key, input_type, g)
        n_in = self._nin(input_type)
        state = dict(state)
        state["centers"] = jnp.zeros((self.n_out, n_in), jnp.float32)
        return params, state

    def update_state_with_labels(self, state, x, labels):
        """EMA center update toward the batch's class means (the reference's
        center update rule); called by the network's loss path where labels
        are available."""
        centers = state["centers"]
        onehot = labels.astype(jnp.float32)
        counts = jnp.sum(onehot, axis=0)  # (C,)
        sums = onehot.T @ x.astype(jnp.float32)  # (C, n_in)
        means = sums / jnp.maximum(counts, 1.0)[:, None]
        updated = jnp.where(counts[:, None] > 0,
                            centers + self.alpha * (means - centers), centers)
        return {**state, "centers": updated}

    def compute_loss(self, params, x, labels, mask=None, state=None):
        ce = compute_loss(self.loss, labels, self.preoutput(params, x),
                          activation=self._act(self._g), mask=mask)
        if not state or "centers" not in state:
            # centers live in model_state, passed by the network's loss path;
            # standalone calls without state skip the center term.
            return ce
        idx = jnp.argmax(labels, axis=-1)
        centers = jax.lax.stop_gradient(
            jnp.take(state["centers"], idx, axis=0).astype(x.dtype))
        diff = x - centers
        center_term = 0.5 * self.lambda_ * jnp.mean(jnp.sum(diff * diff, axis=-1))
        return ce + center_term


@register_layer
@dataclasses.dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 detection loss (reference
    ``org.deeplearning4j.nn.layers.objdetect.Yolo2OutputLayer``).

    Input: (batch, H, W, A*(5+C)) raw predictions with A anchor boxes.
    Labels: same-shaped tensor where, per assigned anchor cell,
    channels are [tx, ty, tw, th, objectness(0/1), class one-hot...].
    Loss = coord (MSE on xy via sigmoid, wh via raw) * lambda_coord
         + objectness BCE (obj + lambda_noobj * noobj) + class CE on
    responsible cells. Simplified from the reference: IoU-based anchor
    assignment is expected to be done by the label encoder.
    """

    anchors: Any = ((1.0, 1.0),)
    n_classes: int = 0
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return x, state

    def activate(self, params, x):
        return x  # raw predictions; use activate_boxes() to decode

    def activate_boxes(self, x):
        b, h, w, _ = x.shape
        a = len(self.anchors)
        p = x.reshape(b, h, w, a, 5 + self.n_classes)
        xy = jax.nn.sigmoid(p[..., 0:2])
        wh = p[..., 2:4]
        obj = jax.nn.sigmoid(p[..., 4:5])
        cls = jax.nn.softmax(p[..., 5:], axis=-1) if self.n_classes else p[..., 5:]
        return xy, wh, obj, cls

    def compute_loss(self, params, x, labels, mask=None, state=None):
        b, h, w, _ = x.shape
        a = len(self.anchors)
        p = x.reshape(b, h, w, a, 5 + self.n_classes)
        t = labels.reshape(b, h, w, a, 5 + self.n_classes)
        resp = t[..., 4]  # 1 where an object is assigned to this anchor
        xy_pred = jax.nn.sigmoid(p[..., 0:2])
        coord = jnp.sum(resp[..., None] * ((xy_pred - t[..., 0:2]) ** 2
                                           + (p[..., 2:4] - t[..., 2:4]) ** 2))
        obj_logit = p[..., 4]
        bce = jnp.maximum(obj_logit, 0) - obj_logit * resp + jnp.log1p(
            jnp.exp(-jnp.abs(obj_logit)))
        obj_loss = jnp.sum(resp * bce) + self.lambda_noobj * jnp.sum((1 - resp) * bce)
        cls_loss = 0.0
        if self.n_classes:
            logp = jax.nn.log_softmax(p[..., 5:], axis=-1)
            cls_loss = -jnp.sum(resp[..., None] * t[..., 5:] * logp)
        # Loss is averaged over the minibatch only (the reference's score
        # convention); per-object normalisation is deliberately not applied.
        return (self.lambda_coord * coord + obj_loss + cls_loss) / (b * 1.0)


@register_layer
@dataclasses.dataclass
class LocallyConnected1D(Layer):
    """1-D conv with UNSHARED weights per output position (reference
    ``LocallyConnected1D``) via ``lax.conv_general_dilated_local`` on a
    width-1 2-D input."""

    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    has_bias: bool = True

    def _geom(self, it: InputType):
        k = int(self.kernel_size if not isinstance(self.kernel_size, (tuple, list))
                else self.kernel_size[0])
        s = int(self.stride if not isinstance(self.stride, (tuple, list))
                else self.stride[0])
        ot = (it.timesteps - k) // s + 1
        return k, s, ot

    def output_type(self, input_type: InputType) -> InputType:
        _, _, ot = self._geom(input_type)
        return InputType.recurrent(self.n_out, ot)

    def init(self, key, input_type, g: GlobalConfig):
        k, _, ot = self._geom(input_type)
        c_in = input_type.size
        params = {"W": init_weights(key, (ot, 1, c_in * k, self.n_out),
                                    self._winit(g), fan=(c_in * k, self.n_out),
                                    dtype=g.dtype)}
        if self.has_bias:
            params["b"] = jnp.full((ot, self.n_out), self._binit(g),
                                   g.dtype or jnp.float32)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        it = InputType.recurrent(x.shape[2], x.shape[1])
        k, s, _ = self._geom(it)
        y = lax.conv_general_dilated_local(
            x[:, :, None, :], params["W"], window_strides=(s, 1),
            padding="VALID", filter_shape=(k, 1),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))[:, :, 0, :]
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state


@register_layer
@dataclasses.dataclass
class SeparableConvolution1D(Layer):
    """Depthwise-separable 1-D conv (reference/Keras ``SeparableConv1D``):
    depthwise over time (feature_group_count) then pointwise 1x1."""

    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    convolution_mode: str = "same"
    depth_multiplier: int = 1
    has_bias: bool = True

    def _geom(self):
        k = int(self.kernel_size if not isinstance(self.kernel_size, (tuple, list))
                else self.kernel_size[0])
        s = int(self.stride if not isinstance(self.stride, (tuple, list))
                else self.stride[0])
        return k, s, self.convolution_mode.lower() == "same"

    def output_type(self, input_type: InputType) -> InputType:
        k, s, same = self._geom()
        t = input_type.timesteps
        t_out = None if t is None else (-(-t // s) if same else (t - k) // s + 1)
        return InputType.recurrent(self.n_out, t_out)

    def init(self, key, input_type, g: GlobalConfig):
        k, _, _ = self._geom()
        c_in = input_type.size
        dm = self.depth_multiplier
        k1, k2 = jax.random.split(key)
        params = {
            "W_depth": init_weights(k1, (k, 1, 1, c_in * dm), self._winit(g),
                                    fan=(k, k * dm), dtype=g.dtype),
            "W_point": init_weights(k2, (1, 1, c_in * dm, self.n_out),
                                    self._winit(g), fan=(c_in * dm, self.n_out),
                                    dtype=g.dtype),
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self._binit(g), dtype=g.dtype)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        k, s, same = self._geom()
        c_in = x.shape[-1]
        y = lax.conv_general_dilated(
            x[:, :, None, :], params["W_depth"], window_strides=(s, 1),
            padding="SAME" if same else "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c_in)
        y = lax.conv_general_dilated(
            y, params["W_point"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))[:, :, 0, :]
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state


@register_layer
@dataclasses.dataclass
class Subsampling1DLayer(Layer):
    """1-D max/avg pooling over (batch, time, features) (reference
    ``Subsampling1DLayer`` / Keras ``MaxPooling1D``/``AveragePooling1D``)."""

    pooling_type: str = "max"
    kernel_size: int = 2
    stride: int = 2
    convolution_mode: str = "truncate"

    def output_type(self, input_type: InputType) -> InputType:
        k, s = int(self.kernel_size), int(self.stride)
        t = input_type.timesteps
        same = self.convolution_mode.lower() == "same"
        t_out = None if t is None else (-(-t // s) if same else (t - k) // s + 1)
        return InputType.recurrent(input_type.size, t_out)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        k, s = int(self.kernel_size), int(self.stride)
        same = self.convolution_mode.lower() == "same"
        pt = str(self.pooling_type).lower()
        pad = "SAME" if same else "VALID"
        if pt == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, (1, k, 1), (1, s, 1), pad)
        else:
            y = lax.reduce_window(x, 0.0, lax.add, (1, k, 1), (1, s, 1), pad)
            # divide by the REAL window size (Keras/TF avg_pool excludes
            # padding) — ones-reduction gives the per-position counts
            cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                    (1, k, 1), (1, s, 1), pad)
            y = y / cnt
        return y, state

    def transform_mask(self, mask):
        if mask is None:
            return None
        k, s = int(self.kernel_size), int(self.stride)
        same = self.convolution_mode.lower() == "same"
        m = lax.reduce_window(mask.astype(jnp.float32), -jnp.inf, lax.max,
                              (1, k), (1, s), "SAME" if same else "VALID")
        return m


@register_layer
@dataclasses.dataclass
class PermuteLayer(Layer):
    """Permute non-batch axes (Keras ``Permute``; dims are 1-indexed like
    Keras)."""

    dims: Any = (2, 1)

    def output_type(self, input_type: InputType) -> InputType:
        d = tuple(int(v) for v in self.dims)
        if input_type.kind == "recurrent" and d == (2, 1):
            return InputType.recurrent(input_type.timesteps, input_type.size)
        if input_type.kind == "convolutional" and len(d) == 3:
            hwc = (input_type.height, input_type.width, input_type.channels)
            nh, nw, nc = (hwc[i - 1] for i in d)
            return InputType.convolutional(nh, nw, nc)
        if d == tuple(range(1, len(d) + 1)):
            return input_type  # identity permutation
        raise NotImplementedError(
            f"Permute(dims={d}) on {input_type.kind} input: output shape "
            "inference not implemented for this combination")

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        perm = (0,) + tuple(int(d) for d in self.dims)
        return jnp.transpose(x, perm), state


@register_layer
@dataclasses.dataclass
class ConvLSTM2D(Layer):
    """Convolutional LSTM (reference/Keras ``ConvLSTM2D``): LSTM whose
    input/recurrent transforms are SAME-padded 2-D convs; input
    (batch, time, H, W, C) — the convolutional3d layout with depth=time."""

    n_out: int = 0                 # filters
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    convolution_mode: str = "same"  # input-conv padding; recurrent conv is
    has_bias: bool = True           # always SAME/stride-1 on the output grid
    return_sequences: bool = True

    def _k(self):
        k = self.kernel_size
        return tuple(k) if isinstance(k, (tuple, list)) else (int(k), int(k))

    def _s(self):
        s = self.stride
        return tuple(s) if isinstance(s, (tuple, list)) else (int(s), int(s))

    def _out_hw(self, h, w):
        kh, kw = self._k()
        sh, sw = self._s()
        if self.convolution_mode.lower() == "same":
            return -(-h // sh), -(-w // sw)
        return (h - kh) // sh + 1, (w - kw) // sw + 1

    def output_type(self, input_type: InputType) -> InputType:
        oh, ow = self._out_hw(input_type.height, input_type.width)
        if self.return_sequences:
            return InputType.convolutional3d(input_type.depth, oh, ow,
                                             self.n_out)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, input_type, g: GlobalConfig):
        kh, kw = self._k()
        c_in = input_type.channels
        F = self.n_out
        k1, k2 = jax.random.split(key)
        params = {
            "W": init_weights(k1, (kh, kw, c_in, 4 * F), self._winit(g),
                              fan=(kh * kw * c_in, kh * kw * F), dtype=g.dtype),
            "W_rec": init_weights(k2, (kh, kw, F, 4 * F), self._winit(g),
                                  fan=(kh * kw * F, kh * kw * F), dtype=g.dtype),
        }
        if self.has_bias:
            # forget-gate bias 1.0 (keras unit_forget_bias default)
            b = jnp.zeros((4 * F,), g.dtype or jnp.float32)
            params["b"] = b.at[F:2 * F].set(1.0)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        F = self.n_out
        dn = ("NHWC", "HWIO", "NHWC")
        same = self.convolution_mode.lower() == "same"

        def conv(v, w, strides=(1, 1), pad="SAME"):
            return lax.conv_general_dilated(v, w, strides, pad,
                                            dimension_numbers=dn)

        b = params.get("b", 0.0)
        n, t = x.shape[0], x.shape[1]
        # hoist the input conv over the whole sequence (one big MXU conv)
        zx = conv(x.reshape((n * t,) + x.shape[2:]), params["W"],
                  strides=self._s(), pad="SAME" if same else "VALID") + b
        zx = zx.reshape((n, t) + zx.shape[1:]).swapaxes(0, 1)  # (T,B,H,W,4F)
        h0 = jnp.zeros(zx.shape[1:-1] + (F,), x.dtype)
        c0 = jnp.zeros_like(h0)

        def step(hc, z):
            h, c = hc
            z = z + conv(h, params["W_rec"])
            i = jax.nn.sigmoid(z[..., :F])
            f = jax.nn.sigmoid(z[..., F:2 * F])
            g_ = jnp.tanh(z[..., 2 * F:3 * F])
            o = jax.nn.sigmoid(z[..., 3 * F:])
            c_new = f * c + i * g_
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        (hT, _), ys = lax.scan(step, (h0, c0), zx)
        if self.return_sequences:
            return ys.swapaxes(0, 1), state
        return hT, state
