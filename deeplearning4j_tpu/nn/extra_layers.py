"""Remaining reference layer types: 3-D convolution family, cropping,
locally-connected, center-loss output, YOLOv2 detection output.

Reference classes: ``Convolution3D``, ``Subsampling3DLayer``,
``Upsampling1D/3D``, ``Cropping2D``, ``LocallyConnected2D``,
``CenterLossOutputLayer``, ``Yolo2OutputLayer``
(upstream ``org.deeplearning4j.nn.conf.layers`` + ``...layers.objdetect``).

Layouts: 3-D convs use NDHWC (channels-last, TPU-native).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer, register_layer
from deeplearning4j_tpu.nn.core_layers import OutputLayer
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.ops.losses import compute_loss


def _triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


@register_layer
@dataclasses.dataclass
class Convolution3D(Layer):
    """3-D conv over (batch, depth, height, width, channels), DHWIO kernel."""

    n_out: int = 0
    kernel_size: Any = (3, 3, 3)
    stride: Any = (1, 1, 1)
    convolution_mode: str = "same"
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        kd, kh, kw = _triple(self.kernel_size)
        sd, sh, sw = _triple(self.stride)
        same = self.convolution_mode.lower() == "same"

        def osz(size, k, s):
            return -(-size // s) if same else (size - k) // s + 1

        return InputType.convolutional3d(osz(input_type.depth, kd, sd),
                                         osz(input_type.height, kh, sh),
                                         osz(input_type.width, kw, sw), self.n_out)

    def init(self, key, input_type, g: GlobalConfig):
        kd, kh, kw = _triple(self.kernel_size)
        c_in = input_type.channels
        fan_in = kd * kh * kw * c_in
        params = {"W": init_weights(key, (kd, kh, kw, c_in, self.n_out), self._winit(g),
                                    fan=(fan_in, kd * kh * kw * self.n_out), dtype=g.dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self._binit(g), g.dtype or jnp.float32)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        same = self.convolution_mode.lower() == "same"
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=_triple(self.stride),
            padding="SAME" if same else "VALID",
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state


@register_layer
@dataclasses.dataclass
class Subsampling3DLayer(Layer):
    pooling_type: str = "max"
    kernel_size: Any = (2, 2, 2)
    stride: Any = (2, 2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        kd, kh, kw = _triple(self.kernel_size)
        sd, sh, sw = _triple(self.stride)
        return InputType.convolutional3d((input_type.depth - kd) // sd + 1,
                                         (input_type.height - kh) // sh + 1,
                                         (input_type.width - kw) // sw + 1,
                                         input_type.channels)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        dims = (1, *_triple(self.kernel_size), 1)
        strides = (1, *_triple(self.stride), 1)
        if self.pooling_type.lower() == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, "VALID"), state
        s = lax.reduce_window(x, 0.0, lax.add, dims, strides, "VALID")
        n = 1
        for k in _triple(self.kernel_size):
            n *= k
        return s / n, state


@register_layer
@dataclasses.dataclass
class Upsampling1D(Layer):
    size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        t = None if input_type.timesteps is None else input_type.timesteps * self.size
        return InputType.recurrent(input_type.size, t)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return jnp.repeat(x, self.size, axis=1), state

    def transform_mask(self, mask):
        return None if mask is None else jnp.repeat(mask, self.size, axis=1)


@register_layer
@dataclasses.dataclass
class Upsampling3D(Layer):
    size: Any = (2, 2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        sd, sh, sw = _triple(self.size)
        return InputType.convolutional3d(input_type.depth * sd, input_type.height * sh,
                                         input_type.width * sw, input_type.channels)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        sd, sh, sw = _triple(self.size)
        x = jnp.repeat(x, sd, axis=1)
        x = jnp.repeat(x, sh, axis=2)
        return jnp.repeat(x, sw, axis=3), state


@register_layer
@dataclasses.dataclass
class Cropping2D(Layer):
    """Crop spatial borders (reference ``Cropping2D``)."""

    crop: Any = (0, 0)  # (top/bottom, left/right) or ((t,b),(l,r))

    def _crops(self):
        c = self.crop
        if isinstance(c, (tuple, list)) and len(c) == 2 and isinstance(c[0], (tuple, list)):
            return tuple(c[0]), tuple(c[1])
        a, b = (c, c) if isinstance(c, int) else c
        return (a, a), (b, b)

    def output_type(self, input_type: InputType) -> InputType:
        (t, b), (l, r) = self._crops()
        return InputType.convolutional(input_type.height - t - b,
                                       input_type.width - l - r, input_type.channels)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        (t, b), (l, r) = self._crops()
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b or None, l:w - r or None, :], state


@register_layer
@dataclasses.dataclass
class LocallyConnected2D(Layer):
    """Conv with UNSHARED weights per output position (reference
    ``LocallyConnected2D``) via ``lax.conv_general_dilated_local``."""

    n_out: int = 0
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    has_bias: bool = True

    def _geom(self, it: InputType):
        kh, kw = (self.kernel_size if isinstance(self.kernel_size, (tuple, list))
                  else (self.kernel_size,) * 2)
        sh, sw = (self.stride if isinstance(self.stride, (tuple, list))
                  else (self.stride,) * 2)
        oh = (it.height - kh) // sh + 1
        ow = (it.width - kw) // sw + 1
        return int(kh), int(kw), int(sh), int(sw), oh, ow

    def output_type(self, input_type: InputType) -> InputType:
        *_, oh, ow = self._geom(input_type)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, input_type, g: GlobalConfig):
        kh, kw, _, _, oh, ow = self._geom(input_type)
        c_in = input_type.channels
        # filter shape for conv_general_dilated_local (spatial..., c_in*kh*kw, c_out)
        params = {"W": init_weights(key, (oh, ow, c_in * kh * kw, self.n_out),
                                    self._winit(g), fan=(c_in * kh * kw, self.n_out),
                                    dtype=g.dtype)}
        if self.has_bias:
            params["b"] = jnp.full((oh, ow, self.n_out), self._binit(g),
                                   g.dtype or jnp.float32)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        kh, kw, sh, sw, _, _ = self._geom(
            InputType.convolutional(x.shape[1], x.shape[2], x.shape[3]))
        y = lax.conv_general_dilated_local(
            x, params["W"], window_strides=(sh, sw), padding="VALID",
            filter_shape=(kh, kw), dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state


@register_layer
@dataclasses.dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (reference ``CenterLossOutputLayer``):
    L = CE + (lambda/2)·||f - c_y||²; per-class centers kept in layer state
    and updated with rate ``alpha`` toward the batch features."""

    alpha: float = 0.05
    lambda_: float = 2e-4

    def init(self, key, input_type, g: GlobalConfig):
        params, state = super().init(key, input_type, g)
        n_in = self._nin(input_type)
        state = dict(state)
        state["centers"] = jnp.zeros((self.n_out, n_in), jnp.float32)
        return params, state

    def update_state_with_labels(self, state, x, labels):
        """EMA center update toward the batch's class means (the reference's
        center update rule); called by the network's loss path where labels
        are available."""
        centers = state["centers"]
        onehot = labels.astype(jnp.float32)
        counts = jnp.sum(onehot, axis=0)  # (C,)
        sums = onehot.T @ x.astype(jnp.float32)  # (C, n_in)
        means = sums / jnp.maximum(counts, 1.0)[:, None]
        updated = jnp.where(counts[:, None] > 0,
                            centers + self.alpha * (means - centers), centers)
        return {**state, "centers": updated}

    def compute_loss(self, params, x, labels, mask=None, state=None):
        ce = compute_loss(self.loss, labels, self.preoutput(params, x),
                          activation=self._act(self._g), mask=mask)
        if not state or "centers" not in state:
            # centers live in model_state, passed by the network's loss path;
            # standalone calls without state skip the center term.
            return ce
        idx = jnp.argmax(labels, axis=-1)
        centers = jax.lax.stop_gradient(
            jnp.take(state["centers"], idx, axis=0).astype(x.dtype))
        diff = x - centers
        center_term = 0.5 * self.lambda_ * jnp.mean(jnp.sum(diff * diff, axis=-1))
        return ce + center_term


@register_layer
@dataclasses.dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 detection loss (reference
    ``org.deeplearning4j.nn.layers.objdetect.Yolo2OutputLayer``).

    Input: (batch, H, W, A*(5+C)) raw predictions with A anchor boxes.
    Labels: same-shaped tensor where, per assigned anchor cell,
    channels are [tx, ty, tw, th, objectness(0/1), class one-hot...].
    Loss = coord (MSE on xy via sigmoid, wh via raw) * lambda_coord
         + objectness BCE (obj + lambda_noobj * noobj) + class CE on
    responsible cells. Simplified from the reference: IoU-based anchor
    assignment is expected to be done by the label encoder.
    """

    anchors: Any = ((1.0, 1.0),)
    n_classes: int = 0
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return x, state

    def activate(self, params, x):
        return x  # raw predictions; use activate_boxes() to decode

    def activate_boxes(self, x):
        b, h, w, _ = x.shape
        a = len(self.anchors)
        p = x.reshape(b, h, w, a, 5 + self.n_classes)
        xy = jax.nn.sigmoid(p[..., 0:2])
        wh = p[..., 2:4]
        obj = jax.nn.sigmoid(p[..., 4:5])
        cls = jax.nn.softmax(p[..., 5:], axis=-1) if self.n_classes else p[..., 5:]
        return xy, wh, obj, cls

    def compute_loss(self, params, x, labels, mask=None, state=None):
        b, h, w, _ = x.shape
        a = len(self.anchors)
        p = x.reshape(b, h, w, a, 5 + self.n_classes)
        t = labels.reshape(b, h, w, a, 5 + self.n_classes)
        resp = t[..., 4]  # 1 where an object is assigned to this anchor
        xy_pred = jax.nn.sigmoid(p[..., 0:2])
        coord = jnp.sum(resp[..., None] * ((xy_pred - t[..., 0:2]) ** 2
                                           + (p[..., 2:4] - t[..., 2:4]) ** 2))
        obj_logit = p[..., 4]
        bce = jnp.maximum(obj_logit, 0) - obj_logit * resp + jnp.log1p(
            jnp.exp(-jnp.abs(obj_logit)))
        obj_loss = jnp.sum(resp * bce) + self.lambda_noobj * jnp.sum((1 - resp) * bce)
        cls_loss = 0.0
        if self.n_classes:
            logp = jax.nn.log_softmax(p[..., 5:], axis=-1)
            cls_loss = -jnp.sum(resp[..., None] * t[..., 5:] * logp)
        # Loss is averaged over the minibatch only (the reference's score
        # convention); per-object normalisation is deliberately not applied.
        return (self.lambda_coord * coord + obj_loss + cls_loss) / (b * 1.0)
