"""Misc parity layers.

Rebuild of upstream layers not covered elsewhere:
``PReLULayer``, ``ElementWiseMultiplicationLayer``
(``org.deeplearning4j.nn.conf.layers.misc``), ``RepeatVector``,
``MaskZeroLayer`` + ``TimeDistributed`` wrappers
(``org.deeplearning4j.nn.conf.layers.{util,recurrent}``), and 1-D
cropping/padding (``Cropping1D``, ``ZeroPadding1DLayer``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer, register_layer
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.initializers import init_weights


@register_layer
@dataclasses.dataclass
class PReLULayer(Layer):
    """Parametric ReLU: y = max(0, x) + alpha * min(0, x) with learned
    ``alpha`` (reference ``PReLULayer``). ``alpha`` has the input's feature
    shape except axes listed in ``shared_axes`` (1-based over non-batch dims,
    matching the reference), which are broadcast."""

    shared_axes: Tuple[int, ...] = ()

    def _alpha_shape(self, input_type: InputType) -> Tuple[int, ...]:
        shape = list(input_type.array_shape(batch=1)[1:])
        for ax in self.shared_axes:
            shape[ax - 1] = 1
        return tuple(shape)

    def init(self, key, input_type, g: GlobalConfig):
        return {"alpha": jnp.zeros(self._alpha_shape(input_type), dtype=g.dtype)}, {}

    def regularizable_params(self):
        return ()

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        a = params["alpha"]
        return jnp.maximum(x, 0) + a * jnp.minimum(x, 0), state


@register_layer
@dataclasses.dataclass
class ElementWiseMultiplicationLayer(Layer):
    """y = act(x * w + b) with a per-feature weight vector (reference
    ``ElementWiseMultiplicationLayer``; nIn == nOut)."""

    n_out: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key, input_type, g: GlobalConfig):
        n = input_type.size
        if self.n_out and self.n_out != n:
            raise ValueError(
                f"ElementWiseMultiplicationLayer requires nIn == nOut "
                f"(got input size {n}, n_out {self.n_out})")
        return {"W": init_weights(key, (n,), self._winit(g), fan=(n, n),
                                  dtype=g.dtype),
                "b": jnp.full((n,), self._binit(g), dtype=g.dtype)}, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        return get_activation(self._act(self._g))(x * params["W"] + params["b"]), state


@register_layer
@dataclasses.dataclass
class RepeatVector(Layer):
    """(batch, size) -> (batch, n, size) (reference ``RepeatVector``)."""

    n: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.size, self.n)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return jnp.repeat(x[:, None, :], self.n, axis=1), state


def _wrap_serde(cls):
    """from_dict support for wrapper layers holding an ``underlying`` layer."""
    orig = cls.from_dict.__func__

    def from_dict(kls, d):
        layer = orig(kls, d)
        if isinstance(layer.underlying, dict):
            layer.underlying = Layer.from_dict(layer.underlying)
        return layer

    cls.from_dict = classmethod(from_dict)
    return cls


@register_layer
@_wrap_serde
@dataclasses.dataclass
class MaskZeroLayer(Layer):
    """Wrapper: where the sequence mask is 0, replace the wrapped layer's
    input with ``masking_value`` (reference ``MaskZeroLayer``)."""

    underlying: Any = None
    masking_value: float = 0.0

    def output_type(self, input_type: InputType) -> InputType:
        return self.underlying.output_type(input_type)

    def init(self, key, input_type, g: GlobalConfig):
        self.underlying._g = g
        return self.underlying.init(key, input_type, g)

    def regularizable_params(self):
        return self.underlying.regularizable_params()

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        if mask is not None:
            m = mask[..., None].astype(x.dtype)
            x = x * m + self.masking_value * (1.0 - m)
        self.underlying._g = self._g
        return self.underlying.forward(params, state, x, training=training,
                                       rng=rng, mask=mask)


@register_layer
@_wrap_serde
@dataclasses.dataclass
class TimeDistributed(Layer):
    """Apply a feed-forward layer independently at every timestep of a
    (batch, time, size) input by folding time into batch (reference
    ``TimeDistributed``). XLA sees one big batched matmul, not a time loop."""

    underlying: Any = None

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.underlying.output_type(InputType.feed_forward(input_type.size))
        return InputType.recurrent(inner.size, input_type.timesteps)

    def init(self, key, input_type, g: GlobalConfig):
        self.underlying._g = g
        return self.underlying.init(key, InputType.feed_forward(input_type.size), g)

    def regularizable_params(self):
        return self.underlying.regularizable_params()

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        b, t = x.shape[0], x.shape[1]
        self.underlying._g = self._g
        y, s = self.underlying.forward(params, state, x.reshape(b * t, -1),
                                       training=training, rng=rng, mask=None)
        return y.reshape(b, t, -1), s


@register_layer
@dataclasses.dataclass
class Cropping1D(Layer):
    """Crop timesteps from a (batch, time, size) input (reference
    ``Cropping1D``)."""

    crop_left: int = 0
    crop_right: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        return InputType.recurrent(
            input_type.size,
            None if t is None else t - self.crop_left - self.crop_right)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        end = x.shape[1] - self.crop_right
        return x[:, self.crop_left:end, :], state

    def transform_mask(self, mask):
        if mask is None:
            return None
        end = mask.shape[1] - self.crop_right
        return mask[:, self.crop_left:end]


@register_layer
@dataclasses.dataclass
class ZeroPadding1DLayer(Layer):
    """Zero-pad timesteps of a (batch, time, size) input (reference
    ``ZeroPadding1DLayer``). Padded timesteps count as valid data (zeros),
    so the mask is padded with ones — matching the reference, where padding
    layers extend the data, not the invalid region."""

    pad_left: int = 0
    pad_right: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        return InputType.recurrent(
            input_type.size,
            None if t is None else t + self.pad_left + self.pad_right)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return jnp.pad(x, ((0, 0), (self.pad_left, self.pad_right), (0, 0))), state

    def transform_mask(self, mask):
        if mask is None:
            return None
        return jnp.pad(mask, ((0, 0), (self.pad_left, self.pad_right)),
                       constant_values=1.0)


# ---------------------------------------------------------------- Lambda

# Named registry for user-defined lambda functions (the reference's
# ``KerasLayer.registerLambdaLayer(name, SameDiffLambdaLayer)``: Keras never
# serializes Lambda code, so imports resolve them by layer NAME from a
# registry the user populates before loading).
_LAMBDA_REGISTRY: dict = {}


def register_lambda(name: str, fn) -> None:
    """Register ``fn(x) -> y`` under ``name`` for :class:`LambdaLayer`
    revival (model import and config deserialization)."""
    _LAMBDA_REGISTRY[name] = fn


def get_lambda(name: str):
    if name not in _LAMBDA_REGISTRY:
        raise KeyError(
            f"Lambda {name!r} not registered; call "
            f"register_lambda({name!r}, fn) before loading this model. "
            f"Registered: {sorted(_LAMBDA_REGISTRY)}")
    return _LAMBDA_REGISTRY[name]


@register_layer
@dataclasses.dataclass
class LambdaLayer(Layer):
    """Parameter-free layer wrapping an arbitrary jax-traceable function
    (reference ``SameDiffLambdaLayer`` / Keras ``Lambda`` import target).

    ``fn`` is code and is never serialized: configs round-trip ``fn_name``,
    and deserialization resolves it from :func:`register_lambda`'s registry
    — the reference's lambda-registry semantics."""

    fn: Any = None
    fn_name: Optional[str] = None
    out_size: Optional[int] = None  # output feature size if fn changes it

    def _fn(self):
        if self.fn is None:
            if self.fn_name is None:
                raise ValueError("LambdaLayer needs fn or a registered fn_name")
            self.fn = get_lambda(self.fn_name)
        return self.fn

    def output_type(self, input_type: InputType) -> InputType:
        if self.out_size is None:
            return input_type
        if input_type.kind == "recurrent":
            return InputType.recurrent(self.out_size, input_type.timesteps)
        return InputType.feed_forward(self.out_size)

    def init(self, key, input_type, g: GlobalConfig):
        return {}, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return self._fn()(x), state

    def to_dict(self) -> dict:
        d = super().to_dict()
        d.pop("fn", None)  # code is not data
        return d


@register_layer
@dataclasses.dataclass
class FlattenLayer(Layer):
    """Flatten all non-batch axes (Keras ``Flatten`` import target; row-major
    like Keras channels-last)."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.flat_size())

    def init(self, key, input_type, g: GlobalConfig):
        return {}, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return x.reshape(x.shape[0], -1), state

    def transform_mask(self, mask):
        return None  # time axis is folded away
