"""Attention / transformer layers.

The reference's attention surface is the SameDiff op
``multiHeadDotProductAttention`` (upstream
``org.nd4j.linalg.api.ops.impl.transforms.custom.MultiHeadDotProductAttention``,
used by imported BERT) plus the DL4J layers ``SelfAttentionLayer`` /
``LearnedSelfAttentionLayer`` (beta4+). Here attention is first-class: a
layer-API multi-head self-attention whose inner product can route through the
Pallas flash-attention kernel (``ops.pallas.flash_attention``) when shapes
warrant, and a full pre/post-LN transformer encoder block used by the zoo's
BERT.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.base import (GlobalConfig, Layer, dropout_mask,
                                        register_layer)
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.initializers import init_weights


def layer_norm(x, gamma, beta, eps=1e-12):
    # Shifted single-pass stats in f32 — one fused read of x (see
    # ops.activations.single_pass_norm_stats for the numerics rationale).
    from deeplearning4j_tpu.ops.activations import single_pass_norm_stats
    mean, var = single_pass_norm_stats(x, -1)
    y = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)
    return (y.astype(x.dtype)) * gamma + beta


def dot_product_attention(q, k, v, mask=None, use_flash: bool = True,
                          causal: bool = False):
    """(batch, heads, time, d) attention. Uses the Pallas flash kernel on TPU
    when available/shapes allow (incl. key-padding masks and causal), else
    the XLA softmax form."""
    if use_flash:
        try:
            from deeplearning4j_tpu.ops.pallas.flash_attention import flash_attention_compatible, flash_attention
            if flash_attention_compatible(q, k, v, mask, causal=causal):
                return flash_attention(q, k, v, mask, causal=causal)
            # NOTE: the short-T fused kernel
            # (ops.pallas.fused_attention_short) is DEPRECATED — never
            # routed here. The chain-amortised bench-of-record A/B reads
            # PARITY with XLA in isolation (0.98-1.01; the old "4x" was a
            # per-call wall timing that overcharged the multi-op XLA
            # reference for tunnel dispatch), and in-model it was a
            # measured NET LOSS on v5e (38 -> 51 ms/step for BERT-base):
            # each pallas_call boundary in the big traced step costs
            # ~0.5-0.7 ms of lost fusion/async-overlap, x24 calls. Same
            # composition failure as the round-3 custom_vjp batch-norm.
            # See BASELINE.md round-6 update.
        except Exception:
            pass
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if mask is not None:
        if mask.ndim == 2:  # (batch, t_k) key-padding form
            mask = mask[:, None, None, :]
        scores = jnp.where(mask, scores, jnp.asarray(-1e9, scores.dtype))
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        # bottom-right aligned triangle: for KV-cache decode (t_q < t_k) the
        # last query row attends every key (offset = t_k - t_q)
        tri = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        scores = jnp.where(tri[None, None], scores,
                           jnp.asarray(-1e9, scores.dtype))
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


@register_layer
@dataclasses.dataclass
class SelfAttentionLayer(Layer):
    """Multi-head self-attention over (batch, time, size) (reference
    ``SelfAttentionLayer`` / ``multiHeadDotProductAttention``)."""

    n_heads: int = 8
    head_size: Optional[int] = None  # default size/n_heads
    n_out: Optional[int] = None  # projection output, default = input size
    with_projection: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        out = self.n_out or input_type.size
        return InputType.recurrent(out, input_type.timesteps)

    def init(self, key, input_type, g: GlobalConfig):
        d_model = input_type.size
        hs = self.head_size or d_model // self.n_heads
        inner = self.n_heads * hs
        out = self.n_out or d_model
        ks = jax.random.split(key, 4)
        params = {
            "W_q": init_weights(ks[0], (d_model, inner), self._winit(g), fan=(d_model, inner), dtype=g.dtype),
            "W_k": init_weights(ks[1], (d_model, inner), self._winit(g), fan=(d_model, inner), dtype=g.dtype),
            "W_v": init_weights(ks[2], (d_model, inner), self._winit(g), fan=(d_model, inner), dtype=g.dtype),
            "b_q": jnp.zeros((inner,), g.dtype or jnp.float32),
            "b_k": jnp.zeros((inner,), g.dtype or jnp.float32),
            "b_v": jnp.zeros((inner,), g.dtype or jnp.float32),
        }
        if self.with_projection:
            params["W_o"] = init_weights(ks[3], (inner, out), self._winit(g), fan=(inner, out), dtype=g.dtype)
            params["b_o"] = jnp.zeros((out,), g.dtype or jnp.float32)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        b, t, _ = x.shape
        h = self.n_heads
        # NOTE on fused QKV: concatenating W_q|W_k|W_v into one matmul was
        # measured SLOWER on v5e (43.7 GB vs 40.5 GB accessed, 40.4 vs
        # 39.1 ms/step on BERT-base) — the fused weight and its gradient
        # materialize as extra traffic while XLA already schedules the three
        # shared-LHS matmuls back-to-back. Kept unfused deliberately.
        q = (x @ params["W_q"] + params["b_q"]).reshape(b, t, h, -1).transpose(0, 2, 1, 3)
        k = (x @ params["W_k"] + params["b_k"]).reshape(b, t, h, -1).transpose(0, 2, 1, 3)
        v = (x @ params["W_v"] + params["b_v"]).reshape(b, t, h, -1).transpose(0, 2, 1, 3)
        attn_mask = None
        if mask is not None:
            attn_mask = mask[:, None, None, :].astype(bool)  # key-side padding mask
        y = dot_product_attention(q, k, v, attn_mask)
        y = y.transpose(0, 2, 1, 3).reshape(b, t, -1)
        if self.with_projection:
            y = y @ params["W_o"] + params["b_o"]
        return y, state


@register_layer
@dataclasses.dataclass
class TransformerEncoderBlock(Layer):
    """Post-LN transformer encoder block (BERT-style): MHA + residual + LN,
    FFN(gelu) + residual + LN."""

    n_heads: int = 12
    ffn_size: int = 3072
    dropout_rate: float = 0.1  # drop probability (transformer convention)
    layer_norm_eps: float = 1e-12

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key, input_type, g: GlobalConfig):
        d = input_type.size
        attn = SelfAttentionLayer(n_heads=self.n_heads)
        attn._g = g
        ks = jax.random.split(key, 3)
        attn_params, _ = attn.init(ks[0], input_type, g)
        f = jnp.float32 if g.dtype is None else g.dtype
        params = {
            "attn": attn_params,
            "ln1_gamma": jnp.ones((d,), f), "ln1_beta": jnp.zeros((d,), f),
            "ln2_gamma": jnp.ones((d,), f), "ln2_beta": jnp.zeros((d,), f),
            "W_ff1": init_weights(ks[1], (d, self.ffn_size), self._winit(g), fan=(d, self.ffn_size), dtype=g.dtype),
            "b_ff1": jnp.zeros((self.ffn_size,), f),
            "W_ff2": init_weights(ks[2], (self.ffn_size, d), self._winit(g), fan=(self.ffn_size, d), dtype=g.dtype),
            "b_ff2": jnp.zeros((d,), f),
        }
        self._attn = attn
        return params, {}

    def _dropout_fn(self, x, training, rng):
        if not training or rng is None or self.dropout_rate <= 0.0:
            return x
        keep = 1.0 - self.dropout_rate
        mask = dropout_mask(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        attn = getattr(self, "_attn", None)
        if attn is None:
            attn = SelfAttentionLayer(n_heads=self.n_heads)
            self._attn = attn
        attn._g = self._g
        r1, r2 = (jax.random.split(rng) if rng is not None else (None, None))
        a, _ = attn.forward(params["attn"], {}, x, training=training, rng=None, mask=mask)
        x = layer_norm(x + self._dropout_fn(a, training, r1),
                       params["ln1_gamma"], params["ln1_beta"], self.layer_norm_eps)
        h = get_activation("gelu")(x @ params["W_ff1"] + params["b_ff1"])
        h = h @ params["W_ff2"] + params["b_ff2"]
        x = layer_norm(x + self._dropout_fn(h, training, r2),
                       params["ln2_gamma"], params["ln2_beta"], self.layer_norm_eps)
        return x, state

    def regularizable_params(self):
        return ("W_ff1", "W_ff2")


@register_layer
@dataclasses.dataclass
class TransformerEncoderStack(Layer):
    """``n_layers`` identical post-LN encoder blocks executed as ONE
    ``lax.scan`` over layer-stacked parameters.

    Why it exists: per-layer parameter pytrees cost real money on
    dispatch-latency-bound links (~400 buffer handles per BERT-base step
    = ~5.4 ms of host marshaling through the v5e tunnel) and in compile
    time (the scan body traces once: 28 s vs ~90 s full compile). Why it
    is NOT the zoo default: measured 48 vs 37 ms/step on v5e at BERT-base
    shape — ``lax.scan`` blocks XLA's inter-layer fusion/overlap and the
    scan backward stacks extra residual copies, costing more on-device
    than the dispatch saving. Pick it when compile time or dispatch
    latency dominates (very deep stacks, remote links). Same math as a
    stack of ``TransformerEncoderBlock``s; init draws the same
    distributions via a vmapped per-layer key split (exact draws differ
    from the sequential form).

    Per-layer dropout keys are folded from the step key inside the scan.
    """

    n_layers: int = 12
    n_heads: int = 12
    ffn_size: int = 3072
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-12

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def _block(self, g) -> TransformerEncoderBlock:
        blk = TransformerEncoderBlock(
            n_heads=self.n_heads, ffn_size=self.ffn_size,
            dropout_rate=self.dropout_rate,
            layer_norm_eps=self.layer_norm_eps)
        blk._g = g
        return blk

    def init(self, key, input_type, g: GlobalConfig):
        blk = self._block(g)

        def one(k):
            p, _ = blk.init(k, input_type, g)
            return p

        params = jax.vmap(one)(jax.random.split(key, self.n_layers))
        return {"stack": params}, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        blk = self._block(self._g)
        stack = params["stack"]
        if rng is not None:
            keys = jax.random.split(rng, self.n_layers)

            def body(carry, per):
                p, k = per
                y, _ = blk.forward(p, {}, carry, training=training,
                                   rng=k, mask=mask)
                return y, None

            y, _ = jax.lax.scan(body, x, (stack, keys))
        else:
            def body(carry, p):
                y, _ = blk.forward(p, {}, carry, training=training,
                                   rng=None, mask=mask)
                return y, None

            y, _ = jax.lax.scan(body, x, stack)
        return y, state

    def regularizable_params(self):
        # W_ff1/W_ff2 live under the stacked subtree, but both the l1/l2
        # walk and the weight-decay mask match by PATH COMPONENT, so the
        # per-block keys reach the stacked leaves; sum-of-squares over the
        # stacked array equals the per-layer sum — same penalty as the
        # discrete-block stack.
        return ("W_ff1", "W_ff2")


@register_layer
@dataclasses.dataclass
class BertEmbeddingLayer(Layer):
    """BERT input embeddings: token + learned position + segment embeddings,
    LayerNorm, dropout. Input: (batch, time) int32 token ids (single-segment;
    pair tasks feed segment ids via ComputationGraph with a second
    EmbeddingSequenceLayer). Reference path: TF-imported BERT's embedding
    lookup subgraph (SURVEY.md §3.3)."""

    vocab_size: int = 30522
    d_model: int = 768
    max_len: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-12

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps if input_type is not None else None
        return InputType.recurrent(self.d_model, t)

    def init(self, key, input_type, g: GlobalConfig):
        ks = jax.random.split(key, 3)
        f = jnp.float32 if g.dtype is None else g.dtype
        return {
            "tok": init_weights(ks[0], (self.vocab_size, self.d_model), self._winit(g),
                                fan=(self.vocab_size, self.d_model), dtype=g.dtype),
            "pos": init_weights(ks[1], (self.max_len, self.d_model), self._winit(g),
                                fan=(self.max_len, self.d_model), dtype=g.dtype),
            "seg": init_weights(ks[2], (self.type_vocab_size, self.d_model), self._winit(g),
                                fan=(self.type_vocab_size, self.d_model), dtype=g.dtype),
            "ln_gamma": jnp.ones((self.d_model,), f),
            "ln_beta": jnp.zeros((self.d_model,), f),
        }, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        ids = x.astype(jnp.int32)
        t = ids.shape[1]
        y = jnp.take(params["tok"], ids, axis=0)
        y = y + params["pos"][None, :t, :] + params["seg"][0][None, None, :]
        y = layer_norm(y, params["ln_gamma"], params["ln_beta"], self.layer_norm_eps)
        if training and rng is not None and self.dropout_rate > 0:
            keep = 1.0 - self.dropout_rate
            keep_mask = dropout_mask(rng, keep, y.shape)
            y = jnp.where(keep_mask, y / keep, 0.0).astype(y.dtype)
        return y, state

    def regularizable_params(self):
        return ()


@register_layer
@dataclasses.dataclass
class ClsPoolingLayer(Layer):
    """Extract one timestep (default 0 — BERT's [CLS]) from (batch, time, d)."""

    index: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.size)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return x[:, self.index], state


@register_layer
@dataclasses.dataclass
class LearnedPositionalEmbeddingLayer(Layer):
    """Adds learned positional embeddings (BERT position table)."""

    max_len: int = 512

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key, input_type, g: GlobalConfig):
        d = input_type.size
        return {"P": init_weights(key, (self.max_len, d), self._winit(g), fan=(self.max_len, d), dtype=g.dtype)}, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        t = x.shape[1]
        return x + params["P"][None, :t, :], state
