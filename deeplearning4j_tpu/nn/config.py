"""Network configuration builder.

Rebuild of upstream ``org.deeplearning4j.nn.conf.NeuralNetConfiguration`` /
``MultiLayerConfiguration``: fluent builder DSL producing a JSON-serializable
config tree ("configs are data" — the property that powers serialization,
hyperparameter search spaces, and the UI in the reference). Usage::

    conf = (NeuralNetConfiguration.builder()
            .seed(123)
            .updater(Adam(1e-3))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build())

Shape inference + automatic ``InputPreProcessor`` insertion happen at
``build()``, as in the reference.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer
from deeplearning4j_tpu.nn.conv_layers import (
    BatchNormalization,
    ConvolutionLayer,
    Convolution1DLayer,
    Deconvolution2D,
    GlobalPoolingLayer,
    LocalResponseNormalization,
    SeparableConvolution2D,
    SpaceToDepthLayer,
    SubsamplingLayer,
    Upsampling2D,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.core_layers import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    EmbeddingSequenceLayer,
)
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.preprocessors import (
    CnnToFeedForwardPreProcessor,
    FeedForwardToCnnPreProcessor,
    InputPreProcessor,
)
from deeplearning4j_tpu.nn.recurrent_layers import BaseRecurrentLayer, Bidirectional, RnnOutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.initializers import WeightInit

_CONV_LAYERS = (ConvolutionLayer, SubsamplingLayer, Upsampling2D, ZeroPaddingLayer,
                SpaceToDepthLayer, LocalResponseNormalization, Deconvolution2D,
                SeparableConvolution2D)
_ANY_LAYERS = (BatchNormalization, ActivationLayer, DropoutLayer, GlobalPoolingLayer)


def _expects(layer: Layer) -> Optional[str]:
    """What input kind a layer needs; None = accepts anything as-is."""
    if isinstance(layer, (Convolution1DLayer,)):
        return "recurrent"
    if isinstance(layer, _CONV_LAYERS):
        return "convolutional"
    if isinstance(layer, _ANY_LAYERS):
        return None
    if isinstance(layer, (BaseRecurrentLayer, Bidirectional, RnnOutputLayer)):
        return "recurrent"
    if isinstance(layer, (EmbeddingLayer, EmbeddingSequenceLayer)):
        return None  # integer index inputs; no reshape applies
    if isinstance(layer, DenseLayer):
        return "feedforward_or_recurrent"
    return None


class NeuralNetConfiguration:
    """Entry point: ``NeuralNetConfiguration.builder()``."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self):
        self._g = GlobalConfig()

    # fluent global defaults (names mirror the reference builder)
    def seed(self, s: int) -> "Builder":
        self._g.seed = int(s)
        return self

    def weight_init(self, wi) -> "Builder":
        self._g.weight_init = WeightInit(wi) if not isinstance(wi, WeightInit) else wi
        return self

    def activation(self, a) -> "Builder":
        self._g.activation = a
        return self

    def updater(self, u) -> "Builder":
        self._g.updater = u
        return self

    def l1(self, v: float) -> "Builder":
        self._g.l1 = float(v)
        return self

    def l2(self, v: float) -> "Builder":
        self._g.l2 = float(v)
        return self

    def weight_decay(self, v: float) -> "Builder":
        self._g.weight_decay = float(v)
        return self

    def dropout(self, retain_prob: float) -> "Builder":
        self._g.dropout = float(retain_prob)
        return self

    def bias_init(self, v: float) -> "Builder":
        self._g.bias_init = float(v)
        return self

    def gradient_normalization(self, kind: str, threshold: float = 1.0) -> "Builder":
        self._g.gradient_normalization = kind
        self._g.gradient_normalization_threshold = float(threshold)
        return self

    def optimization_algo(self, algo: str) -> "Builder":
        """Reference ``optimizationAlgo``: SGD (default) / LBFGS /
        CONJUGATE_GRADIENT / LINE_GRADIENT_DESCENT."""
        self._g.optimization_algo = str(algo).upper()
        return self

    def max_num_line_search_iterations(self, n: int) -> "Builder":
        self._g.max_num_line_search_iterations = int(n)
        return self

    def solver_iterations(self, n: int) -> "Builder":
        """Outer LBFGS/CG/line-GD iterations per batch (the reference's
        optimizer iteration loop)."""
        self._g.solver_iterations = int(n)
        return self

    def dtype(self, dt) -> "Builder":
        self._g.dtype = dt
        return self

    def list(self) -> "ListBuilder":
        return ListBuilder(self._g)

    def graph_builder(self):
        from deeplearning4j_tpu.models.computation_graph import GraphBuilder
        return GraphBuilder(self._g)


class ListBuilder:
    def __init__(self, g: GlobalConfig):
        self._g = g
        self._layers: List[Layer] = []
        self._input_type: Optional[InputType] = None
        self._preprocessors: Dict[int, InputPreProcessor] = {}
        self._tbptt_fwd: Optional[int] = None
        self._tbptt_back: Optional[int] = None

    def layer(self, *args) -> "ListBuilder":
        """``layer(l)`` appends; ``layer(i, l)`` sets index i (reference API)."""
        if len(args) == 1:
            self._layers.append(args[0])
        else:
            i, l = args
            while len(self._layers) <= i:
                self._layers.append(None)
            self._layers[i] = l
        return self

    def set_input_type(self, it: InputType) -> "ListBuilder":
        self._input_type = it
        return self

    def input_pre_processor(self, index: int, pp: InputPreProcessor) -> "ListBuilder":
        self._preprocessors[int(index)] = pp
        return self

    def tbptt_fwd_length(self, n: int) -> "ListBuilder":
        self._tbptt_fwd = int(n)
        return self

    def tbptt_back_length(self, n: int) -> "ListBuilder":
        self._tbptt_back = int(n)
        return self

    def build(self) -> "MultiLayerConfiguration":
        layers = [l for l in self._layers if l is not None]
        if not layers:
            raise ValueError("No layers configured")
        conf = MultiLayerConfiguration(
            global_conf=self._g, layers=layers, input_type=self._input_type,
            preprocessors=dict(self._preprocessors),
            tbptt_fwd_length=self._tbptt_fwd, tbptt_back_length=self._tbptt_back)
        conf._infer_shapes()
        return conf


@dataclasses.dataclass
class MultiLayerConfiguration:
    global_conf: GlobalConfig
    layers: List[Layer]
    input_type: Optional[InputType] = None
    preprocessors: Dict[int, InputPreProcessor] = dataclasses.field(default_factory=dict)
    tbptt_fwd_length: Optional[int] = None
    tbptt_back_length: Optional[int] = None
    # computed by _infer_shapes: input type FED TO each layer (post-preprocessor)
    layer_input_types: List[InputType] = dataclasses.field(default_factory=list)

    def _infer_shapes(self) -> None:
        """Walk the stack once: auto-insert preprocessors on InputType
        mismatches and record each layer's input type (reference:
        ``MultiLayerConfiguration`` + ``InputType.getPreProcessorForInputType``)."""
        self.layer_input_types = []
        cur = self.input_type
        for i, layer in enumerate(self.layers):
            if cur is not None and i not in self.preprocessors:
                pp = self._auto_preprocessor(cur, layer)
                if pp is not None:
                    self.preprocessors[i] = pp
            if i in self.preprocessors and cur is not None:
                cur = self.preprocessors[i].output_type(cur)
            self.layer_input_types.append(cur)
            if cur is not None:
                cur = layer.output_type(cur)
        self.output_type = cur

    @staticmethod
    def _auto_preprocessor(cur: InputType, layer: Layer) -> Optional[InputPreProcessor]:
        need = _expects(layer)
        if need is None:
            return None
        if need == "convolutional" and cur.kind == "convolutional_flat":
            return FeedForwardToCnnPreProcessor(cur.height, cur.width, cur.channels)
        if need in ("feedforward_or_recurrent",) and cur.kind == "convolutional":
            return CnnToFeedForwardPreProcessor(cur.height, cur.width, cur.channels)
        if need in ("feedforward_or_recurrent",) and cur.kind == "convolutional3d":
            # same flatten; Cnn3DToFeedForward in the reference
            return CnnToFeedForwardPreProcessor(cur.height, cur.width, cur.channels)
        if need == "convolutional" and cur.kind == "feedforward":
            raise ValueError(
                "Cannot infer image shape for conv layer from flat feed-forward input; "
                "use InputType.convolutional_flat(h, w, c)")
        return None

    # ---- serde (reference: MultiLayerConfiguration.toJson/fromJson) ----
    def to_dict(self) -> dict:
        g = dataclasses.asdict(self.global_conf)
        if self.global_conf.updater is not None and hasattr(self.global_conf.updater, "to_dict"):
            g["updater"] = self.global_conf.updater.to_dict()
        for k in ("weight_init", "activation"):
            if isinstance(g.get(k), (WeightInit, Activation)):
                g[k] = g[k].value
        if g.get("dtype") is not None:
            import jax.numpy as jnp
            g["dtype"] = jnp.dtype(g["dtype"]).name
        return {
            "global_conf": g,
            "layers": [l.to_dict() for l in self.layers],
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "preprocessors": {str(k): v.to_dict() for k, v in self.preprocessors.items()},
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        g_d = dict(d["global_conf"])
        if isinstance(g_d.get("updater"), dict):
            from deeplearning4j_tpu.train.updaters import Updater
            g_d["updater"] = Updater.from_dict(g_d["updater"])
        if g_d.get("weight_init"):
            g_d["weight_init"] = WeightInit(g_d["weight_init"])
        if isinstance(g_d.get("dtype"), str):
            import jax.numpy as jnp
            g_d["dtype"] = jnp.dtype(g_d["dtype"]).type
        g = GlobalConfig(**{k: v for k, v in g_d.items()
                            if k in {f.name for f in dataclasses.fields(GlobalConfig)}})
        conf = MultiLayerConfiguration(
            global_conf=g,
            layers=[Layer.from_dict(ld) for ld in d["layers"]],
            input_type=InputType.from_dict(d["input_type"]) if d.get("input_type") else None,
            preprocessors={int(k): InputPreProcessor.from_dict(v)
                           for k, v in (d.get("preprocessors") or {}).items()},
            tbptt_fwd_length=d.get("tbptt_fwd_length"),
            tbptt_back_length=d.get("tbptt_back_length"),
        )
        conf._infer_shapes()
        return conf

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))
