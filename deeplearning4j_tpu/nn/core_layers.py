"""Core feed-forward layers.

Rebuild of upstream ``org.deeplearning4j.nn.conf.layers`` core set:
``DenseLayer``, ``OutputLayer``, ``LossLayer``, ``ActivationLayer``,
``DropoutLayer``, ``EmbeddingLayer``, ``EmbeddingSequenceLayer``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.base import (GlobalConfig, Layer, dropout_mask,
                                        register_layer)
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.ops.losses import LossFunction, compute_loss


@register_layer
@dataclasses.dataclass
class DenseLayer(Layer):
    """Fully-connected layer: y = act(x @ W + b). W: (nIn, nOut)."""

    n_out: int = 0
    n_in: Optional[int] = None  # inferred from input type when None
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            # time-distributed dense, like the reference's dense-on-rank3
            return InputType.recurrent(self.n_out, input_type.timesteps)
        return InputType.feed_forward(self.n_out)

    def _nin(self, input_type: InputType) -> int:
        if self.n_in is not None:
            return self.n_in
        return input_type.size if input_type.kind in ("feedforward", "recurrent") \
            else input_type.flat_size()

    def init(self, key, input_type, g: GlobalConfig):
        n_in = self._nin(input_type)
        k1, _ = jax.random.split(key)
        params = {"W": init_weights(k1, (n_in, self.n_out), self._winit(g),
                                    fan=(n_in, self.n_out), dtype=g.dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self._binit(g), dtype=g.dtype)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state


@register_layer
@dataclasses.dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (reference ``OutputLayer``): the network's training
    loss is computed from this layer's *pre-activation* with the configured
    loss function fused with the activation for numerical stability."""

    loss: Any = LossFunction.MCXENT

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        # Activation applied here for inference; training loss uses preoutput.
        return get_activation(self._act(self._g))(y), state

    def preoutput(self, params, x):
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y

    def activate(self, params, x):
        """Forward WITHOUT input dropout — used by the network after it has
        already applied this layer's input dropout (so the training loss and
        the forward output see the same dropped input)."""
        return get_activation(self._act(self._g))(self.preoutput(params, x))

    def compute_loss(self, params, x, labels, mask=None, state=None):
        return compute_loss(self.loss, labels, self.preoutput(params, x),
                            activation=self._act(self._g), mask=mask)


@register_layer
@dataclasses.dataclass
class LossLayer(Layer):
    """Loss without params (reference ``LossLayer``): applies activation +
    loss to its input directly."""

    loss: Any = LossFunction.MCXENT

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return get_activation(self._act(self._g))(x), state

    def activate(self, params, x):
        return get_activation(self._act(self._g))(x)

    def compute_loss(self, params, x, labels, mask=None, state=None):
        return compute_loss(self.loss, labels, x, activation=self._act(self._g), mask=mask)



@register_layer
@dataclasses.dataclass
class ActivationLayer(Layer):
    """Standalone activation (reference ``ActivationLayer``)."""

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        return get_activation(self._act(self._g))(x), state



@register_layer
@dataclasses.dataclass
class DropoutLayer(Layer):
    """Standalone dropout (reference ``DropoutLayer``). ``dropout`` field is
    the retain probability (DL4J convention)."""

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        p = self._dropout(self._g) or 0.5
        if not training or rng is None or p >= 1.0:
            return x, state
        keep = dropout_mask(rng, p, x.shape)
        return jnp.where(keep, x / p, 0.0).astype(x.dtype), state



@register_layer
@dataclasses.dataclass
class EmbeddingLayer(Layer):
    """Index -> vector lookup (reference ``EmbeddingLayer``): input is
    (batch,) or (batch, 1) int indices; output (batch, nOut). Equivalent to a
    one-hot matmul but executed as a gather."""

    n_in: int = 0  # vocab size
    n_out: int = 0
    has_bias: bool = False

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type, g: GlobalConfig):
        params = {"W": init_weights(key, (self.n_in, self.n_out), self._winit(g),
                                    fan=(self.n_in, self.n_out), dtype=g.dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self._binit(g), dtype=g.dtype)
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        y = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self._act(self._g))(y), state



@register_layer
@dataclasses.dataclass
class EmbeddingSequenceLayer(Layer):
    """Sequence of indices -> sequence of vectors (reference
    ``EmbeddingSequenceLayer``): (batch, time) ints -> (batch, time, nOut)."""

    n_in: int = 0
    n_out: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps if input_type.kind == "recurrent" else None
        return InputType.recurrent(self.n_out, t)

    def init(self, key, input_type, g: GlobalConfig):
        return {"W": init_weights(key, (self.n_in, self.n_out), self._winit(g),
                                  fan=(self.n_in, self.n_out), dtype=g.dtype)}, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        y = jnp.take(params["W"], x.astype(jnp.int32), axis=0)
        return get_activation(self._act(self._g))(y), state

