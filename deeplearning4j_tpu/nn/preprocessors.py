"""Input pre-processors: shape adapters auto-inserted between layers.

Rebuild of upstream ``org.deeplearning4j.nn.conf.preprocessor`` —
``CnnToFeedForwardPreProcessor``, ``FeedForwardToCnnPreProcessor``,
``RnnToFeedForwardPreProcessor``, ``FeedForwardToRnnPreProcessor``,
``CnnToRnnPreProcessor``, ``RnnToCnnPreProcessor``. As in the reference,
``ListBuilder.build()`` inserts these automatically from ``InputType``
mismatches; they are pure reshapes that XLA folds away.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Type

import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType

_PREPROC_REGISTRY: Dict[str, Type["InputPreProcessor"]] = {}


def register_preproc(cls):
    _PREPROC_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class InputPreProcessor:
    def pre_process(self, x, mask=None):
        raise NotImplementedError

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["@type"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "InputPreProcessor":
        d = dict(d)
        cls = _PREPROC_REGISTRY[d.pop("@type")]
        return cls(**d)


@register_preproc
@dataclasses.dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x, mask=None):
        return x.reshape(x.shape[0], -1)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.flat_size())


@register_preproc
@dataclasses.dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x, mask=None):
        if x.ndim == 2:
            return x.reshape(x.shape[0], self.height, self.width, self.channels)
        return x

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preproc
@dataclasses.dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """(batch, time, size) -> (batch*time, size). With our time-distributed
    dense layers this is rarely needed, but kept for reference parity."""

    def pre_process(self, x, mask=None):
        return x.reshape(-1, x.shape[-1])

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.size)


@register_preproc
@dataclasses.dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    timesteps: Optional[int] = None

    def pre_process(self, x, mask=None):
        if x.ndim == 2 and self.timesteps:
            return x.reshape(-1, self.timesteps, x.shape[-1])
        return x

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.size, self.timesteps)


@register_preproc
@dataclasses.dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """(batch, h, w, c) -> (batch, h, w*c) treating height as time."""

    def pre_process(self, x, mask=None):
        b, h, w, c = x.shape
        return x.reshape(b, h, w * c)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.width * input_type.channels,
                                   input_type.height)


@register_preproc
@dataclasses.dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x, mask=None):
        b, t, _ = x.shape
        return x.reshape(b * t, self.height, self.width, self.channels)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)
