"""Neural-network configuration DSL: config-as-data layers + shape inference.

TPU-native rebuild of the reference's ``org.deeplearning4j.nn.conf`` package:
builder-style, JSON-round-trippable layer configs with an ``InputType`` shape
inference system and automatic ``InputPreProcessor`` insertion. Unlike the
reference there are no separate conf/impl class pairs — a layer config *is*
the implementation (pure ``init``/``forward`` functions), and the whole
network forward composes into one XLA program.
"""

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer, get_layer_class, register_layer
from deeplearning4j_tpu.nn.constraints import (
    DropConnect,
    MaxNormConstraint,
    MinMaxNormConstraint,
    NonNegativeConstraint,
    UnitNormConstraint,
    WeightNoise,
)
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.config import (
    ListBuilder,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.core_layers import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    EmbeddingSequenceLayer,
    LossLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.conv_layers import (
    BatchNormalization,
    Convolution1DLayer,
    ConvolutionLayer,
    Deconvolution2D,
    GlobalPoolingLayer,
    LocalResponseNormalization,
    PoolingType,
    SeparableConvolution2D,
    SpaceToDepthLayer,
    SubsamplingLayer,
    Upsampling2D,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.recurrent_layers import (
    GRU,
    LSTM,
    Bidirectional,
    GravesLSTM,
    LastTimeStep,
    RnnOutputLayer,
    SimpleRnn,
)
from deeplearning4j_tpu.nn.attention_layers import (
    BertEmbeddingLayer,
    ClsPoolingLayer,
    LearnedPositionalEmbeddingLayer,
    SelfAttentionLayer,
    TransformerEncoderBlock,
)
from deeplearning4j_tpu.nn.extra_layers import (
    CenterLossOutputLayer,
    Convolution3D,
    Cropping2D,
    ConvLSTM2D,
    LocallyConnected1D,
    LocallyConnected2D,
    PermuteLayer,
    SeparableConvolution1D,
    Subsampling1DLayer,
    Subsampling3DLayer,
    Upsampling1D,
    Upsampling3D,
    Yolo2OutputLayer,
)
from deeplearning4j_tpu.nn.autoencoder_layers import (
    AutoEncoder,
    VariationalAutoencoder,
)
from deeplearning4j_tpu.nn.moe_layers import MixtureOfExperts
from deeplearning4j_tpu.nn.misc_layers import (
    Cropping1D,
    FlattenLayer,
    ElementWiseMultiplicationLayer,
    MaskZeroLayer,
    PReLULayer,
    RepeatVector,
    TimeDistributed,
    ZeroPadding1DLayer,
)

__all__ = [
    "GlobalConfig",
    "Layer",
    "register_layer",
    "get_layer_class",
    "InputType",
    "NeuralNetConfiguration",
    "MultiLayerConfiguration",
    "ListBuilder",
    "DenseLayer",
    "OutputLayer",
    "LossLayer",
    "ActivationLayer",
    "DropoutLayer",
    "EmbeddingLayer",
    "EmbeddingSequenceLayer",
    "ConvolutionLayer",
    "Convolution1DLayer",
    "SubsamplingLayer",
    "PoolingType",
    "BatchNormalization",
    "LocalResponseNormalization",
    "Upsampling2D",
    "ZeroPaddingLayer",
    "SeparableConvolution2D",
    "Deconvolution2D",
    "SpaceToDepthLayer",
    "GlobalPoolingLayer",
    "LSTM",
    "GravesLSTM",
    "GRU",
    "SimpleRnn",
    "Bidirectional",
    "LastTimeStep",
    "RnnOutputLayer",
    "SelfAttentionLayer",
    "TransformerEncoderBlock",
    "LearnedPositionalEmbeddingLayer",
    "BertEmbeddingLayer",
    "ClsPoolingLayer",
    "Convolution3D",
    "Subsampling3DLayer",
    "Upsampling1D",
    "Upsampling3D",
    "Cropping2D",
    "ConvLSTM2D",
    "LocallyConnected1D",
    "LocallyConnected2D",
    "FlattenLayer",
    "MaxNormConstraint",
    "MinMaxNormConstraint",
    "UnitNormConstraint",
    "NonNegativeConstraint",
    "DropConnect",
    "WeightNoise",
    "PermuteLayer",
    "SeparableConvolution1D",
    "Subsampling1DLayer",
    "CenterLossOutputLayer",
    "Yolo2OutputLayer",
    "AutoEncoder",
    "MixtureOfExperts",
    "VariationalAutoencoder",
    "PReLULayer",
    "ElementWiseMultiplicationLayer",
    "RepeatVector",
    "MaskZeroLayer",
    "TimeDistributed",
    "Cropping1D",
    "ZeroPadding1DLayer",
]
