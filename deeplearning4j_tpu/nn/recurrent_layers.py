"""Recurrent layers.

Rebuild of upstream ``org.deeplearning4j.nn.conf.layers`` recurrent set:
``LSTM``, ``GravesLSTM`` (peepholes), ``SimpleRnn``, ``GRU``-equivalent,
``Bidirectional`` wrapper, ``LastTimeStep``, ``RnnOutputLayer``.

TPU-first design: the whole sequence runs as ONE ``lax.scan`` inside the
jitted step (the reference needed ``CudnnLSTMHelper`` to fuse the sequence;
under XLA the scan body — a single (batch, 4H) matmul pair per step — is
already the fused form). Gate weights are packed ``(nIn, 4H)`` so each step
is one MXU matmul. Sequence layout is (batch, time, features); masks are
(batch, time) and masked steps carry state through unchanged (matches the
reference's masking semantics).

Stateful inference (reference ``rnnTimeStep``/``rnnClearPreviousState``) is
supported through the explicit carry API: ``init_carry`` +
``forward_with_carry``; ``MultiLayerNetwork`` owns the stored carries.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer, register_layer
from deeplearning4j_tpu.nn.core_layers import OutputLayer
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.ops.losses import LossFunction

# Scan-body unroll factor. Measured on v5e (queue-drained timing, 2-layer
# H=512 char-RNN): unroll 1/8/32 are within 5% — the recurrence is matmul-
# bound, not loop-overhead-bound — so default 1 for fastest compiles. Kept as
# a knob because CPU and future backends may differ.
_SCAN_UNROLL = 1


@dataclasses.dataclass
class BaseRecurrentLayer(Layer):
    n_out: int = 0
    n_in: Optional[int] = None

    def _cell_act(self):
        """Cell-output activation: the layer's own setting wins; an explicit
        non-identity GLOBAL activation is honored; otherwise tanh — the
        reference's recurrent default (the global default identity would
        silently change the cell to h = o*c)."""
        from deeplearning4j_tpu.ops.activations import Activation
        if self.activation is not None:
            return get_activation(self.activation)
        g_act = self._g.activation if self._g is not None else None
        if g_act not in (None, Activation.IDENTITY, "identity"):
            return get_activation(g_act)
        return get_activation("tanh")

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def _nin(self, input_type: InputType) -> int:
        return self.n_in if self.n_in is not None else input_type.size

    def init_carry(self, batch: int, dtype=jnp.float32):
        raise NotImplementedError

    def forward_with_carry(self, params, carry, x, *, training=False, rng=None, mask=None):
        raise NotImplementedError

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        carry = self.init_carry(x.shape[0], x.dtype)
        y, _ = self.forward_with_carry(params, carry, x, training=training, rng=rng, mask=mask)
        return y, state


@register_layer
@dataclasses.dataclass
class LSTM(BaseRecurrentLayer):
    """LSTM with packed gates [i, f, g, o]; forget-gate bias init (reference
    ``LSTM.forgetGateBiasInit``, default 1.0)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: Any = "sigmoid"

    def init(self, key, input_type, g: GlobalConfig):
        n_in, H = self._nin(input_type), self.n_out
        k1, k2 = jax.random.split(key)
        b = jnp.zeros((4 * H,), g.dtype or jnp.float32)
        b = b.at[H:2 * H].set(self.forget_gate_bias_init)
        return {
            "W": init_weights(k1, (n_in, 4 * H), self._winit(g), fan=(n_in, H), dtype=g.dtype),
            "W_rec": init_weights(k2, (H, 4 * H), self._winit(g), fan=(H, H), dtype=g.dtype),
            "b": b,
        }, {}

    def init_carry(self, batch: int, dtype=jnp.float32):
        H = self.n_out
        return (jnp.zeros((batch, H), dtype), jnp.zeros((batch, H), dtype))

    def _step(self, params, h, c, zx_t):
        """One recurrence step. ``zx_t`` is the PRE-COMPUTED input projection
        ``x_t @ W + b`` — hoisting it out of the scan turns T small matmuls
        into one whole-sequence (B*T, nIn)@(nIn, 4H) MXU matmul (the same
        restructuring cuDNN's fused LSTM does), leaving only the unavoidable
        sequential ``h @ W_rec`` inside the loop."""
        H = self.n_out
        act = self._cell_act()
        gate = get_activation(self.gate_activation)
        z = zx_t + h @ params["W_rec"]
        i = gate(z[:, :H])
        f = gate(z[:, H:2 * H])
        g_ = jnp.tanh(z[:, 2 * H:3 * H])
        o = gate(z[:, 3 * H:])
        c_new = f * c + i * g_
        h_new = o * act(c_new)
        return h_new, c_new

    def _kernel_act_ok(self) -> bool:
        """The Pallas kernels implement the default activations only."""
        return (get_activation(self.gate_activation)
                is get_activation("sigmoid")
                and self._cell_act() is get_activation("tanh"))

    def _kernel_eligible(self, mask) -> bool:
        """Plain persistent kernel: default cell, no peepholes, unmasked.
        Masked sequences and GravesLSTM route to the generalised
        peephole+mask kernel (fused_lstm_graves) instead."""
        return mask is None and type(self) is LSTM and self._kernel_act_ok()

    def forward_with_carry(self, params, carry, x, *, training=False, rng=None, mask=None):
        zx = x @ params["W"] + params["b"]  # (batch, time, 4H): one big matmul
        zxs = jnp.swapaxes(zx, 0, 1)  # (time, batch, 4H)
        ms = None if mask is None else jnp.swapaxes(mask, 0, 1)

        if self._kernel_eligible(mask):
            from deeplearning4j_tpu.ops.pallas.fused_lstm import (
                fused_lstm, fused_lstm_compatible)
            h0, c0 = carry
            if fused_lstm_compatible(zxs, h0):
                ys, h, c = fused_lstm(zxs, params["W_rec"],
                                      h0.astype(zxs.dtype),
                                      c0.astype(zxs.dtype))
                return jnp.swapaxes(ys, 0, 1), (h, c)
        elif type(self) in _GRAVES_KERNEL_TYPES and self._kernel_act_ok():
            # GravesLSTM (any mask) and masked plain LSTM: the generalised
            # kernel (zero peepholes == plain cell)
            from deeplearning4j_tpu.ops.pallas.fused_lstm_graves import (
                fused_graves_lstm, fused_graves_lstm_compatible)
            h0, c0 = carry
            if fused_graves_lstm_compatible(zxs, h0):
                H = self.n_out
                peep = params.get("peephole")
                if peep is None:
                    peep = jnp.zeros((3 * H,), zxs.dtype)
                m = jnp.ones(zxs.shape[:2], zxs.dtype) if ms is None \
                    else ms.astype(zxs.dtype)
                ys, h, c = fused_graves_lstm(
                    zxs, params["W_rec"], peep.astype(zxs.dtype),
                    h0.astype(zxs.dtype), c0.astype(zxs.dtype), m)
                return jnp.swapaxes(ys, 0, 1), (h, c)

        def step(hc, inp):
            h, c = hc
            zx_t = inp[0] if ms is not None else inp
            h_new, c_new = self._step(params, h, c, zx_t)
            if ms is not None:
                m = inp[1][:, None].astype(h.dtype)
                h_new = m * h_new + (1 - m) * h
                c_new = m * c_new + (1 - m) * c
            return (h_new, c_new), h_new

        inputs = (zxs, ms) if ms is not None else zxs
        (h, c), ys = lax.scan(step, carry, inputs, unroll=_SCAN_UNROLL)
        return jnp.swapaxes(ys, 0, 1), (h, c)


@register_layer
@dataclasses.dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (reference ``GravesLSTM``). Routes to
    the fused peephole Pallas kernel when shapes allow."""

    def init(self, key, input_type, g: GlobalConfig):
        params, state = super().init(key, input_type, g)
        H = self.n_out
        # peephole columns live in the recurrent weight matrix in the
        # reference and draw from the configured weight-init distribution
        params["peephole"] = init_weights(
            jax.random.fold_in(key, 3), (3 * H,), self._winit(g),
            fan=(H, H), dtype=g.dtype)
        return params, state

    def _step(self, params, h, c, zx_t):
        H = self.n_out
        act = self._cell_act()
        gate = get_activation(self.gate_activation)
        p = params["peephole"]
        z = zx_t + h @ params["W_rec"]
        i = gate(z[:, :H] + c * p[:H])
        f = gate(z[:, H:2 * H] + c * p[H:2 * H])
        g_ = jnp.tanh(z[:, 2 * H:3 * H])
        c_new = f * c + i * g_
        o = gate(z[:, 3 * H:] + c_new * p[2 * H:])
        h_new = o * act(c_new)
        return h_new, c_new


# Types served by the generalised peephole+mask kernel. Subclasses of these
# may change the math arbitrarily, so membership is exact-type.
_GRAVES_KERNEL_TYPES = (LSTM, GravesLSTM)


@register_layer
@dataclasses.dataclass
class SimpleRnn(BaseRecurrentLayer):
    """Vanilla RNN: h' = act(x W + h W_rec + b) (reference ``SimpleRnn``,
    default activation tanh)."""

    def init(self, key, input_type, g: GlobalConfig):
        n_in, H = self._nin(input_type), self.n_out
        k1, k2 = jax.random.split(key)
        return {
            "W": init_weights(k1, (n_in, H), self._winit(g), fan=(n_in, H), dtype=g.dtype),
            "W_rec": init_weights(k2, (H, H), self._winit(g), fan=(H, H), dtype=g.dtype),
            "b": jnp.full((H,), self._binit(g), g.dtype or jnp.float32),
        }, {}

    def init_carry(self, batch: int, dtype=jnp.float32):
        return (jnp.zeros((batch, self.n_out), dtype),)

    def forward_with_carry(self, params, carry, x, *, training=False, rng=None, mask=None):
        act = self._cell_act()
        zxs = jnp.swapaxes(x @ params["W"] + params["b"], 0, 1)  # hoisted
        ms = None if mask is None else jnp.swapaxes(mask, 0, 1)

        def step(hs, inp):
            (h,) = hs
            zx_t = inp[0] if ms is not None else inp
            h_new = act(zx_t + h @ params["W_rec"])
            if ms is not None:
                m = inp[1][:, None].astype(h.dtype)
                h_new = m * h_new + (1 - m) * h
            return (h_new,), h_new

        inputs = (zxs, ms) if ms is not None else zxs
        (h,), ys = lax.scan(step, carry, inputs, unroll=_SCAN_UNROLL)
        return jnp.swapaxes(ys, 0, 1), (h,)


@register_layer
@dataclasses.dataclass
class GRU(BaseRecurrentLayer):
    """GRU with packed gates [r, u, n].

    ``reset_after=True`` (default) is the CuDNN/modern-Keras cell
    (``n = tanh(x_n + r * (h @ U_n [+ b_rn]))``); ``reset_after=False`` is
    the classic reset-BEFORE variant (``n = tanh(x_n + (r*h) @ U_n)``) —
    Keras 1's GRU and Keras 2 with ``reset_after=False``. An optional
    ``b_rec`` param (recurrent bias, CuDNN's second bias set) is applied
    inside the reset product, matching Keras's dual-bias semantics."""

    reset_after: bool = True
    gate_activation: Any = "sigmoid"

    def init(self, key, input_type, g: GlobalConfig):
        n_in, H = self._nin(input_type), self.n_out
        k1, k2 = jax.random.split(key)
        return {
            "W": init_weights(k1, (n_in, 3 * H), self._winit(g), fan=(n_in, H), dtype=g.dtype),
            "W_rec": init_weights(k2, (H, 3 * H), self._winit(g), fan=(H, H), dtype=g.dtype),
            "b": jnp.zeros((3 * H,), g.dtype or jnp.float32),
        }, {}

    def init_carry(self, batch: int, dtype=jnp.float32):
        return (jnp.zeros((batch, self.n_out), dtype),)

    def forward_with_carry(self, params, carry, x, *, training=False, rng=None, mask=None):
        H = self.n_out
        gate = get_activation(self.gate_activation)
        act = self._cell_act()
        zxs = jnp.swapaxes(x @ params["W"] + params["b"], 0, 1)  # hoisted
        ms = None if mask is None else jnp.swapaxes(mask, 0, 1)
        b_rec = params.get("b_rec")

        if mask is None and type(self) is GRU and self.reset_after \
                and b_rec is None \
                and gate is get_activation("sigmoid") \
                and act is get_activation("tanh"):  # kernel's fixed cell
            from deeplearning4j_tpu.ops.pallas.fused_gru import (
                fused_gru, fused_gru_compatible)
            (h0,) = carry
            if fused_gru_compatible(zxs, h0):
                ys, h = fused_gru(zxs, params["W_rec"], h0.astype(zxs.dtype))
                return jnp.swapaxes(ys, 0, 1), (h,)

        def step(hs, inp):
            (h,) = hs
            zx = inp[0] if ms is not None else inp
            # reset-before only needs the r/u thirds of the recurrent
            # matmul here — the n third runs on (r*h) below
            W_ru = params["W_rec"] if self.reset_after \
                else params["W_rec"][:, :2 * H]
            zh = h @ W_ru
            if b_rec is not None:
                zh = zh + (b_rec if self.reset_after else b_rec[:2 * H])
            r = gate(zx[:, :H] + zh[:, :H])
            u = gate(zx[:, H:2 * H] + zh[:, H:2 * H])
            if self.reset_after:
                n = act(zx[:, 2 * H:] + r * zh[:, 2 * H:])
            else:
                zn = (r * h) @ params["W_rec"][:, 2 * H:]
                if b_rec is not None:
                    zn = zn + b_rec[2 * H:]
                n = act(zx[:, 2 * H:] + zn)
            h_new = (1 - u) * n + u * h
            if ms is not None:
                m = inp[1][:, None].astype(h.dtype)
                h_new = m * h_new + (1 - m) * h
            return (h_new,), h_new

        inputs = (zxs, ms) if ms is not None else zxs
        (h,), ys = lax.scan(step, carry, inputs, unroll=_SCAN_UNROLL)
        return jnp.swapaxes(ys, 0, 1), (h,)


@register_layer
@dataclasses.dataclass
class Bidirectional(Layer):
    """Bidirectional wrapper (reference ``Bidirectional``): runs the wrapped
    recurrent layer forward and on the time-reversed sequence; merge modes
    CONCAT / ADD / MUL / AVERAGE."""

    layer: Any = None  # a BaseRecurrentLayer (or dict after deserialization)
    mode: str = "concat"

    def __post_init__(self):
        if isinstance(self.layer, dict):
            self.layer = Layer.from_dict(self.layer)

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.layer.output_type(input_type)
        if self.mode.lower() == "concat":
            return InputType.recurrent(inner.size * 2, inner.timesteps)
        return inner

    def init(self, key, input_type, g: GlobalConfig):
        self.layer._g = g
        k1, k2 = jax.random.split(key)
        fwd, _ = self.layer.init(k1, input_type, g)
        bwd, _ = self.layer.init(k2, input_type, g)
        return {"fwd": fwd, "bwd": bwd}, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        self.layer._g = self._g
        y_f, _ = self.layer.forward(params["fwd"], {}, x, training=training, rng=rng, mask=mask)
        x_rev = jnp.flip(x, axis=1)
        m_rev = None if mask is None else jnp.flip(mask, axis=1)
        y_b, _ = self.layer.forward(params["bwd"], {}, x_rev, training=training, rng=rng, mask=m_rev)
        y_b = jnp.flip(y_b, axis=1)
        mode = self.mode.lower()
        if mode == "concat":
            return jnp.concatenate([y_f, y_b], axis=-1), state
        if mode == "add":
            return y_f + y_b, state
        if mode == "mul":
            return y_f * y_b, state
        return 0.5 * (y_f + y_b), state


@register_layer
@dataclasses.dataclass
class LastTimeStep(Layer):
    """Extract the last (mask-aware) timestep (reference ``LastTimeStep``)."""

    layer: Any = None

    def __post_init__(self):
        if isinstance(self.layer, dict):
            self.layer = Layer.from_dict(self.layer)

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.layer.output_type(input_type) if self.layer else input_type
        return InputType.feed_forward(inner.size)

    def init(self, key, input_type, g: GlobalConfig):
        if self.layer is None:
            return {}, {}
        self.layer._g = g
        return self.layer.init(key, input_type, g)

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        if self.layer is not None:
            self.layer._g = self._g
            x, state = self.layer.forward(params, state, x, training=training, rng=rng, mask=mask)
        if mask is not None:
            idx = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)
            return x[jnp.arange(x.shape[0]), idx], state
        return x[:, -1], state


@register_layer
@dataclasses.dataclass
class RnnOutputLayer(OutputLayer):
    """Time-distributed output head (reference ``RnnOutputLayer``): dense +
    loss applied at every timestep of (batch, time, nIn)."""

    loss: Any = LossFunction.MCXENT

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)
