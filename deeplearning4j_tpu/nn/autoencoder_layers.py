"""Unsupervised / pretraining layers.

Rebuild of upstream ``org.deeplearning4j.nn.conf.layers.variational.
VariationalAutoencoder`` and ``org.deeplearning4j.nn.conf.layers.AutoEncoder``
(denoising autoencoder). In the reference these are "pretrainable" layers:
``MultiLayerNetwork.pretrain(iter)`` trains them greedily layer-by-layer on an
unsupervised objective, and in supervised training they act as plain
feed-forward encoders. Same contract here — the unsupervised objective is
exposed as ``pretrain_loss`` and consumed by
``MultiLayerNetwork.pretrain_layer``, which jits one donated update step per
pretrained layer (no per-op dispatch, unlike the reference's pretraining path).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer, register_layer
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import Activation, get_activation
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.ops.losses import LossFunction, compute_loss

_LOG2PI = 1.8378770664093453


def _mlp_init(key, sizes, winit, bias_init, dtype, prefix):
    params = {}
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        k = jax.random.fold_in(key, i)
        params[f"W_{prefix}{i}"] = init_weights(k, (a, b), winit, fan=(a, b), dtype=dtype)
        params[f"b_{prefix}{i}"] = jnp.full((b,), bias_init, dtype=dtype)
    return params


def _mlp_forward(params, x, n, act, prefix):
    for i in range(n):
        x = act(x @ params[f"W_{prefix}{i}"] + params[f"b_{prefix}{i}"])
    return x


@register_layer
@dataclasses.dataclass
class VariationalAutoencoder(Layer):
    """VAE (Kingma & Welling) as a layer, matching the reference's semantics:

    - supervised forward = encoder MLP -> mean of q(z|x) (``pzx_activation``
      applied), so the layer is a drop-in feed-forward encoder of width
      ``n_out`` (the latent size).
    - ``pretrain_loss`` = negative ELBO: reconstruction negative
      log-likelihood under ``reconstruction_distribution`` plus analytic
      KL(q(z|x) || N(0, I)), averaged over the minibatch, estimated with
      ``num_samples`` reparameterized draws.

    ``reconstruction_distribution``: "gaussian" (decoder emits mean and
    log-variance per visible unit — 2*nIn outputs) or "bernoulli" (decoder
    emits logits — nIn outputs; use for binary/binarized data).
    """

    n_out: int = 0  # latent size
    n_in: Optional[int] = None
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    reconstruction_distribution: str = "gaussian"
    pzx_activation: Any = Activation.IDENTITY
    num_samples: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def _nin(self, input_type: InputType) -> int:
        return self.n_in if self.n_in is not None else input_type.flat_size()

    def _vis_out(self, n_in: int) -> int:
        if self.reconstruction_distribution == "gaussian":
            return 2 * n_in
        if self.reconstruction_distribution == "bernoulli":
            return n_in
        raise ValueError(f"Unknown reconstruction distribution "
                         f"{self.reconstruction_distribution!r}")

    def init(self, key, input_type, g: GlobalConfig):
        n_in = self._nin(input_type)
        winit, binit, dt = self._winit(g), self._binit(g), g.dtype
        enc = (n_in,) + tuple(self.encoder_layer_sizes)
        dec = (self.n_out,) + tuple(self.decoder_layer_sizes)
        k_e, k_d, k_m, k_v, k_x = jax.random.split(key, 5)
        params = {}
        params.update(_mlp_init(k_e, enc, winit, binit, dt, "enc"))
        params.update(_mlp_init(k_d, dec, winit, binit, dt, "dec"))
        h = enc[-1]
        params["W_zmean"] = init_weights(k_m, (h, self.n_out), winit,
                                         fan=(h, self.n_out), dtype=dt)
        params["b_zmean"] = jnp.full((self.n_out,), binit, dtype=dt)
        params["W_zvar"] = init_weights(k_v, (h, self.n_out), winit,
                                        fan=(h, self.n_out), dtype=dt)
        params["b_zvar"] = jnp.full((self.n_out,), binit, dtype=dt)
        d_h, vis = dec[-1], self._vis_out(n_in)
        params["W_pxz"] = init_weights(k_x, (d_h, vis), winit, fan=(d_h, vis), dtype=dt)
        params["b_pxz"] = jnp.full((vis,), binit, dtype=dt)
        return params, {}

    def regularizable_params(self):
        return tuple(f"W_enc{i}" for i in range(len(self.encoder_layer_sizes))) + \
            tuple(f"W_dec{i}" for i in range(len(self.decoder_layer_sizes))) + \
            ("W_zmean", "W_zvar", "W_pxz")

    # ---- pieces ----
    def _encode(self, params, x):
        act = get_activation(self._act(self._g))
        h = _mlp_forward(params, x, len(self.encoder_layer_sizes), act, "enc")
        mean = h @ params["W_zmean"] + params["b_zmean"]
        logvar = h @ params["W_zvar"] + params["b_zvar"]
        return mean, logvar

    def _decode(self, params, z):
        act = get_activation(self._act(self._g))
        h = _mlp_forward(params, z, len(self.decoder_layer_sizes), act, "dec")
        return h @ params["W_pxz"] + params["b_pxz"]

    def _recon_logp(self, vis_out, x):
        """log p(x|z), summed over visible units, per example."""
        if self.reconstruction_distribution == "gaussian":
            mean, logvar = jnp.split(vis_out, 2, axis=-1)
            lp = -0.5 * (_LOG2PI + logvar + jnp.square(x - mean) / jnp.exp(logvar))
        else:  # bernoulli logits
            lp = x * jax.nn.log_sigmoid(vis_out) + (1.0 - x) * jax.nn.log_sigmoid(-vis_out)
        return jnp.sum(lp, axis=-1)

    # ---- supervised path: encoder as a feed-forward layer ----
    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        mean, _ = self._encode(params, x)
        return get_activation(self.pzx_activation)(mean), state

    # ---- unsupervised objective ----
    def pretrain_loss(self, params, x, rng):
        """Negative ELBO, minibatch mean."""
        mean, logvar = self._encode(params, x)
        kl = -0.5 * jnp.sum(1.0 + logvar - jnp.square(mean) - jnp.exp(logvar), axis=-1)
        recon = jnp.zeros(x.shape[0], dtype=mean.dtype)
        for s in range(self.num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            recon = recon + self._recon_logp(self._decode(params, z), x)
        recon = recon / self.num_samples
        return jnp.mean(kl - recon)

    # ---- reference utility API ----
    def reconstruction_log_probability(self, params, x, num_samples: int = 1,
                                       rng=None):
        """Importance-weighted estimate of log p(x) per example
        (reference ``reconstructionLogProbability``)."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        mean, logvar = self._encode(params, x)
        std = jnp.exp(0.5 * logvar)
        ws = []
        for s in range(num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape, mean.dtype)
            z = mean + std * eps
            logp_xz = self._recon_logp(self._decode(params, z), x)
            logp_z = jnp.sum(-0.5 * (_LOG2PI + jnp.square(z)), axis=-1)
            logq = jnp.sum(-0.5 * (_LOG2PI + logvar + jnp.square(eps)), axis=-1)
            ws.append(logp_xz + logp_z - logq)
        return jax.scipy.special.logsumexp(jnp.stack(ws), axis=0) - jnp.log(
            float(num_samples))

    def generate_at_mean_given_z(self, params, z):
        """Decoder mean output for latent ``z`` (reference
        ``generateAtMeanGivenZ``)."""
        out = self._decode(params, z)
        if self.reconstruction_distribution == "gaussian":
            return jnp.split(out, 2, axis=-1)[0]
        return jax.nn.sigmoid(out)

    def reconstruction_error(self, params, x):
        """Deterministic round-trip error ||x - dec(enc_mean(x))||^2 mean."""
        mean, _ = self._encode(params, x)
        rec = self.generate_at_mean_given_z(params, mean)
        return jnp.mean(jnp.sum(jnp.square(x - rec), axis=-1))


@register_layer
@dataclasses.dataclass
class AutoEncoder(Layer):
    """Denoising autoencoder layer (reference ``AutoEncoder``): tied-weight
    encode/decode with input corruption. Supervised forward = encoder only;
    ``pretrain_loss`` corrupts the input (zeroing with probability
    ``corruption_level``), encodes with (W, b), decodes with (W^T, vb), and
    scores reconstruction against the clean input with ``loss``."""

    n_out: int = 0
    n_in: Optional[int] = None
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: Any = LossFunction.MSE

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type, g: GlobalConfig):
        n_in = self.n_in if self.n_in is not None else input_type.flat_size()
        params = {
            "W": init_weights(key, (n_in, self.n_out), self._winit(g),
                              fan=(n_in, self.n_out), dtype=g.dtype),
            "b": jnp.full((self.n_out,), self._binit(g), dtype=g.dtype),
            "vb": jnp.zeros((n_in,), dtype=g.dtype),
        }
        return params, {}

    def forward(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self._apply_input_dropout(x, self._g, training, rng)
        act = get_activation(self._act(self._g))
        return act(x @ params["W"] + params["b"]), state

    def pretrain_loss(self, params, x, rng):
        act = get_activation(self._act(self._g))
        corrupted = x
        if self.corruption_level > 0.0:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0).astype(x.dtype)
        h = act(corrupted @ params["W"] + params["b"])
        recon_pre = h @ params["W"].T + params["vb"]
        l = compute_loss(self.loss, x, recon_pre, activation=self._act(self._g))
        if self.sparsity > 0.0:
            l = l + self.sparsity * jnp.mean(jnp.abs(h))
        return l
