"""Layer base class and serde registry.

In the reference every layer is a *pair*: a Jackson-serializable conf class
(``org.deeplearning4j.nn.conf.layers.*``) and a runtime impl
(``org.deeplearning4j.nn.layers.*``) with ``activate()`` /
``backpropGradient()``. Here a layer is ONE dataclass that is both the
serializable config (``to_dict``/``from_dict`` via a name registry, the
Jackson-polymorphism analog) and the pure-functional implementation
(``init``/``forward``); backprop comes from ``jax.grad`` of the composed
forward, so no hand-written backward passes exist anywhere.

Forward contract (uniform across layers so the network can compose them into
one traced program):

    y, new_state = layer.forward(params, state, x, training=..., rng=..., mask=...)

- ``params``: dict of trainable arrays ("W", "b", "gamma", ...). Keys starting
  with "W" or "gamma"-free weight keys are subject to l1/l2 (see
  ``regularizable_params``).
- ``state``:  dict of non-trainable arrays (batch-norm running stats).
- ``rng``:    PRNG key, only consumed when the layer is stochastic + training.
- ``mask``:   optional (batch, time) validity mask for sequence data.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple, Type

import jax

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.initializers import WeightInit

_LAYER_REGISTRY: Dict[str, Type["Layer"]] = {}


def register_layer(cls: Type["Layer"]) -> Type["Layer"]:
    """Class decorator: registers the layer under its class name for serde
    (the Jackson-polymorphic-type analog)."""
    _LAYER_REGISTRY[cls.__name__] = cls
    return cls


def get_layer_class(name: str) -> Type["Layer"]:
    if name not in _LAYER_REGISTRY:
        raise KeyError(f"Unknown layer type {name!r}; registered: {sorted(_LAYER_REGISTRY)}")
    return _LAYER_REGISTRY[name]


def dropout_mask(rng, keep_prob, shape):
    """Bernoulli keep-mask backed by XLA's ``RngBitGenerator`` (jax "rbg"
    PRNG) instead of the default threefry.

    Dropout is pure traffic — the mask is consumed once — and threefry's
    counter math costs real MXU-adjacent cycles: on the v5e it was measured
    at ~15 ms/step of BERT-base (64x128), ~27% of the whole step. The rbg
    generator is hardware-backed and cut that to noise (1187 -> 1637
    samples/s, v5e, dropout-site-only switch; see BASELINE.md round 3).
    Only dropout routes through here; weight init and every
    other draw keep the threefry key chain, so seeds/goldens elsewhere are
    unchanged. The incoming key may be a raw uint32 vector (old-style) or a
    typed key; both are folded into the 4-word rbg key format.
    """
    import numpy as np

    import jax.numpy as jnp
    if jnp.issubdtype(rng.dtype, jax.dtypes.prng_key):
        data = jax.random.key_data(rng)
    else:
        data = rng
    data = data.astype(jnp.uint32).reshape(-1)
    if data.shape[0] < 4:
        data = jnp.concatenate([data, data])[:4]
    key = jax.random.wrap_key_data(data[:4], impl="rbg")
    # Draw over the FLATTENED (rows, features) view: profiled on v5e, the
    # 3-D rbg bits tensor's tiling never matches its consumer and XLA
    # inserts a 25 MB u32 layout copy per dropout site (~1 ms/step on
    # BERT-base across 25 sites); the 2-D draw layout-matches and the
    # reshape back is a free bitcast.
    if len(shape) > 2:
        rows = int(np.prod(shape[:-1]))
        return jax.random.bernoulli(key, keep_prob,
                                    shape=(rows, shape[-1])).reshape(shape)
    return jax.random.bernoulli(key, keep_prob, shape=shape)


def cast_floating(tree, dtype):
    """Cast floating-point leaves of a pytree to ``dtype``.

    The mixed-precision policy: master params stay in ``default_dtype``
    (float32); the jitted step casts them to ``compute_dtype`` (bfloat16 on
    TPU) here, right before use. Autodiff transposes the cast, so gradients
    land back in the master dtype and the optimizer update stays full
    precision."""
    import jax.numpy as jnp

    def _c(a):
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != dtype:
            return a.astype(dtype)
        return a

    return jax.tree.map(_c, tree)


@dataclasses.dataclass
class GlobalConfig:
    """Network-wide defaults that layers inherit when their own field is None.

    Mirrors the fields configured on the outer ``NeuralNetConfiguration.Builder``
    in the reference (seed, weightInit, activation, l1/l2, dropout, ...).
    """

    seed: int = 0
    weight_init: WeightInit = WeightInit.XAVIER
    activation: Any = Activation.IDENTITY
    l1: float = 0.0
    l2: float = 0.0
    weight_decay: float = 0.0
    dropout: Optional[float] = None  # retain probability, DL4J convention
    bias_init: float = 0.0
    updater: Any = None  # train.updaters.Updater; resolved by the training engine
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    dtype: Any = None  # resolved against runtime Environment
    # Reference OptimizationAlgorithm: STOCHASTIC_GRADIENT_DESCENT (default),
    # LBFGS, CONJUGATE_GRADIENT, LINE_GRADIENT_DESCENT (legacy second-order /
    # line-search solvers; see train/solvers.py).
    optimization_algo: str = "STOCHASTIC_GRADIENT_DESCENT"
    max_num_line_search_iterations: int = 5  # line-search step budget
    solver_iterations: int = 10  # outer LBFGS/CG iterations per batch


@dataclasses.dataclass
class Layer:
    """Base layer config. Subclasses add fields and override the four methods.

    Fields that default to ``None`` inherit from :class:`GlobalConfig` at
    build time (the reference's conf-inheritance or "layer overrides global
    builder" behaviour).
    """

    name: Optional[str] = None
    activation: Any = None
    weight_init: Any = None
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    weight_decay: Optional[float] = None
    dropout: Optional[float] = None  # retain probability applied to layer INPUT
    updater: Any = None
    frozen: bool = False  # transfer-learning: exclude params from training
    # Post-update projections (reference LayerConstraint) and train-time
    # weight perturbation (reference IWeightNoise / DropConnect)
    constraints: Any = None
    bias_constraints: Any = None
    weight_noise: Any = None
    # GlobalConfig attached by the network at build time (not serialized) so
    # forward() needs no extra argument.
    _g: Any = dataclasses.field(default=None, repr=False, compare=False)

    # ---- shape inference ----
    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    # ---- parameters ----
    def init(self, key: jax.Array, input_type: InputType, g: GlobalConfig
             ) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array]]:
        """Return (params, state). Default: parameterless layer."""
        return {}, {}

    def forward(self, params: Dict, state: Dict, x, *, training: bool = False,
                rng: Optional[jax.Array] = None, mask=None) -> Tuple[Any, Dict]:
        raise NotImplementedError

    # ---- regularization ----
    def regularizable_params(self) -> Tuple[str, ...]:
        """Param keys subject to l1/l2/weight-decay (weights, not biases —
        the reference's default regularization split)."""
        return ("W", "W_rec", "W_point", "W_depth", "W_q", "W_k", "W_v", "W_o")

    # ---- inherited-field resolution ----
    def _act(self, g: GlobalConfig):
        return self.activation if self.activation is not None else g.activation

    def _winit(self, g: GlobalConfig):
        return self.weight_init if self.weight_init is not None else g.weight_init

    def _binit(self, g: GlobalConfig) -> float:
        return self.bias_init if self.bias_init is not None else g.bias_init

    def _dropout(self, g: GlobalConfig):
        return self.dropout if self.dropout is not None else g.dropout

    def _apply_input_dropout(self, x, g: GlobalConfig, training: bool, rng):
        """DL4J semantics: ``dropOut(p)`` on a layer drops the layer's INPUT
        with retain probability p, inverted scaling."""
        p = self._dropout(g)
        if not training or p is None or p >= 1.0 or rng is None:
            return x
        keep = dropout_mask(rng, p, x.shape)
        return jax.numpy.where(keep, x / p, 0.0).astype(x.dtype)

    # ---- serde ----
    def to_dict(self) -> dict:
        d = {"@type": type(self).__name__}
        for f in dataclasses.fields(self):
            if f.name.startswith("_"):
                continue
            v = getattr(self, f.name)
            if v is None or v == f.default:
                continue
            if isinstance(v, (Activation, WeightInit)):
                v = v.value
            elif dataclasses.is_dataclass(v) and not isinstance(v, type):
                v = v.to_dict() if hasattr(v, "to_dict") else dataclasses.asdict(v)
            elif hasattr(v, "to_dict"):
                v = v.to_dict()
            elif isinstance(v, (list, tuple)) and v and hasattr(v[0], "to_dict"):
                v = [e.to_dict() for e in v]
            d[f.name] = v
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Layer":
        d = dict(d)
        typ = d.pop("@type", cls.__name__)
        target = get_layer_class(typ)
        # Delegate to a subclass's overridden from_dict (e.g. wrapper layers
        # that must revive their nested ``underlying`` layer).
        if target.from_dict.__func__ is not cls.from_dict.__func__:
            return target.from_dict({**d, "@type": typ})
        field_names = {f.name for f in dataclasses.fields(target)}
        kwargs = {}
        for k, v in d.items():
            if k not in field_names:
                continue
            if k == "updater" and isinstance(v, dict):
                from deeplearning4j_tpu.train.updaters import Updater
                v = Updater.from_dict(v)
            elif k in ("constraints", "bias_constraints") and v is not None:
                from deeplearning4j_tpu.nn.constraints import Constraint
                vs = v if isinstance(v, list) else [v]
                v = [Constraint.from_dict(e) if isinstance(e, dict) else e
                     for e in vs]
            elif k == "weight_noise" and isinstance(v, dict):
                from deeplearning4j_tpu.nn.constraints import (DropConnect,
                                                               WeightNoise)
                v = (DropConnect if v.get("type") == "DropConnect"
                     else WeightNoise)(**{a: b for a, b in v.items()
                                          if a != "type"})
            kwargs[k] = v
        return target(**kwargs)


def spectral_key(key: jax.Array, i: int) -> jax.Array:
    """Deterministic per-index subkey (used to give each layer its own stream)."""
    return jax.random.fold_in(key, i)
