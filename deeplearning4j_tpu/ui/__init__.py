"""Training UI / stats pipeline.

Rebuild of the reference's UI stack (upstream ``deeplearning4j-ui-parent``):
``StatsListener`` -> ``StatsStorage`` (in-memory / file) -> ``UIServer``
rendering overview/model charts. The storage format here is JSONL (one
record per iteration) and the server is a dependency-free stdlib HTTP server
with an inline-JS chart page — same overview diagnostics the reference's
Play/Vert.x UI ships: score curve, update:parameter mean-magnitude ratios
(the marquee diagnostic), per-layer param stats, memory.
"""

from deeplearning4j_tpu.ui.stats import (FileStatsStorage, InMemoryStatsStorage,
                                         RemoteUIStatsStorage, StatsListener)
from deeplearning4j_tpu.ui.server import UIServer

__all__ = ["StatsListener", "InMemoryStatsStorage", "FileStatsStorage",
           "RemoteUIStatsStorage", "UIServer"]
