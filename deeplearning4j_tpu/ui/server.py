"""UI server (reference ``UIServer.getInstance().attach(storage)``).

Dependency-free stdlib HTTP server: ``/`` serves an inline-JS dashboard
(score curve + update:param ratio chart, canvas-drawn, no external assets —
the environment is offline), ``/api/records`` serves the raw JSONL records.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>body{font-family:sans-serif;margin:24px;background:#fafafa}
h2{margin:8px 0}canvas{background:#fff;border:1px solid #ddd;margin-bottom:24px}</style>
</head><body>
<h1>Training overview</h1>
<h2>Score vs iteration</h2><canvas id="score" width="900" height="260"></canvas>
<h2>Iterations / second</h2><canvas id="speed" width="900" height="160"></canvas>
<script>
async function draw() {
  const res = await fetch('/api/records');
  const recs = await res.json();
  plot('score', recs.map(r => [r.iteration, r.score]));
  plot('speed', recs.filter(r => r.iterations_per_second)
                    .map(r => [r.iteration, r.iterations_per_second]));
}
function plot(id, pts) {
  const c = document.getElementById(id), g = c.getContext('2d');
  g.clearRect(0, 0, c.width, c.height);
  if (!pts.length) return;
  const xs = pts.map(p => p[0]), ys = pts.map(p => p[1]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs) || 1;
  const y0 = Math.min(...ys), y1 = Math.max(...ys) || 1;
  g.strokeStyle = '#1a73e8'; g.beginPath();
  pts.forEach((p, i) => {
    const x = 40 + (p[0] - x0) / (x1 - x0 || 1) * (c.width - 60);
    const y = c.height - 20 - (p[1] - y0) / (y1 - y0 || 1) * (c.height - 40);
    i ? g.lineTo(x, y) : g.moveTo(x, y);
  });
  g.stroke();
  g.fillStyle = '#333';
  g.fillText(y1.toPrecision(4), 2, 14);
  g.fillText(y0.toPrecision(4), 2, c.height - 8);
}
draw(); setInterval(draw, 3000);
</script></body></html>"""


class UIServer:
    _instance: Optional["UIServer"] = None

    def __init__(self):
        self._storage = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    @classmethod
    def get_instance(cls) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer()
        return cls._instance

    def attach(self, storage) -> None:
        self._storage = storage

    def enable_remote_listener(self) -> None:  # reference API surface
        pass

    def start(self, port: int = 9000) -> int:
        storage_ref = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.startswith("/api/records"):
                    recs = storage_ref._storage.records() if storage_ref._storage else []
                    body = json.dumps(recs).encode()
                    ctype = "application/json"
                else:
                    body = _PAGE.encode()
                    ctype = "text/html"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd = None
