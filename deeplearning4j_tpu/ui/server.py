"""UI server (reference ``UIServer.getInstance().attach(storage)``; the
Play/Vert.x web UI of ``deeplearning4j-ui-parent`` rebuilt as a
dependency-free stdlib HTTP server — the environment is offline, so the page
is inline JS with canvas charts, no external assets).

Tabs mirror the reference UI: **overview** (score curve, throughput),
**model** (per-layer update:parameter ratios — the marquee diagnostic),
**arbiter** (hyperparameter-search results table/chart), **tsne** (embedding
scatter), **system** (device memory). ``POST /api/post`` ingests remote
records (reference ``RemoteUIStatsStorage``): a trainer in another process
posts its stats here with :class:`~deeplearning4j_tpu.ui.stats.RemoteUIStatsStorage`.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>body{font-family:sans-serif;margin:24px;background:#fafafa}
h2{margin:8px 0}canvas{background:#fff;border:1px solid #ddd;margin-bottom:24px}
nav a{margin-right:16px;font-weight:bold;text-decoration:none;color:#1a73e8}
table{border-collapse:collapse;background:#fff}td,th{border:1px solid #ddd;padding:4px 10px}
</style></head><body>
<nav><a href="/">overview</a><a href="/model">model</a>
<a href="/arbiter">arbiter</a><a href="/tsne">t-SNE</a>
<a href="/system">system</a></nav>
<div id="content"></div>
<script>
const TAB = location.pathname === '/' ? 'overview' : location.pathname.slice(1);
function el(html){document.getElementById('content').insertAdjacentHTML('beforeend', html)}
function plot(id, pts, color) {
  const c = document.getElementById(id), g = c.getContext('2d');
  g.clearRect(0, 0, c.width, c.height);
  if (!pts.length) return;
  const xs = pts.map(p => p[0]), ys = pts.map(p => p[1]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs) || 1;
  const y0 = Math.min(...ys), y1 = Math.max(...ys) || 1;
  g.strokeStyle = color || '#1a73e8'; g.beginPath();
  pts.forEach((p, i) => {
    const x = 40 + (p[0] - x0) / (x1 - x0 || 1) * (c.width - 60);
    const y = c.height - 20 - (p[1] - y0) / (y1 - y0 || 1) * (c.height - 40);
    i ? g.lineTo(x, y) : g.moveTo(x, y);
  });
  g.stroke();
  g.fillStyle = '#333';
  g.fillText(y1.toPrecision(4), 2, 14);
  g.fillText(y0.toPrecision(4), 2, c.height - 8);
}
async function overview() {
  el('<h1>Training overview</h1><h2>Score vs iteration</h2>' +
     '<canvas id="score" width="900" height="260"></canvas>' +
     '<h2>Iterations / second</h2><canvas id="speed" width="900" height="160"></canvas>');
  async function draw() {
    const recs = await (await fetch('/api/records')).json();
    plot('score', recs.map(r => [r.iteration, r.score]));
    plot('speed', recs.filter(r => r.iterations_per_second)
                      .map(r => [r.iteration, r.iterations_per_second]));
  }
  draw(); setInterval(draw, 3000);
}
function bars(id, hist, color) {
  const c = document.getElementById(id), g = c.getContext('2d');
  g.clearRect(0, 0, c.width, c.height);
  if (!hist || !hist.counts) { g.fillText('no histogram', 10, 20); return; }
  const n = hist.counts.length, mx = Math.max(...hist.counts) || 1;
  const bw = (c.width - 60) / n;
  g.fillStyle = color || '#1a73e8';
  hist.counts.forEach((v, i) => {
    const h = v / mx * (c.height - 30);
    g.fillRect(40 + i * bw, c.height - 16 - h, Math.max(bw - 1, 1), h);
  });
  g.fillStyle = '#333';
  g.fillText(Number(hist.lo).toPrecision(3), 40, c.height - 4);
  g.fillText(Number(hist.hi).toPrecision(3), c.width - 60, c.height - 4);
}
async function model() {
  el('<h1>Model</h1><h2>update : parameter ratios (log10)</h2><div id="charts"></div>' +
     '<h2>Parameter / update histograms (latest sample)</h2><div id="hists"></div>');
  async function draw() {
    const recs = await (await fetch('/api/records')).json();
    const layers = {};
    recs.forEach(r => Object.entries(r.update_param_ratios || {}).forEach(
      ([k, v]) => { (layers[k] = layers[k] || []).push([r.iteration, Math.log10(v + 1e-12)]); }));
    const div = document.getElementById('charts');
    Object.keys(layers).sort().forEach(k => {
      const id = 'c_' + k.replace(/[^a-zA-Z0-9]/g, '_');
      if (!document.getElementById(id))
        div.insertAdjacentHTML('beforeend',
          `<h2>${k}</h2><canvas id="${id}" width="900" height="120"></canvas>`);
      plot(id, layers[k], '#e8710a');
    });
    const last = recs.filter(r => r.params).slice(-1)[0];
    if (last) {
      const hd = document.getElementById('hists');
      Object.entries(last.params).forEach(([layer, ps]) =>
        Object.entries(ps).forEach(([pname, st]) => {
          if (!st.hist) return;
          const base = (layer + '_' + pname).replace(/[^a-zA-Z0-9]/g, '_');
          if (!document.getElementById('h_' + base)) {
            hd.insertAdjacentHTML('beforeend',
              `<h3>${layer}/${pname}</h3>` +
              `<canvas id="h_${base}" width="440" height="130"></canvas> ` +
              `<canvas id="u_${base}" width="440" height="130"></canvas>`);
          }
          bars('h_' + base, st.hist, '#1a73e8');
          const ust = ((last.updates || {})[layer] || {})[pname];
          bars('u_' + base, ust && ust.hist, '#188038');
        }));
      if (last.activations && last.activations.length &&
          !document.getElementById('a_0')) {
        hd.insertAdjacentHTML('beforeend', '<h2>Activation histograms</h2>' +
          last.activations.map((_, i) =>
            `<h3>layer ${i}</h3><canvas id="a_${i}" width="440" height="130"></canvas>`).join(''));
      }
      (last.activations || []).forEach((a, i) => bars('a_' + i, a.hist, '#9334e6'));
    }
  }
  draw(); setInterval(draw, 3000);
}
async function arbiter() {
  el('<h1>Hyperparameter search</h1>' +
     '<h2>Candidate scores</h2><canvas id="scores" width="900" height="220"></canvas>' +
     '<div id="table"></div>');
  async function draw() {
    const res = await (await fetch('/api/arbiter')).json();
    plot('scores', res.map(r => [r.index, r.score]), '#188038');
    const rows = res.map(r =>
      `<tr><td>${r.index}</td><td>${r.score.toPrecision(5)}</td>` +
      `<td>${r.duration_s.toFixed(1)}s</td><td>${JSON.stringify(r.candidate)}</td></tr>`);
    document.getElementById('table').innerHTML =
      '<table><tr><th>#</th><th>score</th><th>time</th><th>candidate</th></tr>' +
      rows.join('') + '</table>';
  }
  draw(); setInterval(draw, 3000);
}
async function tsne() {
  el('<h1>t-SNE embedding</h1><canvas id="emb" width="800" height="800"></canvas>');
  const data = await (await fetch('/api/tsne')).json();
  const c = document.getElementById('emb'), g = c.getContext('2d');
  if (!data.points || !data.points.length) { g.fillText('no embedding uploaded', 20, 20); return; }
  const xs = data.points.map(p => p[0]), ys = data.points.map(p => p[1]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs), y0 = Math.min(...ys), y1 = Math.max(...ys);
  const colors = ['#1a73e8','#e8710a','#188038','#d93025','#9334e6','#12b5cb','#f29900','#5f6368'];
  data.points.forEach((p, i) => {
    const x = 20 + (p[0]-x0)/((x1-x0)||1)*(c.width-40);
    const y = 20 + (p[1]-y0)/((y1-y0)||1)*(c.height-40);
    const lbl = (data.labels || [])[i];
    g.fillStyle = colors[(typeof lbl === 'number' ? lbl : i) % colors.length];
    g.fillRect(x-2, y-2, 4, 4);
    if (typeof lbl === 'string') g.fillText(lbl, x + 4, y);
  });
}
async function system() {
  el('<h1>System</h1><h2>Device memory in use (bytes)</h2>' +
     '<canvas id="mem" width="900" height="220"></canvas>');
  async function draw() {
    const recs = await (await fetch('/api/records')).json();
    plot('mem', recs.filter(r => r.device_memory && r.device_memory.bytes_in_use)
                    .map(r => [r.iteration, r.device_memory.bytes_in_use]), '#d93025');
  }
  draw(); setInterval(draw, 3000);
}
({overview, model, arbiter, tsne, system}[TAB] || overview)();
</script></body></html>"""


class UIServer:
    _instance: Optional["UIServer"] = None

    def __init__(self):
        from deeplearning4j_tpu.ui.stats import InMemoryStatsStorage
        self._storage = None
        self._remote_storage = InMemoryStatsStorage()  # POSTed records
        self._arbiter_results: List[Dict[str, Any]] = []
        self._tsne: Dict[str, Any] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        self._remote_enabled = False

    @classmethod
    def get_instance(cls) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer()
        return cls._instance

    def attach(self, storage) -> None:
        self._storage = storage

    def enable_remote_listener(self) -> None:
        """Opt in to accepting POSTed stats/arbiter records (reference:
        ``UIServer.enableRemoteListener()``). Until called, the POST
        endpoints return 403 so other local processes can't inject
        records into the dashboard."""
        self._remote_enabled = True

    def disable_remote_listener(self) -> None:
        self._remote_enabled = False

    def attach_arbiter(self, runner) -> None:
        """Live-attach a :class:`LocalOptimizationRunner`: its results render
        in the arbiter tab (the reference's arbiter UI module)."""
        def listener(res):
            self._arbiter_results.append({
                "index": res.index, "score": float(res.score),
                "duration_s": float(res.duration_s),
                "candidate": {k: (v if isinstance(v, (int, float, str, bool))
                                  else str(v))
                              for k, v in res.candidate.items()},
            })
        runner.listeners.append(listener)

    def upload_tsne(self, points, labels=None) -> None:
        """Publish a 2-D embedding (e.g. from ``plot.BarnesHutTsne``) to the
        t-SNE tab (reference UI's t-SNE visualization page)."""
        import numpy as np
        pts = np.asarray(points, dtype=float)
        if labels is not None:
            labels = [l.item() if hasattr(l, "item") else l for l in labels]
        self._tsne = {"points": pts[:, :2].tolist(), "labels": labels}

    def _records(self):
        recs = list(self._storage.records()) if self._storage else []
        return recs + self._remote_storage.records()

    def start(self, port: int = 9000) -> int:
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.startswith("/api/records"):
                    body = json.dumps(ui._records()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/api/arbiter"):
                    body = json.dumps(ui._arbiter_results).encode()
                    ctype = "application/json"
                elif self.path.startswith("/api/tsne"):
                    body = json.dumps(ui._tsne or {}).encode()
                    ctype = "application/json"
                else:
                    body = _PAGE.encode()
                    ctype = "text/html"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                code = 404
                if not ui._remote_enabled and (
                        self.path.startswith("/api/post")
                        or self.path.startswith("/api/arbiter")):
                    code = 403
                elif self.path.startswith("/api/post"):
                    try:
                        record = json.loads(raw.decode())
                        if not isinstance(record, dict):
                            raise ValueError("record must be an object")
                        ui._remote_storage.put_record(record)
                        code = 200
                    except Exception:
                        code = 400
                elif self.path.startswith("/api/arbiter"):
                    try:
                        r = json.loads(raw.decode())
                        # shape-validate so one bad record can't break the tab
                        entry = {"index": int(r["index"]),
                                 "score": float(r["score"]),
                                 "duration_s": float(r.get("duration_s", 0.0)),
                                 "candidate": dict(r.get("candidate", {}))}
                        ui._arbiter_results.append(entry)
                        code = 200
                    except Exception:
                        code = 400
                self.send_response(code)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="ui-stats-server")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd = None
